"""dslint engine + rule tests (tier-1, `lint` marker).

Three layers:

  * per-rule fixture pairs — every rule fires on its seeded violation file
    and stays quiet on the clean twin (and a rule without a pair fails
    ``test_every_rule_has_fixture_pair``)
  * engine mechanics — inline suppression parsing, baseline
    grandfather/stale round-trip, CLI exit codes and JSON output
  * self-enforcement — ``deepspeed_tpu/`` lints clean against the
    checked-in ``dslint_baseline.json``; a new unsuppressed finding
    anywhere in the package fails tier-1
"""

import json
import pathlib
import shutil
import subprocess
import sys

import pytest

from deepspeed_tpu.tools.dslint import (get_rules, lint_paths, load_baseline,
                                        write_baseline)
from deepspeed_tpu.tools.dslint.engine import LintEngine, parse_suppressions
from deepspeed_tpu.tools.dslint.hotpath import EscapeHatch, HotRoot
from deepspeed_tpu.tools.dslint.rules import ALL_RULES
from deepspeed_tpu.tools.dslint.rules.ds002_hot_sync import HotPathSyncRule
from deepspeed_tpu.tools.dslint.rules.ds009_offline_purity import \
    OfflinePurityRule

pytestmark = pytest.mark.lint

REPO = pathlib.Path(__file__).resolve().parent.parent
FIXTURES = pathlib.Path(__file__).resolve().parent / "dslint_fixtures"


def _lint(paths, **kw):
    return lint_paths([str(p) for p in paths], root=str(FIXTURES), **kw)


def _rules_of(result):
    return {f.rule for f in result.findings}


# ----------------------------------------------------------------------
# per-rule fixture pairs
# ----------------------------------------------------------------------
def _ds002_rules(name):
    """Taint-model DS002 pointed at the fixture: one hot root, a guarded
    hatch on ``record``, the designated drain as ``sync_ok``."""
    path = f"{name}.py"
    return [HotPathSyncRule(
        roots=(HotRoot(path=path, qualname="FakeEngine.train_batch",
                       reason="fixture root"),),
        hatches=(
            EscapeHatch(path=path, qualname="FakeEngine.record",
                        mode="guarded", guard_attr="_async_enabled",
                        reason="fixture guarded hatch"),
            EscapeHatch(path=path, qualname="FakeEngine.drain",
                        mode="sync_ok", reason="fixture drain"),
        ))]


def _ds009_rules(name):
    """Fixture-scoped offline/hot declarations for the purity rule."""
    return [OfflinePurityRule(
        offline=(f"{name}/offline_tool.py",),
        roots=(HotRoot(path=f"{name}/hot.py", qualname="Hot.step",
                       reason="fixture root"),),
        hatches=())]


@pytest.mark.parametrize("rule_id,min_findings", [
    ("DS001", 2), ("DS002", 3), ("DS003", 3), ("DS004", 2), ("DS005", 4),
    ("DS006", 2), ("DS007", 4), ("DS008", 3), ("DS009", 2),
])
def test_rule_fires_on_violation_and_not_on_clean(rule_id, min_findings):
    low = rule_id.lower()
    if rule_id in ("DS006", "DS007"):   # project-shaped fixtures (dirs:
        bad = [FIXTURES / f"{low}_violation"]    # config/constants.py or
        good = [FIXTURES / f"{low}_clean"]       # telemetry/names.py)
        kw_bad = kw_good = {"select": [rule_id]}
    elif rule_id == "DS002":        # registry-driven: point a root at the
        bad = [FIXTURES / f"{low}_violation.py"]     # fixture file
        good = [FIXTURES / f"{low}_clean.py"]
        kw_bad = {"rules": _ds002_rules(f"{low}_violation")}
        kw_good = {"rules": _ds002_rules(f"{low}_clean")}
    elif rule_id == "DS009":        # declaration-driven like DS002
        bad = [FIXTURES / f"{low}_violation"]
        good = [FIXTURES / f"{low}_clean"]
        kw_bad = {"rules": _ds009_rules(f"{low}_violation")}
        kw_good = {"rules": _ds009_rules(f"{low}_clean")}
    else:
        bad = [FIXTURES / f"{low}_violation.py"]
        good = [FIXTURES / f"{low}_clean.py"]
        kw_bad = kw_good = {"select": [rule_id]}

    fired = _lint(bad, **kw_bad)
    hits = [f for f in fired.findings if f.rule == rule_id]
    assert len(hits) >= min_findings, (
        f"{rule_id} fixture expected >= {min_findings} findings, got "
        f"{[f.render() for f in fired.findings]}")

    quiet = _lint(good, **kw_good)
    assert not [f for f in quiet.findings if f.rule == rule_id], (
        f"{rule_id} fired on its clean twin: "
        f"{[f.render() for f in quiet.findings]}")


def test_renaming_an_emitted_span_trips_ds007(tmp_path):
    """The exact drift DS007 exists for: rename the name at the emitter
    only (registry untouched) and the clean fixture starts firing."""
    work = tmp_path / "proj"
    shutil.copytree(FIXTURES / "ds007_clean", work)
    emit = work / "emit.py"
    emit.write_text(emit.read_text().replace(
        '"engine/train_step"', '"engine/training_step"'))
    res = lint_paths([str(work)], root=str(tmp_path), select=["DS007"])
    assert any(f.rule == "DS007" and "engine/training_step" in f.message
               for f in res.findings), \
        [f.render() for f in res.findings]


def test_every_rule_has_fixture_pair():
    """A new rule cannot land without a fires/doesn't-fire pair."""
    for cls in ALL_RULES:
        low = cls.id.lower()
        has_file_pair = ((FIXTURES / f"{low}_violation.py").exists()
                         and (FIXTURES / f"{low}_clean.py").exists())
        has_dir_pair = ((FIXTURES / f"{low}_violation").is_dir()
                        and (FIXTURES / f"{low}_clean").is_dir())
        assert has_file_pair or has_dir_pair, (
            f"rule {cls.id} has no fixture pair under tests/dslint_fixtures/")


def test_ds002_root_drift_is_a_finding(tmp_path):
    """Renaming a registered hot root without updating hotpath.py must
    fire, not silently retire the taint coverage."""
    f = tmp_path / "engine_like.py"
    f.write_text("class FakeEngine:\n    def renamed(self):\n        pass\n")
    root = HotRoot(path="engine_like.py",
                   qualname="FakeEngine.train_batch", reason="t")
    res = lint_paths([str(f)], root=str(tmp_path),
                     rules=[HotPathSyncRule(roots=(root,), hatches=())])
    assert any("hot-root drift" in f.message for f in res.findings)


def test_ds002_hatch_drift_is_a_finding(tmp_path):
    """An escape hatch pointing at a function that no longer exists is
    drift too — a stale hatch must not silently widen or narrow."""
    f = tmp_path / "engine_like.py"
    f.write_text(
        "class FakeEngine:\n"
        "    def train_batch(self, b):\n        return b\n")
    root = HotRoot(path="engine_like.py",
                   qualname="FakeEngine.train_batch", reason="t")
    hatch = EscapeHatch(path="engine_like.py",
                        qualname="FakeEngine.gone_drain",
                        mode="sync_ok", reason="t")
    res = lint_paths([str(f)], root=str(tmp_path),
                     rules=[HotPathSyncRule(roots=(root,),
                                            hatches=(hatch,))])
    assert any("escape-hatch drift" in f.message for f in res.findings)


# ----------------------------------------------------------------------
# engine mechanics
# ----------------------------------------------------------------------
def test_suppression_parsing_trailing_and_standalone():
    src = (
        "x = 1  # dslint: disable=DS003 -- trailing\n"
        "# dslint: disable=DS004, DS005 -- standalone,\n"
        "# continuation of the reason comment\n"
        "y = 2\n"
        "z = 3\n")
    sup = parse_suppressions(src)
    assert sup[1] == {"DS003"}
    assert sup[4] == {"DS004", "DS005"}      # binds past comment lines
    assert 5 not in sup


def test_inline_suppression_kills_finding(tmp_path):
    bad = (FIXTURES / "ds003_violation.py").read_text()
    unsup = tmp_path / "unsup.py"
    unsup.write_text(bad)
    res = lint_paths([str(unsup)], root=str(tmp_path), select=["DS003"])
    assert res.findings
    sup_text = bad.replace(
        "if np.all(mask > 0):",
        "if np.all(mask > 0):  # dslint: disable=DS003 -- fixture")
    sup = tmp_path / "sup.py"
    sup.write_text(sup_text)
    res2 = lint_paths([str(sup)], root=str(tmp_path), select=["DS003"])
    assert len(res2.findings) == len(res.findings) - 1
    assert len(res2.suppressed) == 1


def test_baseline_roundtrip_add_then_expire(tmp_path):
    """violation -> write-baseline -> clean run; fix -> stale entry
    surfaces -> re-write -> empty baseline."""
    work = tmp_path / "mod.py"
    shutil.copyfile(FIXTURES / "ds003_violation.py", work)
    bl = tmp_path / "dslint_baseline.json"

    first = lint_paths([str(work)], root=str(tmp_path), select=["DS003"])
    assert first.findings
    write_baseline(str(bl), first.findings)

    second = lint_paths([str(work)], baseline_path=str(bl),
                        root=str(tmp_path), select=["DS003"])
    assert not second.findings and len(second.baselined) == len(first.findings)
    assert not second.stale_baseline

    # a NEW violation at a different anchor is NOT shielded by the baseline
    work.write_text(work.read_text()
                    + "\n\ndef extra(y):\n    return bool(1) if y.any() "
                      "else False\n")
    third = lint_paths([str(work)], baseline_path=str(bl),
                       root=str(tmp_path), select=["DS003"])
    assert len(third.findings) == 1

    # fix everything -> every entry goes stale; --write-baseline expires it
    shutil.copyfile(FIXTURES / "ds003_clean.py", work)
    fourth = lint_paths([str(work)], baseline_path=str(bl),
                        root=str(tmp_path), select=["DS003"])
    assert not fourth.findings
    assert len(fourth.stale_baseline) == len(
        {f.key for f in first.findings})
    write_baseline(str(bl), fourth.findings)
    assert load_baseline(str(bl))["entries"] == []


def test_partial_runs_do_not_judge_uncovered_baseline_entries(tmp_path):
    """A single-file or --select run neither reports unrelated baseline
    entries as stale nor truncates them on --write-baseline."""
    a, b = tmp_path / "a.py", tmp_path / "b.py"
    shutil.copyfile(FIXTURES / "ds003_violation.py", a)
    shutil.copyfile(FIXTURES / "ds003_violation.py", b)
    bl = tmp_path / "dslint_baseline.json"
    full = lint_paths([str(a), str(b)], root=str(tmp_path), select=["DS003"])
    write_baseline(str(bl), full.findings)

    # a-only run: b's entries are not covered -> not stale, exit 0
    part = lint_paths([str(a)], baseline_path=str(bl), root=str(tmp_path),
                      select=["DS003"])
    assert not part.findings and not part.stale_baseline
    assert part.exit_code == 0

    # rule-subset run: DS003 not active -> its entries are not judged
    other = lint_paths([str(a), str(b)], baseline_path=str(bl),
                       root=str(tmp_path), select=["DS001"])
    assert not other.findings and not other.stale_baseline

    # merge-write over an a-only run (baseline-free lint, as the CLI does
    # for --write-baseline) rewrites a's entries and keeps b's verbatim
    part_nb = lint_paths([str(a)], root=str(tmp_path), select=["DS003"])
    write_baseline(str(bl), part_nb.findings, prior=load_baseline(str(bl)),
                   covered_paths=set(part_nb.linted_paths),
                   active_rules=set(part_nb.active_rules))
    kept = load_baseline(str(bl))["entries"]
    assert {e["path"] for e in kept} == {"a.py", "b.py"}
    assert len(kept) == len(full.findings)


def test_parse_error_is_a_finding_and_never_grandfathered(tmp_path):
    f = tmp_path / "broken.py"
    f.write_text("def f(:\n")
    res = lint_paths([str(f)], root=str(tmp_path))
    assert any(x.rule == "DS000" for x in res.findings)
    # an unparseable file is an UNLINTED file: --write-baseline must not
    # hide it — the entry list stays free of DS000
    bl = tmp_path / "bl.json"
    write_baseline(str(bl), res.findings)
    assert load_baseline(str(bl))["entries"] == []


def test_ds002_taint_follows_calls_not_file_membership(tmp_path):
    """The taint reaches a helper in ANOTHER class through a call edge,
    and does NOT flag an identical sync in a function nothing hot calls
    — coverage is the call graph, not file or class membership."""
    f = tmp_path / "engine_like.py"
    f.write_text(
        "import jax\n\n"
        "class FakeEngine:\n"
        "    def __init__(self):\n"
        "        self.h = Helper()\n"
        "    def train_batch(self, b):\n"
        "        return self.h.peek()\n\n"
        "class Helper:\n"
        "    def peek(self):\n"
        "        return jax.device_get(self.x)   # reached: fires\n"
        "    def cold_report(self):\n"
        "        return jax.device_get(self.x)   # unreached: quiet\n")
    root = HotRoot(path="engine_like.py",
                   qualname="FakeEngine.train_batch", reason="t")
    res = lint_paths([str(f)], root=str(tmp_path),
                     rules=[HotPathSyncRule(roots=(root,), hatches=())])
    assert len(res.findings) == 1, [x.render() for x in res.findings]
    assert "peek" in res.findings[0].anchor


def test_suppression_reaches_multiline_statement_continuation(tmp_path):
    """A standalone disable before a multi-line statement suppresses a
    finding anchored on a continuation line of that statement."""
    f = tmp_path / "m.py"
    f.write_text(
        "import jax\n\n"
        "def g(state, batch, ring):\n"
        "    step = jax.jit(lambda s, b: s, donate_argnums=(0,))\n"
        "    out = step(state, batch)\n"
        "    # dslint: disable=DS001 -- snapshot is provably pre-dispatch\n"
        "    ring.append({\n"
        "        'scale': state.loss_scale,\n"
        "    })\n"
        "    return out\n")
    res = lint_paths([str(f)], root=str(tmp_path), select=["DS001"])
    assert not res.findings and len(res.suppressed) == 1


def _guarded_record_rule():
    """A root that is ALSO its own guarded hatch (the FaultTolerantRunner
    shape): the async side of the guard stays sync-free, the fallback
    side is the designed sync path."""
    return HotPathSyncRule(
        roots=(HotRoot(path="engine_like.py",
                       qualname="FakeEngine.record", reason="t"),),
        hatches=(EscapeHatch(path="engine_like.py",
                             qualname="FakeEngine.record", mode="guarded",
                             guard_attr="_async_enabled", reason="t"),))


def test_ds002_early_return_guard_still_scans_the_async_tail(tmp_path):
    """Refactoring the guard to early-return form must not retire the
    tripwire: the tail after `if not <guard>: ...; return` IS the async
    push path and stays sync-free."""
    f = tmp_path / "engine_like.py"
    f.write_text(
        "import jax\n\n"
        "class FakeEngine:\n"
        "    def record(self, out):\n"
        "        if not self._async_enabled:\n"
        "            self.last = float(out)    # sync fallback: allowed\n"
        "            return\n"
        "        self.ring.append(jax.device_get(out))  # async tail: fires\n")
    res = lint_paths([str(f)], root=str(tmp_path),
                     rules=[_guarded_record_rule()])
    assert len(res.findings) == 1
    assert ".device_get" in res.findings[0].message


def test_ds004_acquire_only_protects_the_acquired_span(tmp_path):
    """An unrelated .acquire() later in a method must not silence an
    unprotected thread-shared write before it."""
    f = tmp_path / "w.py"
    f.write_text(
        "import threading\n\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._shared = None\n"
        "        self._t = threading.Thread(target=self._loop)\n"
        "    def _loop(self):\n"
        "        x = self._shared\n"
        "    def poke(self):\n"
        "        self._shared = 1          # BEFORE the acquire: unprotected\n"
        "        self._sem.acquire()\n"
        "        self._sem.release()\n")
    res = lint_paths([str(f)], root=str(tmp_path), select=["DS004"])
    assert len(res.findings) == 1 and "_shared" in res.findings[0].message


def test_ds002_inverted_guard_checks_the_async_side(tmp_path):
    """`if not <guard>: <sync fallback>` must not flag the fallback — the
    async side (the else branch) is what stays sync-free."""
    f = tmp_path / "engine_like.py"
    f.write_text(
        "import jax\n\n"
        "class FakeEngine:\n"
        "    def record(self, out):\n"
        "        if not self._async_enabled:\n"
        "            return float(out)      # sync fallback: allowed\n"
        "        else:\n"
        "            self.ring.append(jax.device_get(out))  # async: fires\n")
    res = lint_paths([str(f)], root=str(tmp_path),
                     rules=[_guarded_record_rule()])
    assert len(res.findings) == 1
    assert ".device_get" in res.findings[0].message


def test_cli_changed_mode_lints_changed_files_plus_reverse_deps(
        tmp_path, capsys, monkeypatch):
    """--changed lints exactly the git-diff subset plus files whose call/
    import edges reach it — a seeded violation in the edited file fires,
    and the caller file rides along as a reverse dep."""
    from deepspeed_tpu.tools.dslint import cli

    def git(*args):
        subprocess.run(["git", "-c", "user.email=t@t", "-c", "user.name=t",
                        *args], cwd=tmp_path, check=True,
                       capture_output=True)

    (tmp_path / "lib.py").write_text("def ok(x):\n    return x\n")
    (tmp_path / "app.py").write_text(
        "import lib\n\ndef run(x):\n    return lib.ok(x)\n")
    (tmp_path / "lone.py").write_text("def solo():\n    return 1\n")
    git("init", "-q")
    git("add", ".")
    git("commit", "-q", "-m", "seed")
    # edit lib.py: the DS003 shape (array truthiness in an assert)
    (tmp_path / "lib.py").write_text(
        "import numpy as np\n\n"
        "def ok(x):\n"
        "    assert np.isfinite(x)\n"
        "    return x\n")
    monkeypatch.chdir(tmp_path)
    rc = cli.main(["--changed", "HEAD", "--baseline", "none"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "1 changed file(s) + 1 reverse dep(s)" in out   # app, not lone
    assert "lib.py" in out and "DS003" in out


def test_cli_exit_codes_and_json(tmp_path):
    cli = str(REPO / "bin" / "dslint")
    bad = subprocess.run(
        [sys.executable, cli, "--baseline", "none", "--select", "DS003",
         str(FIXTURES / "ds003_violation.py")],
        capture_output=True, text=True)
    assert bad.returncode == 1, bad.stderr
    good = subprocess.run(
        [sys.executable, cli, "--baseline", "none", "--select", "DS003",
         "--format", "json", str(FIXTURES / "ds003_clean.py")],
        capture_output=True, text=True)
    assert good.returncode == 0, good.stderr
    payload = json.loads(good.stdout)
    assert payload["findings"] == [] and payload["files_checked"] == 1


# ----------------------------------------------------------------------
# self-enforcement: the whole package lints clean vs the checked-in baseline
# ----------------------------------------------------------------------
def test_self_lint_package_clean_vs_baseline():
    baseline = REPO / "dslint_baseline.json"
    assert baseline.exists(), "checked-in dslint_baseline.json is missing"
    res = lint_paths([str(REPO / "deepspeed_tpu")],
                     baseline_path=str(baseline))
    assert not res.findings, (
        "dslint found new unsuppressed findings in deepspeed_tpu/ — fix "
        "them, add an inline `# dslint: disable=RULE -- reason`, or (for a "
        "deliberate grandfather) regenerate dslint_baseline.json:\n  "
        + "\n  ".join(f.render() for f in res.findings))
    assert not res.stale_baseline, (
        "stale dslint baseline entries (the violation was fixed — expire "
        "them with `bin/dslint --write-baseline deepspeed_tpu/`):\n  "
        + "\n  ".join(str(e) for e in res.stale_baseline))


def test_rule_count_matches_catalog():
    assert len(get_rules()) >= 9
    engine = LintEngine(get_rules())
    assert len(engine.rules) == len(ALL_RULES)


def test_suppression_binds_through_decorator_stacks(tmp_path):
    """A standalone disable above a decorator stack lexically binds to the
    FIRST decorator line — it must still reach a finding anchored on a
    LATER decorator of the same (async) def, which previously slipped
    through because decorators are not simple statements."""
    f = tmp_path / "engine_like.py"
    f.write_text(
        "import jax\n\n"
        "def deco(fn=None, **kw):\n"
        "    return fn if fn is not None else deco\n\n"
        "class FakeEngine:\n"
        "    # dslint: disable=DS002 -- fixture: host scale, not an array\n"
        "    @deco\n"
        "    @deco(scale=float(3))\n"
        "    async def train_batch(self, b):\n"
        "        return b\n")
    root = HotRoot(path="engine_like.py",
                   qualname="FakeEngine.train_batch", reason="t")
    res = lint_paths([str(f)], root=str(tmp_path),
                     rules=[HotPathSyncRule(roots=(root,), hatches=())])
    assert not res.findings, [x.render() for x in res.findings]
    assert res.suppressed

    # without the comment the decorator-line sink IS a finding (the
    # suppression path above is exercised, not vacuous)
    f.write_text(f.read_text().replace(
        "    # dslint: disable=DS002 -- fixture: host scale, not an "
        "array\n", ""))
    res2 = lint_paths([str(f)], root=str(tmp_path),
                      rules=[HotPathSyncRule(roots=(root,), hatches=())])
    assert len(res2.findings) == 1
