"""Universal checkpoint tool tests: inspect, fp32 consolidation, per-param
extraction, CLI.

Reference analog: tests/unit/checkpoint/test_universal_checkpoint.py +
zero_to_fp32 usage.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint.universal import (
    consolidate_to_fp32, extract_param, inspect_checkpoint, load_fp32_state,
    resolve_checkpoint_dir)
from deepspeed_tpu.models.simple import SimpleModel, random_batch


@pytest.fixture(scope="module")
def saved_ckpt(tmp_path_factory):
    d = tmp_path_factory.mktemp("ckpt")
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 3},
        "bf16": {"enabled": True},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=config,
        example_batch=random_batch(4))
    for i in range(2):
        engine.train_batch(batch=random_batch(8, seed=i))
    engine.save_checkpoint(str(d), tag="step2")
    return str(d), engine


def test_resolve_by_tag_and_latest(saved_ckpt):
    d, _ = saved_ckpt
    by_tag = resolve_checkpoint_dir(d, tag="step2")
    by_latest = resolve_checkpoint_dir(d)
    assert by_tag == by_latest and by_tag.endswith("step2")
    with pytest.raises(FileNotFoundError):
        resolve_checkpoint_dir("/nonexistent/dir")


def test_inspect_lists_all_params(saved_ckpt):
    d, engine = saved_ckpt
    info = inspect_checkpoint(d)
    n_leaves = len(jax.tree.leaves(engine.state.params))
    assert len(info["parameters"]) == n_leaves
    assert info["meta"]["global_steps"] == 2
    total = sum(int(np.prod(p.size)) for p in jax.tree.leaves(engine.state.params))
    assert info["num_params"] == total


def test_consolidate_fp32_roundtrip(saved_ckpt, tmp_path):
    d, engine = saved_ckpt
    out = consolidate_to_fp32(d, str(tmp_path / "fp32_model"))
    state = load_fp32_state(out)
    live = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(
        jax.device_get(engine.state.params))
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        live[name] = np.asarray(leaf, np.float32)
    assert set(state) == set(live)
    for k in live:
        assert state[k].dtype == np.float32
        np.testing.assert_allclose(state[k], live[k], rtol=1e-6)


def test_consolidate_with_optimizer(saved_ckpt, tmp_path):
    d, _ = saved_ckpt
    out = consolidate_to_fp32(d, str(tmp_path / "full"), include_optimizer=True)
    data = np.load(out)
    assert any(k.startswith("opt_state/") for k in data.files)


def test_extract_param(saved_ckpt):
    d, engine = saved_ckpt
    info = inspect_checkpoint(d)
    name = next(iter(info["parameters"]))
    arr = extract_param(d, name)
    assert list(arr.shape) == info["parameters"][name]["shape"]
    with pytest.raises(KeyError):
        extract_param(d, "definitely/not/a/param")


@pytest.mark.slow
def test_cli_inspect_and_consolidate(saved_ckpt, tmp_path):
    d, _ = saved_ckpt
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    r = subprocess.run([sys.executable, "-m", "deepspeed_tpu.checkpoint.universal",
                        "inspect", d], capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    info = json.loads(r.stdout)
    assert info["num_params"] > 0
    out = str(tmp_path / "cli_fp32")
    r2 = subprocess.run([sys.executable, "-m", "deepspeed_tpu.checkpoint.universal",
                         "consolidate", d, out], capture_output=True, text=True, env=env)
    assert r2.returncode == 0, r2.stderr
    assert os.path.exists(out + ".npz")
