"""Universal checkpoint tool tests: inspect, fp32 consolidation, per-param
extraction, CLI.

Reference analog: tests/unit/checkpoint/test_universal_checkpoint.py +
zero_to_fp32 usage.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

import deepspeed_tpu
import jax.numpy as jnp
from deepspeed_tpu.comm.mesh import create_mesh, set_global_mesh
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.checkpoint.universal import (
    consolidate_to_fp32, extract_param, inspect_checkpoint, load_fp32_state,
    resolve_checkpoint_dir)
from deepspeed_tpu.models.simple import SimpleModel, random_batch


@pytest.fixture(scope="module")
def saved_ckpt(tmp_path_factory):
    d = tmp_path_factory.mktemp("ckpt")
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 3},
        "bf16": {"enabled": True},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=config,
        example_batch=random_batch(4))
    for i in range(2):
        engine.train_batch(batch=random_batch(8, seed=i))
    engine.save_checkpoint(str(d), tag="step2")
    return str(d), engine


def test_resolve_by_tag_and_latest(saved_ckpt):
    d, _ = saved_ckpt
    by_tag = resolve_checkpoint_dir(d, tag="step2")
    by_latest = resolve_checkpoint_dir(d)
    assert by_tag == by_latest and by_tag.endswith("step2")
    with pytest.raises(FileNotFoundError):
        resolve_checkpoint_dir("/nonexistent/dir")


def test_inspect_lists_all_params(saved_ckpt):
    d, engine = saved_ckpt
    info = inspect_checkpoint(d)
    n_leaves = len(jax.tree.leaves(engine.state.params))
    assert len(info["parameters"]) == n_leaves
    assert info["meta"]["global_steps"] == 2
    total = sum(int(np.prod(p.size)) for p in jax.tree.leaves(engine.state.params))
    assert info["num_params"] == total


def test_consolidate_fp32_roundtrip(saved_ckpt, tmp_path):
    d, engine = saved_ckpt
    out = consolidate_to_fp32(d, str(tmp_path / "fp32_model"))
    state = load_fp32_state(out)
    live = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(
        jax.device_get(engine.state.params))
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        live[name] = np.asarray(leaf, np.float32)
    assert set(state) == set(live)
    for k in live:
        assert state[k].dtype == np.float32
        np.testing.assert_allclose(state[k], live[k], rtol=1e-6)


def test_consolidate_with_optimizer(saved_ckpt, tmp_path):
    d, _ = saved_ckpt
    out = consolidate_to_fp32(d, str(tmp_path / "full"), include_optimizer=True)
    data = np.load(out)
    assert any(k.startswith("opt_state/") for k in data.files)


def test_extract_param(saved_ckpt):
    d, engine = saved_ckpt
    info = inspect_checkpoint(d)
    name = next(iter(info["parameters"]))
    arr = extract_param(d, name)
    assert list(arr.shape) == info["parameters"][name]["shape"]
    with pytest.raises(KeyError):
        extract_param(d, "definitely/not/a/param")


@pytest.mark.slow
def test_cli_inspect_and_consolidate(saved_ckpt, tmp_path):
    d, _ = saved_ckpt
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    r = subprocess.run([sys.executable, "-m", "deepspeed_tpu.checkpoint.universal",
                        "inspect", d], capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    info = json.loads(r.stdout)
    assert info["num_params"] > 0
    out = str(tmp_path / "cli_fp32")
    r2 = subprocess.run([sys.executable, "-m", "deepspeed_tpu.checkpoint.universal",
                         "consolidate", d, out], capture_output=True, text=True, env=env)
    assert r2.returncode == 0, r2.stderr
    assert os.path.exists(out + ".npz")


@pytest.mark.slow
def test_pipeline_checkpoint_inspect_extract_consolidate(tmp_path):
    """The universal tooling understands PipelineEngine's staged/tied layout
    end to end: inspect counts every param, extract fetches a leaf, and
    consolidate writes a non-empty fp32 npz — via the save_dir AND the
    tagged dir itself (bare orbax markers, no ds_meta.json)."""
    import deepspeed_tpu
    from deepspeed_tpu.checkpoint.universal import (consolidate_to_fp32,
                                                    extract_param,
                                                    inspect_checkpoint)
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.runtime.pipe.module import llama_pipe_module

    cfg = LlamaConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                      num_layers=4, num_heads=2, num_kv_heads=2,
                      max_seq_len=32, scan_layers=True, dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    tokens = np.random.default_rng(0).integers(
        0, 128, size=(8, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": jnp.asarray(tokens)})
    mesh = create_mesh(MeshConfig(pipe=4, data=2))
    set_global_mesh(mesh)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=llama_pipe_module(cfg, params), mesh=mesh,
        config={"gradient_accumulation_steps": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
    tagged = eng.save_checkpoint(str(tmp_path))

    n_model = sum(np.asarray(x).size for x in jax.tree.leaves(params))
    for addr in (str(tmp_path), tagged):
        info = inspect_checkpoint(addr)
        assert info["num_params"] == n_model, addr
    name = next(k for k in info["parameters"] if k.startswith("tied/"))
    leaf = extract_param(str(tmp_path), name)
    assert leaf.size > 0
    out = consolidate_to_fp32(str(tmp_path), str(tmp_path / "fp32"))
    data = np.load(out)
    assert sum(data[k].size for k in data.files) == n_model
