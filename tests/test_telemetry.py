"""dstrace telemetry tests (tracer core + cross-subsystem instrumentation).

Contracts pinned here:

  round-trip   : spans/instants -> valid Chrome-trace JSON (Perfetto object
                 format), nesting by ts/dur containment, step correlation
                 keys, monotonic ids, bounded ring with exact drop count
  train        : sync and async modes emit the SAME per-step dispatch spans;
                 async additionally emits drain + reconciled-window spans
                 whose step counts tie out
  serving      : request lifecycle spans alone reproduce TTFT exactly as
                 the serving metrics measured it
  resilience   : signal path stays DS005-clean and emits an append-only
                 breadcrumb (no sink fan-out from handler context);
                 quarantine bundles embed a Perfetto-loadable trace tail
  end-to-end   : a chaos run under tracing produces dispatch/drain/prefetch/
                 checkpoint/comm spans and resilience instants in ONE trace
                 (the PR's acceptance shape)
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.simple import SimpleModel, random_batch
from deepspeed_tpu.telemetry import get_tracer, request_tid
from deepspeed_tpu.telemetry.tracer import Tracer

pytestmark = pytest.mark.telemetry

CFG = {
    "train_batch_size": 8,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
}


@pytest.fixture
def tracing():
    """Enable the process tracer for one test, fully restored afterwards
    (other suites rely on the disabled no-op fast path)."""
    t = get_tracer()
    t.clear()
    t.detach_sink()
    t.configure(enabled=True)
    try:
        yield t
    finally:
        t.configure(enabled=False)
        t.detach_sink()
        t.clear()


def _engine(seed=1, extra=None):
    cfg = dict(CFG)
    if extra:
        cfg.update(extra)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=32), config=cfg,
        example_batch=random_batch(4), seed=seed)
    return engine


def _spans(trace, name=None):
    out = [e for e in trace["traceEvents"]
           if e.get("ph") == "X" and (name is None or e["name"] == name)]
    return out


def _instants(trace, name=None):
    return [e for e in trace["traceEvents"]
            if e.get("ph") == "i" and (name is None or e["name"] == name)]


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------
def test_trace_round_trip_valid_chrome_json(tmp_path, tracing):
    with tracing.span("outer", cat="t", step=3):
        with tracing.span("inner", cat="t", step=3):
            time.sleep(0.002)
    tracing.instant("marker", step=3, detail="x")
    path = str(tmp_path / "trace.json")
    tracing.export_chrome(path)
    trace = json.loads(open(path).read())     # round-trips as strict JSON
    assert isinstance(trace["traceEvents"], list)
    assert trace["displayTimeUnit"] == "ms"
    outer, = _spans(trace, "outer")
    inner, = _spans(trace, "inner")
    # nesting: same thread track, inner contained within outer's ts window
    assert inner["tid"] == outer["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    # step correlation + monotonic ids
    assert outer["args"]["step"] == 3 and inner["args"]["step"] == 3
    marker, = _instants(trace, "marker")
    assert marker["args"]["step"] == 3 and marker["s"] == "t"
    assert inner["args"]["id"] < outer["args"]["id"] < marker["args"]["id"]
    # thread metadata present so Perfetto labels the track
    assert any(e.get("ph") == "M" and e["name"] == "thread_name"
               for e in trace["traceEvents"])


def test_ring_bounded_with_exact_drop_count():
    t = Tracer(capacity=32)
    t.enabled = True
    for i in range(100):
        t.instant(f"e{i}")
    snap = t.events_snapshot()
    assert len(snap) == 32
    assert t.dropped() == 68
    assert snap[-1][1] == "e99"           # newest survives
    # clear() discards, it does not evict: drop count survives unchanged
    # and cleared events never masquerade as ring pressure
    t.clear()
    for i in range(5):
        t.instant(f"post{i}")
    assert len(t.events_snapshot()) == 5
    assert t.dropped() == 68
    # resizing the ring keeps every retained event
    t.configure(capacity=64)
    assert len(t.events_snapshot()) == 5


def test_disabled_tracer_is_noop():
    t = Tracer()
    s1, s2 = t.span("a"), t.span("b", step=1)
    assert s1 is s2                       # shared no-op context, no allocs
    with s1:
        pass
    t.instant("x", step=1)
    t.complete("y", 0.5)
    assert t.events_snapshot() == []


def test_tail_slice_and_summary(tracing):
    tracing.complete("old", 0.001, end_ts=time.monotonic() - 120.0)
    tracing.complete("fresh", 0.002)
    tail = tracing.tail(60.0)
    assert [e[1] for e in tail] == ["fresh"]
    summ = tracing.summary()
    assert summ["fresh"]["count"] == 1
    assert summ["fresh"]["total_s"] == pytest.approx(0.002)
    assert set(summ) == {"old", "fresh"}


def test_dstpu_trace_env_activation(tmp_path):
    """DSTPU_TRACE=path turns tracing on at first use and dumps at exit."""
    out = str(tmp_path / "env_trace.json")
    code = (
        "from deepspeed_tpu.telemetry import get_tracer\n"
        "t = get_tracer()\n"
        "assert t.enabled\n"
        "with t.span('probe', step=1):\n"
        "    pass\n")
    env = dict(os.environ, DSTPU_TRACE=out)
    subprocess.run([sys.executable, "-c", code], check=True, env=env,
                   cwd=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
    trace = json.load(open(out))
    assert _spans(trace, "probe")


def test_report_cli_renders_top_spans(tmp_path, tracing, capsys):
    with tracing.span("engine/dispatch", cat="train", step=0):
        time.sleep(0.001)
    tracing.instant("chaos/nan", step=0)
    path = str(tmp_path / "t.json")
    tracing.export_chrome(path)
    from deepspeed_tpu.telemetry.report import main as report_main
    assert report_main([path]) == 0
    text = capsys.readouterr().out
    assert "engine/dispatch" in text and "chaos/nan" in text
    assert report_main([path, "--json"]) == 0
    agg = json.loads(capsys.readouterr().out)
    assert agg["spans"][0]["name"] == "engine/dispatch"
    assert agg["instants"]["chaos/nan"] == 1
    assert report_main([str(tmp_path / "missing.json")]) == 2


# ---------------------------------------------------------------------------
# comms logging satellites
# ---------------------------------------------------------------------------
def test_calc_bw_degenerate_guards():
    from deepspeed_tpu.comm.comms_logging import calc_bw
    # zero/negative duration and negative size never produce inf/garbage
    assert calc_bw("all_reduce", 1 << 20, 0.0, 8) == (0.0, 0.0)
    assert calc_bw("all_reduce", 1 << 20, -1.0, 8) == (0.0, 0.0)
    assert calc_bw("all_reduce", -5, 1.0, 8) == (0.0, 0.0)
    # world==1: busbw == algbw, not ring-factor zero
    alg, bus = calc_bw("all_reduce", 1 << 20, 1.0, 1)
    assert alg == bus == float(1 << 20)
    alg, bus = calc_bw("all_gather", 1 << 20, 1.0, 1)
    assert bus == alg
    # the ring factors still apply for world > 1
    alg, bus = calc_bw("all_reduce", 1 << 20, 1.0, 4)
    assert bus == pytest.approx(alg * 1.5)


def test_comms_per_op_totals_and_env_rows(tracing):
    from deepspeed_tpu.comm.comms_logging import CommsLogger
    cl = CommsLogger()
    cl.configure(enabled=True)
    cl.record_traced("all_reduce", 1000, 4)
    cl.record_traced("all_reduce", 500, 4)
    with cl.timed("broadcast", 2000, 2):
        time.sleep(0.001)
    totals = cl.per_op_totals()
    assert totals["all_reduce"] == {"count": 2, "bytes": 1500.0,
                                    "wire_bytes": 1500.0, "seconds": 0.0}
    assert totals["broadcast"]["count"] == 1
    assert totals["broadcast"]["seconds"] > 0
    rows = dict(cl.env_report_rows())
    assert "comms[all_reduce]" in rows and "comms[broadcast]" in rows
    # traced ops emit comm instants; timed ops emit comm spans with bw args
    counts = tracing.instant_counts(prefix="comm/")
    assert counts["comm/all_reduce"] == 2
    span = [e for e in tracing.events_snapshot()
            if e[1] == "comm/broadcast" and e[3] == "X"]
    assert span and span[0][7]["bytes"] == 2000
    assert "busbw_gbps" in span[0][7]
    # env_report surface never dies and includes the comms section
    from deepspeed_tpu.env_report import comms_report, trace_report
    assert comms_report()
    assert any("dstrace" in k for k, _ in trace_report())


# ---------------------------------------------------------------------------
# monitor events sink
# ---------------------------------------------------------------------------
def _csv_master(tmp_path):
    from deepspeed_tpu.config.config import (CometConfig, CSVConfig,
                                             TensorBoardConfig, WandbConfig)
    from deepspeed_tpu.monitor.monitor import MonitorMaster
    cfg = types.SimpleNamespace(
        csv_monitor=CSVConfig(enabled=True, output_path=str(tmp_path),
                              job_name="events"),
        tensorboard=TensorBoardConfig(enabled=False),
        wandb=WandbConfig(enabled=False),
        comet=CometConfig(enabled=False))
    return MonitorMaster(cfg)


def test_monitor_events_sink_receives_instants(tmp_path, tracing):
    mon = _csv_master(tmp_path)
    assert mon.enabled
    tracing.attach_sink(mon.write_instant)
    tracing.instant("chaos/nan", step=5)             # fans out
    tracing.instant("resilience/quiet", step=6, fanout=False)  # must not
    tracing.instant("no_step_marker")                # no step -> no fan-out
    written = {p.stem for p in (tmp_path / "events").glob("*.csv")}
    assert "Events_chaos_nan" in written
    assert "Events_resilience_quiet" not in written
    rows = open(tmp_path / "events" / "Events_chaos_nan.csv").read()
    assert "5,1.0" in rows


# ---------------------------------------------------------------------------
# nvtx routing
# ---------------------------------------------------------------------------
def test_nvtx_routes_through_tracer(tracing):
    from deepspeed_tpu.utils import nvtx

    @nvtx.instrument(name="scaled")
    def f(x):
        return x * 2

    assert f(3) == 6
    with nvtx.annotate("outer_range"):
        pass
    ctx = nvtx.range_push("pushed")
    nvtx.range_pop(ctx)
    names = {e[1] for e in tracing.events_snapshot()}
    assert {"scaled", "outer_range", "pushed"} <= names


def test_nvtx_noop_when_tracing_off():
    from deepspeed_tpu.utils import nvtx
    t = get_tracer()
    assert not t.enabled
    before = len(t.events_snapshot())
    with nvtx.annotate("quiet"):
        pass

    @nvtx.instrument
    def g():
        return 1

    assert g() == 1
    assert len(t.events_snapshot()) == before


# ---------------------------------------------------------------------------
# engine: sync vs async span parity
# ---------------------------------------------------------------------------
def _batches(n, bs=8):
    return iter([random_batch(bs, seed=i) for i in range(n)])


def test_sync_vs_async_dispatch_drain_span_parity(tracing):
    steps = 8
    engine = _engine(seed=1)
    it = _batches(steps)
    for _ in range(steps):
        engine.train_batch(data_iter=it)
    sync_events = tracing.events_snapshot()
    sync_dispatch = [e for e in sync_events if e[1] == "engine/dispatch"]
    assert len(sync_dispatch) == steps
    assert all(e[7]["mode"] == "sync" for e in sync_dispatch)
    assert not [e for e in sync_events if e[1] == "engine/drain"]
    # step correlation: one dispatch per engine step, in order
    assert [e[7]["step"] for e in sync_dispatch] == list(range(steps))

    tracing.clear()
    engine = _engine(seed=1, extra={
        "async_pipeline": {"enabled": True, "sync_every": 4}})
    it = _batches(steps)
    for _ in range(steps):
        engine.train_batch(data_iter=it)
    engine.flush_metrics()
    async_events = tracing.events_snapshot()
    async_dispatch = [e for e in async_events if e[1] == "engine/dispatch"]
    # PARITY: async mode emits the same per-step dispatch spans...
    assert len(async_dispatch) == steps
    assert [e[7]["step"] for e in async_dispatch] == list(range(steps))
    assert all(e[7]["mode"] == "async" for e in async_dispatch)
    # ...plus drains whose per-drain step counts tie out to every step
    drains = [e for e in async_events if e[1] == "engine/drain"]
    assert len(drains) == steps // 4
    assert sum(e[7]["steps"] for e in drains) == steps
    reconciled = [e for e in async_events
                  if e[1] == "engine/steps_reconciled"]
    assert sum(e[7]["steps"] for e in reconciled) == steps
    # the reconciled windows cover real wall time (dispatch-gap vs step time)
    assert all(e[5] > 0 for e in reconciled)


def test_dump_trace_and_summary_from_engine(tmp_path, tracing):
    engine = _engine(seed=3)
    it = _batches(2)
    for _ in range(2):
        engine.train_batch(data_iter=it)
    path = str(tmp_path / "engine_trace.json")
    trace = engine.dump_trace(path)
    assert os.path.exists(path)
    assert _spans(trace, "engine/dispatch")
    assert _spans(trace, "comm/h2d")
    summ = engine.trace_summary(prefix="engine/")
    assert summ["engine/dispatch"]["count"] == 2


# ---------------------------------------------------------------------------
# serving: TTFT derivable from the trace alone
# ---------------------------------------------------------------------------
class _OneTokenPerStepEngine:
    """Engine double: every resident sequence yields one token per step."""

    def __init__(self):
        self.state = types.SimpleNamespace(max_context_length=512,
                                           get=lambda uid: None)
        self.kv = types.SimpleNamespace(blocks_needed=lambda total: 1)
        self._resident = set()
        self._finished = []

    def kv_usable_blocks(self):
        return 64

    def kv_occupancy(self):
        return 0.0

    def can_schedule(self, uids, needs):
        return True

    def admit(self, uid, tokens):
        self._resident.add(uid)

    def has_work(self):
        return bool(self._resident)

    def step(self):
        return {uid: 7 for uid in sorted(self._resident)}

    def finish(self, uid):
        self._resident.discard(uid)
        self._finished.append(uid)

    def reap_finished(self):
        gone, self._finished = self._finished, []
        return gone


def test_serving_request_spans_reproduce_ttft(tracing):
    from deepspeed_tpu.serving import InferenceServer, ServingConfig
    server = InferenceServer(_OneTokenPerStepEngine(),
                             ServingConfig(idle_poll_s=0.001)).start()
    try:
        req = server.submit([1, 2, 3], max_new_tokens=4)
        toks = req.result(timeout=30.0)
        assert len(toks) == 4
    finally:
        server.stop(drain_timeout=5.0)
    trace = tracing.to_chrome()
    tid = request_tid(req.uid)
    queued, = [e for e in _spans(trace, "serve/queued")
               if e["tid"] == tid]
    prefill, = [e for e in _spans(trace, "serve/prefill")
                if e["tid"] == tid]
    decode, = [e for e in _spans(trace, "serve/decode")
               if e["tid"] == tid]
    # TTFT from the trace alone == the metric the server recorded
    ttft_trace = (queued["dur"] + prefill["dur"]) / 1e6
    assert ttft_trace == pytest.approx(req.ttft_s, rel=1e-6, abs=1e-6)
    # TPOT derivable too: decode span / (tokens - 1)
    assert decode["args"]["tokens"] == 4
    tpot_trace = decode["dur"] / 1e6 / 3
    assert tpot_trace == pytest.approx(req.tpot_s, rel=1e-6, abs=1e-6)
    # terminal instant on the same per-request track
    finished = [e for e in _instants(trace, "serve/finished")
                if e["tid"] == tid]
    assert finished and finished[0]["args"]["uid"] == req.uid
    # /metrics grows tracer-sourced span summaries
    prom = server.metrics.prometheus_text()
    assert 'dstpu_trace_span_seconds{span="serve/decode"' in prom
    assert 'dstpu_trace_span_seconds_count{span="serve/queued"} 1' in prom


# ---------------------------------------------------------------------------
# resilience: signal-path safety + bundle trace tail
# ---------------------------------------------------------------------------
@pytest.mark.lint
def test_signal_path_stays_ds005_clean():
    """The instrumented SIGTERM handler (tracer breadcrumb included) must
    carry no new non-reentrant work — DS005 over the runner file must only
    show the two recorded inline suppressions, no findings."""
    from deepspeed_tpu.tools.dslint import lint_paths
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    result = lint_paths(
        [os.path.join(root, "deepspeed_tpu/resilience/runner.py")],
        root=root, select=["DS005"])
    assert not result.findings, [str(f) for f in result.findings]


def test_signal_breadcrumb_is_append_only(tmp_path, tracing):
    """The handler's instant skips the monitor sink (fanout=False): no I/O
    can happen in handler context even with a sink attached."""
    from deepspeed_tpu.resilience import FaultTolerantRunner
    engine = _engine(seed=2)
    sink_calls = []
    tracing.attach_sink(lambda name, step: sink_calls.append(name))
    runner = FaultTolerantRunner(engine, save_dir=str(tmp_path / "ckpt"))
    try:
        runner._on_signal(signal.SIGTERM, None)
        assert runner.preempted
        crumbs = [e for e in tracing.events_snapshot()
                  if e[1] == "resilience/preempt_signal"]
        assert crumbs and crumbs[0][7]["signum"] == signal.SIGTERM
        assert sink_calls == []           # append-only: sink untouched
    finally:
        runner.close()


@pytest.mark.chaos
def test_quarantine_bundle_embeds_trace_tail(tmp_path, tracing):
    from deepspeed_tpu.resilience import (ChaosConfig, ChaosMonkey,
                                          FaultTolerantRunner,
                                          QuarantineError, ResilienceConfig)
    engine = _engine(seed=5)
    rc = ResilienceConfig(
        step_guard={"backoff_after": 0, "quarantine_after": 2},
        diagnostics_dir=str(tmp_path / "diag"))
    chaos = ChaosMonkey(ChaosConfig(seed=1, nan_prob=1.0))
    runner = FaultTolerantRunner(engine, save_dir=str(tmp_path / "ckpt"),
                                 config=rc, chaos=chaos,
                                 install_signal_handlers=False)
    try:
        with pytest.raises(QuarantineError) as ei:
            runner.run(num_steps=5,
                       batch_fn=lambda step: random_batch(8, seed=step))
        bundle = ei.value.bundle_path
        tail_path = os.path.join(bundle, "trace_tail.json")
        assert os.path.exists(tail_path)
        tail = json.load(open(tail_path))
        names = {e["name"] for e in tail["traceEvents"]}
        # the slice holds the story: chaos injections, guard trips, the
        # dispatches that carried them, and the final quarantine marker
        assert "chaos/nan" in names
        assert "resilience/bad_step" in names
        assert "resilience/quarantine" in names
        assert "engine/dispatch" in names
    finally:
        runner.close()


# ---------------------------------------------------------------------------
# end-to-end: one trace, every subsystem (the acceptance shape)
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_end_to_end_chaos_trace_has_all_span_families(tmp_path, tracing):
    from deepspeed_tpu.resilience import (ChaosConfig, ChaosMonkey,
                                          FaultTolerantRunner,
                                          ResilienceConfig)
    engine = _engine(seed=7, extra={
        "async_pipeline": {"enabled": True, "sync_every": 2,
                           "prefetch": True}})
    rc = ResilienceConfig(
        autosave={"every_steps": 4, "io_backoff_s": 0.01},
        diagnostics_dir=str(tmp_path / "diag"))
    chaos = ChaosMonkey(ChaosConfig(seed=7, nan_steps=frozenset({2})))
    runner = FaultTolerantRunner(engine, save_dir=str(tmp_path / "ckpt"),
                                 config=rc, chaos=chaos,
                                 install_signal_handlers=False)
    try:
        result = runner.run(num_steps=6,
                            batch_fn=lambda step: random_batch(8, seed=step))
        assert result.steps_completed == 6
    finally:
        runner.close()
    path = str(tmp_path / "full_trace.json")
    trace = engine.dump_trace(path)
    names = {e["name"] for e in trace["traceEvents"]}
    # every span family of the unified timeline, in ONE dump
    assert "engine/dispatch" in names          # train dispatch
    assert "engine/drain" in names             # deferred readback
    assert "engine/steps_reconciled" in names  # true step-time windows
    assert "comm/h2d" in names                 # batch staging volume
    assert "ckpt/save" in names                # autosave boundary
    assert "chaos/nan" in names                # chaos injection instant
    assert "resilience/bad_step" in names      # guard trip instant
    # Perfetto-loadable: strict JSON from disk with the object envelope
    loaded = json.load(open(path))
    assert loaded["traceEvents"] and loaded["displayTimeUnit"] == "ms"
    # and the text report renders it
    from deepspeed_tpu.telemetry.report import aggregate, load_events
    rows, instants, wall = aggregate(load_events(path))
    assert wall > 0 and any(r["name"] == "engine/dispatch" for r in rows)
    assert instants.get("chaos/nan", 0) >= 1


# ---------------------------------------------------------------------------
# dslint proves the tracer itself never syncs
# ---------------------------------------------------------------------------
@pytest.mark.lint
def test_hotpath_taint_covers_tracer_emit_helpers(package_callgraph,
                                                 hot_reached):
    g = package_callgraph
    # the emit surface every instrumented subsystem calls per step/tick
    # stays inside the DS002 taint (no host sync can grow into it)
    for qn in ("Tracer.span", "Tracer.instant", "Tracer.complete",
               "Tracer._emit", "_Span.__enter__", "_Span.__exit__"):
        key = g.resolve("deepspeed_tpu/telemetry/tracer.py", qn)
        assert key is not None, f"{qn} gone from tracer.py"
        assert key in hot_reached, f"{qn} fell out of the hot taint"


def test_tracer_emit_is_thread_safe(tracing):
    """Concurrent emitters (serve loop / prefetch worker / watchdog shapes)
    never corrupt the ring: every event lands, ids stay unique."""
    n_threads, per = 8, 200
    tracing.configure(capacity=n_threads * per + 16)

    def emit(k):
        for i in range(per):
            tracing.instant(f"t{k}", fanout=False, i=i)

    threads = [threading.Thread(target=emit, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = tracing.events_snapshot()
    assert len(snap) == n_threads * per
    ids = [e[0] for e in snap]
    assert len(set(ids)) == len(ids)
