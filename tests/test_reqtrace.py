"""Request-tracing tests: the deterministic SLO histograms (golden
buckets — no wall clock anywhere), the dstpu_req_* /metrics families,
the reqtrace stitcher (synthetic router+replica+flight dumps with exact
tie-out arithmetic), TickLedger request attribution, and the
env_report rows.

Every duration in this file is a constructed constant (powers of two or
TickLedger ceil-div units), so bucket verdicts and tie-out errors are
bit-identical on every platform — the histogram's whole design point.
"""

import json
import os

import pytest

from deepspeed_tpu.telemetry import hist as dshist
from deepspeed_tpu.telemetry import reqtrace
from deepspeed_tpu.telemetry.names import REQ_STAGE_OF

pytestmark = pytest.mark.telemetry


# ---------------------------------------------------------------------------
# LogHistogram: golden buckets, exact and platform-independent
# ---------------------------------------------------------------------------
def test_log2_bounds_are_exact_powers():
    bounds = dshist.log2_bounds()
    assert len(bounds) == (dshist.DEFAULT_HIGH_EXP
                           - dshist.DEFAULT_LOW_EXP + 1)
    assert bounds[0] == 2.0 ** -20
    assert bounds[-1] == 64.0
    # strictly doubling — each bound IEEE-754-exact
    for a, b in zip(bounds, bounds[1:]):
        assert b == a * 2.0


def test_golden_bucket_indices():
    h = dshist.LogHistogram()
    # le-inclusive: a value exactly on a bound lands IN that bucket
    assert h.bucket_index(0.25) == h.bounds.index(0.25)
    assert h.bucket_index(0.2500001) == h.bounds.index(0.5)
    # zero and negatives land in the first bucket
    assert h.bucket_index(0.0) == 0
    assert h.bucket_index(-1.0) == 0
    # over the top bound -> the +Inf bucket (index == len(bounds))
    assert h.bucket_index(65.0) == len(h.bounds)


def test_golden_counts_sum_and_quantiles():
    h = dshist.LogHistogram()
    # durations derived from tick units, not clocks: 3 obs at 0.25s,
    # 1 at 1.0s, 1 saturating
    for v in (0.25, 0.25, 0.25, 1.0, 100.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == 0.25 * 3 + 1.0 + 100.0
    assert h.counts[h.bounds.index(0.25)] == 3
    assert h.counts[h.bounds.index(1.0)] == 1
    assert h.inf_count == 1
    # quantiles are bucket upper edges at the repo-wide exact rank rule
    assert h.quantile(0.5) == 0.25
    assert h.quantile(0.79) == 1.0
    # +Inf hits floor at the top finite bound, never a fabricated value
    assert h.quantile(0.99) == 64.0
    assert dshist.LogHistogram().quantile(0.5) == 0.0


def test_merge_delta_and_snapshot_roundtrip():
    a = dshist.LogHistogram()
    b = dshist.LogHistogram()
    a.observe_many([0.125, 0.125, 2.0])
    b.observe_many([0.125, 4.0])
    merged = dshist.LogHistogram.from_snapshot(a.snapshot())
    merged.merge(b)
    assert merged.count == 5
    assert merged.counts[merged.bounds.index(0.125)] == 3
    delta = merged.delta_from(a)
    assert delta.count == b.count
    assert delta.counts == b.counts
    assert delta.sum == pytest.approx(b.sum)
    # differing bounds are a programming error, loudly
    with pytest.raises(ValueError):
        a.merge(dshist.LogHistogram(bounds=(1.0, 2.0)))


def test_prometheus_histogram_lines_shape():
    h = dshist.LogHistogram(bounds=(0.5, 1.0))
    h.observe_many([0.5, 0.75, 3.0])
    lines = dshist.prometheus_histogram_lines(
        "dstpu_req_test_seconds", h, help_text="test family")
    text = "\n".join(lines)
    # DS008 shape: exactly one TYPE block, declared histogram
    assert text.count("# TYPE dstpu_req_test_seconds histogram") == 1
    # cumulative buckets, le-labelled, +Inf == count
    assert 'le="0.5"} 1' in text
    assert 'le="1.0"} 2' in text
    assert 'le="+Inf"} 3' in text
    assert "dstpu_req_test_seconds_count 3" in text
    assert "dstpu_req_test_seconds_sum" in text


# ---------------------------------------------------------------------------
# ServingMetrics: the dstpu_req_* families
# ---------------------------------------------------------------------------
def _finished_request(uid=1, queue_wait=0.25, prefill=0.25, decode=1.0,
                      tokens=3):
    """A terminal Request with CONSTRUCTED timestamps (no sleeping):
    queue_wait/ttft/tpot become exact powers of two."""
    from deepspeed_tpu.serving.request import Request, RequestState
    r = Request(uid, [1, 2, 3, 4], max_new_tokens=tokens)
    r.admit_ts = r.arrival_ts + queue_wait
    r.first_token_ts = r.admit_ts + prefill
    r.finish_ts = r.first_token_ts + decode
    r.tokens = list(range(tokens))
    r.state = RequestState.FINISHED
    return r


def test_serving_metrics_slo_histograms_and_families():
    from deepspeed_tpu.serving.metrics import REQ_HIST_FAMILIES, \
        ServingMetrics
    m = ServingMetrics()
    # queue_wait=0.25, ttft=0.5, tpot = 1.0/(3-1) = 0.5 — all exact bounds
    m.on_finish(_finished_request())
    m.on_handoff_latency(0.125)
    snap = m.slo_snapshot()
    assert set(snap) == {f for f, _a, _h in REQ_HIST_FAMILIES}
    ttft = dshist.LogHistogram.from_snapshot(
        snap["dstpu_req_ttft_seconds"])
    assert ttft.count == 1
    assert ttft.counts[ttft.bounds.index(0.5)] == 1
    qw = dshist.LogHistogram.from_snapshot(
        snap["dstpu_req_queue_wait_seconds"])
    assert qw.counts[qw.bounds.index(0.25)] == 1
    tpot = dshist.LogHistogram.from_snapshot(
        snap["dstpu_req_tpot_seconds"])
    assert tpot.counts[tpot.bounds.index(0.5)] == 1
    hand = dshist.LogHistogram.from_snapshot(
        snap["dstpu_req_handoff_seconds"])
    assert hand.counts[hand.bounds.index(0.125)] == 1


def test_serving_metrics_prometheus_exports_req_families():
    from deepspeed_tpu.serving.metrics import REQ_HIST_FAMILIES, \
        ServingMetrics
    m = ServingMetrics()
    m.on_finish(_finished_request())
    text = m.prometheus_text()
    for family, _attr, _help in REQ_HIST_FAMILIES:
        # DS008: exactly one TYPE block per family on the whole page
        assert text.count(f"# TYPE {family} histogram") == 1, family
        assert f'{family}_bucket{{le="+Inf"}}' in text
        assert f"{family}_count" in text
    assert 'dstpu_req_ttft_seconds_bucket{le="0.5"} 1' in text


# ---------------------------------------------------------------------------
# TickLedger request attribution (wall-clock-free units)
# ---------------------------------------------------------------------------
def test_tick_ledger_units_ceil_div():
    from deepspeed_tpu.runtime.sched import TickLedger
    assert TickLedger.units(0, 16) == 0
    assert TickLedger.units(16, 0) == 0
    assert TickLedger.units(1, 16) == 1
    assert TickLedger.units(16, 16) == 1
    assert TickLedger.units(17, 16) == 2


def test_tick_ledger_request_attribution_and_cap():
    from deepspeed_tpu.runtime.sched import TickLedger
    led = TickLedger()
    led.attribute_request(7, prefill_tokens=48, chunks=3)
    led.attribute_request(7, decode_tokens=1)
    led.attribute_request(7, decode_tokens=1)
    entry = led.pop_request(7)
    assert entry == {"ticks": 3, "prefill_tokens": 48, "chunks": 3,
                     "decode_tokens": 2}
    assert led.pop_request(7) is None          # popped == settled
    # FIFO age-out keeps the table bounded at REQUEST_CAP
    for uid in range(TickLedger.REQUEST_CAP + 5):
        led.attribute_request(uid, decode_tokens=1)
    assert len(led.request_ticks) == TickLedger.REQUEST_CAP
    assert led.pop_request(0) is None          # the oldest aged out
    assert led.pop_request(TickLedger.REQUEST_CAP + 4) is not None


# ---------------------------------------------------------------------------
# reqtrace: synthetic stitch with exact tie-out arithmetic
# ---------------------------------------------------------------------------
def _dump(pid, wall_s, events, flight=None):
    """A minimal to_chrome()-shaped dump whose epoch sits at wall time
    ``wall_s`` (monotonic_s == epoch_monotonic_s, so the wall anchor is
    exactly ``wall_s``)."""
    other = {"clock": "monotonic",
             "process": {"rank": 0, "world": 1, "hostname": "host",
                         "pid": pid, "monotonic_s": 50.0, "wall_s": wall_s,
                         "epoch_monotonic_s": 50.0}}
    if flight is not None:
        other["flight"] = flight
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


def _ev(name, ts_us, dur_us, **args):
    return {"name": name, "ph": "X", "ts": ts_us, "dur": dur_us,
            "cat": "serve", "pid": 0, "tid": 1, "args": args}


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def _drill_dumps(tmp_path):
    """The canonical failover story, in exact microseconds: the router's
    wall envelope [100, 1100]; replica A (pid 20) is killed mid-decode
    (flight dump, ledger only); the router backs off (req/reroute
    [300, 400]); replica B (pid 30, clock +500us vs the router) serves
    queue[500,600] prefill[600,800] decode[800,1050]."""
    router = _dump(10, 1000.0, [
        _ev("req/wall", 100, 1000, trace_id="t1", outcome="finished",
            uid=1, tokens=6),
        _ev("req/reroute", 300, 100, trace_id="t1", uid=1, from_replica=0,
            sent=2, recompute=10),
    ])
    flight = _dump(20, 1000.0001, [], flight={
        "reason": "chaos_replica_kill", "replica_id": 0, "pid": 20,
        "tick": 4,
        "inflight": [{"uid": 3, "trace_id": "t1", "state": "decode",
                      "generated_tokens": 2, "queue_wait_s": 1e-4,
                      "ttft_s": 2e-4,
                      "sched_attribution": {"ticks": 3, "decode_tokens": 2,
                                            "prefill_tokens": 10,
                                            "chunks": 1}}],
        "queued": []})
    replica_b = _dump(30, 1000.0005, [
        _ev("req/queue", 0, 100, trace_id="t1", uid=7),
        _ev("req/prefill", 100, 200, trace_id="t1", uid=7),
        _ev("req/decode", 300, 250, trace_id="t1", uid=7),
        _ev("req/handoff", 320, 50, trace_id="t1", uid=7),
        _ev("req/decode", 0, 100, trace_id="nobody-minted-me", uid=9),
    ])
    return [_write(tmp_path, "router.json", router),
            _write(tmp_path, "flight_replica0_20.json", flight),
            _write(tmp_path, "replica_b.json", replica_b)]


def test_stitch_failover_timeline_exact(tmp_path):
    report = reqtrace.stitch_requests(_drill_dumps(tmp_path))
    assert report["alignment"] == "wall_anchor"
    assert report["requests_stitched"] == 1
    assert report["flight_dumps"] == 1
    t1 = report["traces"]["t1"]
    assert t1["wall"]["dur_us"] == 1000.0
    assert t1["wall"]["outcome"] == "finished"
    # the surviving replica's visit chain, on the shared wall axis
    assert [v["pid"] for v in t1["visits"]] == [30]
    # handoff sub-spans decode and reroute is router-side, so neither
    # appears as a visit stage
    assert t1["visits"][0]["stages"] == ["queue", "prefill", "decode"]
    # req/reroute links the dead replica to the survivor
    assert t1["reroutes"] == 1
    # the killed attempt is visible, recovered from the flight ledger
    assert t1["flight_recovered"]
    assert t1["recovered"][0]["reason"] == "chaos_replica_kill"
    assert t1["recovered"][0]["generated_tokens"] == 2
    # EXACT tie-out: phases 100+200+250 + reroute 100 = 650us, all
    # disjoint inside the envelope -> covered == span_sum, error == 0
    assert t1["span_sum_us"] == 650.0
    assert t1["covered_us"] == 650.0
    assert t1["tie_out_error"] == 0.0
    assert t1["gap_us"] == 350.0              # unattributed transport time
    assert report["tie_out_violations"] == []
    assert report["max_tie_out_error"] == 0.0


def test_stitch_counts_orphans_loudly(tmp_path):
    report = reqtrace.stitch_requests(_drill_dumps(tmp_path))
    # the span whose trace id has no req/wall envelope anywhere
    assert report["orphan_spans"] == 1
    assert report["orphan_traces"] == ["nobody-minted-me"]
    assert report["traces"]["nobody-minted-me"]["orphan"]


def test_tie_out_flags_spans_outside_envelope(tmp_path):
    """A decode span running 300us past the wall end is overflow — the
    tie-out names it instead of trusting the row."""
    router = _dump(10, 1000.0, [
        _ev("req/wall", 100, 1000, trace_id="t1", outcome="finished",
            uid=1)])
    replica = _dump(20, 1000.0005, [
        _ev("req/queue", 0, 100, trace_id="t1", uid=7),
        _ev("req/decode", 100, 800, trace_id="t1", uid=7)])  # ends +1400
    paths = [_write(tmp_path, "r.json", router),
             _write(tmp_path, "w.json", replica)]
    report = reqtrace.stitch_requests(paths)
    t1 = report["traces"]["t1"]
    # 900us of span time, only 600 fit inside [100, 1100] -> 30% overflow
    assert t1["tie_out_error"] == pytest.approx(0.3)
    assert report["tie_out_violations"] == ["t1"]
    # ... and the CLI turns that into the regression exit code
    assert reqtrace.main(paths) == reqtrace.EXIT_REGRESSION


def test_unaligned_dump_is_flagged_not_dropped(tmp_path):
    router = _dump(10, 1000.0, [
        _ev("req/wall", 100, 1000, trace_id="t1", uid=1,
            outcome="finished")])
    headerless = {"traceEvents": [
        _ev("req/decode", 300, 200, trace_id="t1", uid=7)]}
    paths = [_write(tmp_path, "r.json", router),
             _write(tmp_path, "old.json", headerless)]
    report = reqtrace.stitch_requests(paths)
    assert report["alignment"] == "partial"
    assert report["unaligned_sources"] == [1]
    t1 = report["traces"]["t1"]
    assert not t1["aligned"]
    # the span still joined by trace id — flagged, not vanished
    assert any(s["name"] == "req/decode" for s in t1["spans"])


def test_cli_unreadable_and_artifact(tmp_path):
    assert reqtrace.main([str(tmp_path / "absent.json")]) \
        == reqtrace.EXIT_UNREADABLE
    paths = _drill_dumps(tmp_path)
    art = str(tmp_path / "reqtrace.json")
    assert reqtrace.main(paths + ["--out", art]) == reqtrace.EXIT_OK
    with open(art) as f:
        saved = json.load(f)
    assert saved["requests_stitched"] == 1
    assert saved["version"] == reqtrace.REQTRACE_VERSION


def test_render_mentions_the_story(tmp_path):
    report = reqtrace.stitch_requests(_drill_dumps(tmp_path))
    text = reqtrace.render(report)
    assert "1 requests stitched" in text
    assert "1 flight dumps" in text
    assert "t1" in text
    assert "flight" in text
    assert "nobody-minted-me" in text


def test_stage_registry_matches_stitcher_contract():
    """Every req/ span the stitcher understands is a registered trace
    name with a stage label; the envelope is not a stage."""
    from deepspeed_tpu.telemetry.names import TRACE_NAMES
    for name in REQ_STAGE_OF:
        assert name in TRACE_NAMES
    assert reqtrace.REQ_WALL_NAME in TRACE_NAMES
    assert reqtrace.REQ_WALL_NAME not in REQ_STAGE_OF


# ---------------------------------------------------------------------------
# env_report rows
# ---------------------------------------------------------------------------
def test_env_report_reqtrace_rows(tmp_path, monkeypatch):
    from deepspeed_tpu import env_report
    art = str(tmp_path / "reqtrace.json")
    assert reqtrace.main(_drill_dumps(tmp_path) + ["--out", art]) == 0
    monkeypatch.setenv(reqtrace.REQTRACE_ARTIFACT_ENV, art)
    rows = dict(env_report.reqtrace_report())
    assert "reqtrace" in rows
    assert "1 requests stitched" in rows["reqtrace"]
    assert "1 flight dumps" in rows["reqtrace"]
    assert "slo histograms" in rows
    assert "ttft" in rows["slo histograms"]
    assert "handoff" in rows["slo histograms"]


def test_env_report_reqtrace_hint_without_artifact(tmp_path, monkeypatch):
    from deepspeed_tpu import env_report
    monkeypatch.delenv(reqtrace.REQTRACE_ARTIFACT_ENV, raising=False)
    monkeypatch.chdir(tmp_path)       # no ./reqtrace.json here
    rows = dict(env_report.reqtrace_report())
    assert "no artifact" in rows["reqtrace"]
    assert reqtrace.REQTRACE_ARTIFACT_ENV in rows["reqtrace"]
