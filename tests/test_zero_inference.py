"""ZeRO-Inference tests: weight-only quantization, dequant fidelity, host
offload + layer streaming, generation parity.

Reference analog: tests/unit/inference/quantization/test_intX_quantization.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.zero_inference import (
    QuantizedTensor, ZeROInferenceEngine, dequantize_model_params,
    quantize_model_params, quantized_nbytes)
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM, random_tokens


@pytest.fixture(scope="module")
def tiny_model():
    cfg = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_layers=2, num_heads=4, num_kv_heads=4,
                      max_seq_len=128, dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        random_tokens(2, 16, vocab_size=cfg.vocab_size))["params"]
    return cfg, model, params


def test_quantize_dequantize_fidelity(tiny_model):
    _, _, params = tiny_model
    q = quantize_model_params(params, q_bits=8, group_size=64)
    back = dequantize_model_params(q, dtype=jnp.float32)
    for orig, rec in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        if np.ndim(orig) >= 2:
            rel = np.abs(np.asarray(rec) - np.asarray(orig)).max() / \
                (np.abs(np.asarray(orig)).max() + 1e-9)
            assert rel < 0.02, rel


def test_quantized_storage_is_smaller(tiny_model):
    _, _, params = tiny_model
    orig = sum(np.asarray(x).nbytes for x in jax.tree.leaves(params))
    q8 = quantized_nbytes(quantize_model_params(params, q_bits=8, group_size=64))
    assert q8 < 0.5 * orig  # int8 + scales vs fp32 → ~3.8x smaller


def test_module_scoping(tiny_model):
    _, _, params = tiny_model
    q = quantize_model_params(params, modules=["mlp/"])
    attn = q["model"]["layer_0"]["attn"]["wq"]["kernel"]
    mlp = q["model"]["layer_0"]["mlp"]["w_gate"]["kernel"]
    assert isinstance(attn, np.ndarray)          # untouched
    assert isinstance(mlp, QuantizedTensor) and mlp.codes.dtype == np.int8


def test_resident_forward_close_to_fp(tiny_model):
    cfg, model, params = tiny_model
    engine = ZeROInferenceEngine(model, params, cfg, q_bits=8, group_size=64,
                                 dtype=jnp.float32)
    batch = random_tokens(2, 12, vocab_size=cfg.vocab_size)
    logits_q = engine.forward(batch)
    logits_fp = model.apply({"params": params}, jnp.asarray(batch["input_ids"]),
                            method=lambda m, x: m.model(x))
    # quantization noise shifts logits slightly; argmax agreement is the bar
    agree = (np.argmax(np.asarray(logits_q), -1)
             == np.argmax(np.asarray(logits_fp), -1)).mean()
    assert agree > 0.9, agree


def test_streamed_forward_matches_resident(tiny_model):
    cfg, model, params = tiny_model
    resident = ZeROInferenceEngine(model, params, cfg, q_bits=8, group_size=64,
                                   dtype=jnp.float32, offload="none")
    streamed = ZeROInferenceEngine(model, params, cfg, q_bits=8, group_size=64,
                                   dtype=jnp.float32, offload="cpu")
    batch = random_tokens(1, 10, vocab_size=cfg.vocab_size)
    lr = resident.forward(batch)
    ls = streamed.forward(batch)
    np.testing.assert_allclose(np.asarray(ls), np.asarray(lr), rtol=2e-2,
                               atol=2e-2)


@pytest.mark.slow
def test_generate_resident_and_streamed_agree(tiny_model):
    cfg, model, params = tiny_model
    resident = ZeROInferenceEngine(model, params, cfg, q_bits=8, group_size=64,
                                   dtype=jnp.float32, offload="none")
    streamed = ZeROInferenceEngine(model, params, cfg, q_bits=8, group_size=64,
                                   dtype=jnp.float32, offload="cpu")
    prompt = [3, 7, 11, 19]
    out_r = resident.generate(prompt, max_new_tokens=4)
    out_s = streamed.generate(prompt, max_new_tokens=4)
    assert out_r == out_s, (out_r, out_s)


@pytest.mark.slow
def test_streamed_forward_gemma_knobs_match_model():
    """The streamed layer-by-layer path must honor the gemma llama-variant
    knobs ((1+scale) norms, gelu_tanh, embed normalizer, logit softcap)."""
    import dataclasses

    from deepspeed_tpu.models.llama import TINY_LLAMA, LlamaForCausalLM

    cfg = dataclasses.replace(
        TINY_LLAMA, dtype=jnp.float32, tie_embeddings=True,
        hidden_act="gelu_tanh", rms_scale_offset=True, scale_embeddings=True,
        logits_soft_cap=20.0, num_kv_heads=4)
    model = LlamaForCausalLM(cfg)
    batch = random_tokens(1, 10, vocab_size=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(5), batch)["params"]
    resident = ZeROInferenceEngine(model, params, cfg, q_bits=8,
                                    group_size=64, dtype=jnp.float32,
                                    offload="none")
    streamed = ZeROInferenceEngine(model, params, cfg, q_bits=8,
                                   group_size=64, dtype=jnp.float32,
                                   offload="cpu")
    got = np.asarray(streamed.forward(batch))
    # resident runs the v2 policy path on the same quantized store — both
    # sides must agree on the gemma knobs for this to hold
    want = np.asarray(resident.forward(batch))
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("q_bits,max_rel,bytes_per_elem", [
    (6, 0.14, 0.75), (8, 0.08, 1.0), (12, 0.01, 1.5)])
def test_fp_weight_quantization_formats(tiny_model, q_bits, max_rel,
                                        bytes_per_elem):
    """fmt='fp' weight-only quantization (reference FP_Quantize breadth):
    fp6/fp12 store densely bit-packed codes, fp8 native float8; dequant
    error follows the mantissa width and storage matches the bit width."""
    _, _, params = tiny_model
    q = quantize_model_params(params, q_bits=q_bits, group_size=64, fmt="fp")
    leaves = [x for x in jax.tree.leaves(
        q, is_leaf=lambda n: isinstance(n, QuantizedTensor))
        if isinstance(x, QuantizedTensor)]
    assert leaves and all(
        leaf.fmt == f"fp{q_bits}" for leaf in leaves)
    # storage: codes bytes per quantized element (pad + scales excluded)
    for leaf in leaves:
        n_padded = int(np.ceil(np.prod(leaf.shape) / 64) * 64)
        assert np.asarray(leaf.codes).nbytes == int(n_padded * bytes_per_elem)
    back = dequantize_model_params(q, dtype=jnp.float32)
    for orig, deq in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        if orig.ndim < 2:
            continue
        rel = float(jnp.abs(deq - orig).max() /
                    (jnp.abs(orig).max() + 1e-12))
        assert rel < max_rel, (q_bits, rel)


def test_fp_weight_quantization_forward_close(tiny_model):
    """fp12-quantized resident forward stays close to the fp model."""
    cfg, model, params = tiny_model
    eng = ZeROInferenceEngine(model, params, cfg, q_bits=12, fmt="fp",
                              dtype=jnp.float32)
    batch = random_tokens(2, 16, vocab_size=cfg.vocab_size)
    ref = model.apply({"params": params}, batch,
                      method=lambda m, b: m.model(b["input_ids"]))
    out = eng.forward(batch)
    rel = float(jnp.abs(out - ref).max() / (jnp.abs(ref).max() + 1e-9))
    assert rel < 0.05, rel


@pytest.mark.slow
def test_streamed_generate_uses_host_kv_cache(tiny_model):
    """Offload-mode generation decodes incrementally against the
    host-offloaded KV cache (reference ZeRO-Inference KV offload) and
    matches the resident paged engine's greedy ids exactly."""
    cfg, model, params = tiny_model
    prompt = list(np.random.default_rng(5).integers(0, cfg.vocab_size, 10))
    res = ZeROInferenceEngine(model, params, cfg, dtype=jnp.float32)
    off = ZeROInferenceEngine(model, params, cfg, offload="cpu",
                              dtype=jnp.float32)
    g_res = res.generate(prompt, max_new_tokens=6)
    g_off = off.generate(prompt, max_new_tokens=6)
    assert g_res == g_off, (g_res, g_off)


def test_int4_packed_weights_halve_storage_and_serve(tiny_model):
    """q_bits=4 nibble-packs two codes per byte (reference csrc/quantization
    int4 layout): ~half the int8 store, and the streamed forward still
    generates."""
    cfg, model, params = tiny_model
    q4 = quantize_model_params(params, q_bits=4, group_size=64)
    q8 = quantize_model_params(params, q_bits=8, group_size=64)
    assert quantized_nbytes(q4) < 0.62 * quantized_nbytes(q8)
    # roundtrip error bounded by one int4 step per group
    for p4, orig in zip(jax.tree.leaves(
            dequantize_model_params(q4, jnp.float32)),
            jax.tree.leaves(params)):
        err = float(np.max(np.abs(np.asarray(p4, np.float32)
                                  - np.asarray(orig, np.float32))))
        assert err <= float(np.abs(np.asarray(orig)).max()) / 7 + 1e-6
    eng = ZeROInferenceEngine(model, params, model_config=cfg, q_bits=4)
    out = eng.generate(list(range(8)), max_new_tokens=4)
    assert len(out) == 4


def test_int4_odd_group_size_rejected(tiny_model):
    """int4 packs two codes per byte: an odd group_size must fail with a
    descriptive config error, not an opaque reshape ValueError."""
    _, _, params = tiny_model
    with pytest.raises(ValueError, match="two codes per byte"):
        quantize_model_params(params, q_bits=4, group_size=63)
    # other int widths don't pack, so odd groups stay legal
    quantize_model_params(params, q_bits=8, group_size=63)
