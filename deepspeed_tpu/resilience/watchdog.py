"""Hung-step watchdog.

A stuck collective (one slice preempted mid-allreduce, a wedged DMA) makes
``train_batch`` block forever with no exception to catch — the job burns its
reservation silently. The watchdog is a monitor thread fed step begin/end
heartbeats; when a step overruns its deadline it (1) dumps a diagnostics
snapshot — live Python stacks of every thread (``faulthandler``), the last
step metrics, device memory stats — and (2) escalates per policy: ``warn``
logs and keeps waiting; ``interrupt`` delivers SIGINT to the main thread,
which the ``FaultTolerantRunner`` treats exactly like a preemption (final
autosave, clean stop) — note this only reaches a main thread that still
executes Python bytecode, i.e. host-side stalls; ``kill`` SIGKILLs the
process from the monitor thread, which works even for a main thread wedged
inside a native XLA collective — the snapshot is already on disk and the
elastic agent relaunches from the last committed checkpoint.

Reference analog: torchelastic's watchdog/health-check loop + the
``py-spy``-style stack dump operators attach by hand when a job wedges.
"""

import faulthandler
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from deepspeed_tpu.resilience.config import WatchdogConfig
from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.logging import logger

#: trailing trace slice embedded in hang/quarantine diagnostic bundles
TRACE_TAIL_S = 60.0


@dataclass
class WatchdogEvent:
    step: int
    elapsed_s: float
    snapshot_path: Optional[str]


class StepWatchdog:
    """``begin_step``/``end_step`` bracket every engine step; the monitor
    thread flags at most once per step index."""

    def __init__(self, config: Optional[WatchdogConfig] = None,
                 diagnostics_dir: str = "./resilience_diagnostics",
                 on_flag: Optional[Callable[[WatchdogEvent], None]] = None,
                 context_fn: Optional[Callable[[], dict]] = None):
        self.cfg = config or WatchdogConfig()
        self.diagnostics_dir = diagnostics_dir
        self.on_flag = on_flag
        self.context_fn = context_fn
        self.events = []                     # flagged WatchdogEvents
        self._lock = threading.Lock()
        self._current: Optional[tuple] = None    # (step, start_monotonic)
        self._flagged_step: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def start(self) -> "StepWatchdog":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="dstpu-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def begin_step(self, step: int):
        with self._lock:
            self._current = (int(step), time.monotonic())

    def end_step(self):
        with self._lock:
            self._current = None

    # ------------------------------------------------------------------
    def _loop(self):
        poll = max(0.05, min(self.cfg.poll_s, self.cfg.step_deadline_s / 4))
        while not self._stop.wait(timeout=poll):
            with self._lock:
                cur = self._current
            if cur is None:
                continue
            step, start = cur
            elapsed = time.monotonic() - start
            if elapsed < self.cfg.step_deadline_s or self._flagged_step == step:
                continue
            self._flagged_step = step
            self._flag(step, elapsed)

    def _flag(self, step: int, elapsed: float):
        get_tracer().instant("resilience/watchdog_flag", cat="resilience",
                             step=step, elapsed_s=round(elapsed, 3),
                             policy=self.cfg.policy)
        snapshot = None
        try:
            snapshot = self._dump_snapshot(step, elapsed)
        except Exception:
            logger.exception("watchdog: diagnostics snapshot failed")
        logger.error(
            f"watchdog: step {step} exceeded deadline "
            f"({elapsed:.1f}s > {self.cfg.step_deadline_s:.1f}s); "
            f"snapshot: {snapshot}")
        event = WatchdogEvent(step=step, elapsed_s=elapsed,
                              snapshot_path=snapshot)
        self.events.append(event)
        if self.on_flag is not None:
            try:
                self.on_flag(event)
            except Exception:
                logger.exception("watchdog: on_flag callback failed")
        if self.cfg.policy == "interrupt":
            # reaches the main thread at its next bytecode — effective for
            # Python-level stalls; a native-code hang needs policy "kill"
            import _thread
            _thread.interrupt_main()
        elif self.cfg.policy == "kill":
            import os as _os
            import signal as _signal
            logger.error("watchdog: policy=kill — SIGKILL self; the "
                         "supervisor relaunches from the last committed "
                         "checkpoint")
            _os.kill(_os.getpid(), _signal.SIGKILL)

    def _dump_snapshot(self, step: int, elapsed: float) -> str:
        """Diagnostics bundle for one hang: live stacks of every thread plus
        whatever host-side context the runner wired in (last metrics, KV/HBM
        occupancy)."""
        d = os.path.join(self.diagnostics_dir, f"hang_step{step}")
        os.makedirs(d, exist_ok=True)
        stacks = os.path.join(d, "stacks.txt")
        with open(stacks, "w") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
        context = {"step": step, "elapsed_s": elapsed,
                   "deadline_s": self.cfg.step_deadline_s,
                   "device_memory": _device_memory_stats()}
        if self.context_fn is not None:
            try:
                context.update(self.context_fn())
            except Exception as e:
                context["context_error"] = repr(e)
        with open(os.path.join(d, "context.json"), "w") as f:
            json.dump(context, f, indent=2, default=str)
        # the last minute of the unified timeline ("what led up to the
        # hang"), Perfetto-loadable straight out of the bundle
        tracer = get_tracer()
        if tracer.enabled:
            tracer.export_chrome(os.path.join(d, "trace_tail.json"),
                                 tail_s=TRACE_TAIL_S)
        return d


def _device_memory_stats() -> dict:
    """Best-effort per-device memory stats (HBM occupancy on TPU; often
    empty on CPU backends)."""
    out = {}
    try:
        import jax
        for dev in jax.local_devices():
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if stats:
                out[str(dev)] = {k: stats[k] for k in
                                 ("bytes_in_use", "bytes_limit",
                                  "peak_bytes_in_use") if k in stats}
    except Exception:
        pass
    return out
