"""Distributed health: per-worker heartbeats, a membership view, and
rank-relative straggler detection.

The comm guard (``comm/guard.py``) bounds individual host-driven ops; this
module answers the cluster-level question a bounded op cannot — *which
worker is the problem?* Each worker runs a ``Heartbeat`` thread publishing
liveness + its last-completed comm op into a shared directory (one JSON
file per rank, written atomically); any process — the serve loop, the
elastic agent, an oncall shell — reads the same files through
``MembershipView`` and classifies peers alive / lost by heartbeat age.

A filesystem store is deliberate: it needs no extra rendezvous (the thing
that is wedged when you need membership most), works identically for the
single-host MULTICHIP harness, gcsfuse-mounted pods, and CPU tests, and a
dead worker's file going stale is exactly the failure signal — no
unpublish protocol to get wrong. Heartbeat age is measured from the rank
file's **mtime** (the store's own clock, assigned by the filesystem on
every atomic replace), never from the writer's embedded wall-clock — N
workers' clock skew cannot fake a dead peer or hide one.

Straggler detection is separate from liveness: a slow peer still
heartbeats. ``StragglerDetector`` consumes per-op per-rank durations
(from dstrace comm spans, or synthetic timings in tests) and flags
rank-relative outliers with a ``comm/straggler`` instant + counter.
"""

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.comm.guard import (clear_comm_op_listener,
                                      set_comm_op_listener)
from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.logging import logger

MEMBERSHIP_DIR_ENV = "DSTPU_MEMBERSHIP_DIR"
_RANK_FILE = "rank_{rank}.json"


def default_membership_dir() -> str:
    return os.environ.get(MEMBERSHIP_DIR_ENV,
                          os.path.join(os.getcwd(), "membership"))


class Heartbeat:
    """Per-worker liveness publisher.

    A daemon thread writes ``rank_<N>.json`` every ``interval_s`` with the
    wall-clock timestamp, beat counter, and the last comm op this worker
    completed (fed lock-free-for-the-producer via ``note_op``, which the
    collective facade calls through ``comm.guard.note_comm_op``).

    Chaos: a duck-typed monkey with ``peer_dead(rank) -> bool`` silences
    this rank's publisher — the membership view then sees the file go
    stale, exactly like a real dead worker.
    """

    def __init__(self, rank: int, directory: Optional[str] = None,
                 interval_s: float = 1.0, chaos=None,
                 listen_comm_ops: bool = True):
        self.rank = int(rank)
        self.directory = directory or default_membership_dir()
        self.interval_s = float(interval_s)
        self.chaos = chaos
        self._listen = listen_comm_ops
        self._lock = threading.Lock()     # guards _last_op/_op_seq across
        self._last_op: Optional[str] = None   # producer vs publisher thread
        self._op_seq = 0
        self._beats = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- producer side (registered DS002 hot path: no host sync) ---------
    def note_op(self, op_name: str) -> None:
        with self._lock:
            self._last_op = op_name
            self._op_seq += 1

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "Heartbeat":
        if self._thread is None:
            os.makedirs(self.directory, exist_ok=True)
            if self._listen:
                set_comm_op_listener(self.note_op)
            self.publish_now()            # visible before the first interval
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name=f"dstpu-heartbeat-r{self.rank}")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._listen:
            # conditional clear: when heartbeat lifetimes overlap (rolling
            # runner replacement, training + serving in one process) a
            # stopped heartbeat must never sever the NEWER one's feed
            clear_comm_op_listener(self.note_op)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- publisher side --------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            try:
                self.publish_now()
            except OSError:
                # the membership dir being briefly unwritable must not kill
                # the worker; a missed beat is the degraded signal itself
                logger.exception("heartbeat: publish failed")

    def publish_now(self) -> None:
        """One atomic publish (tmp + rename so readers never see a torn
        JSON). Silenced when chaos declares this rank dead."""
        if self.chaos is not None and self.chaos.peer_dead(self.rank):
            return
        with self._lock:
            last_op, op_seq = self._last_op, self._op_seq
        self._beats += 1
        rec = {"rank": self.rank, "pid": os.getpid(), "ts": time.time(),
               "beat": self._beats, "last_op": last_op, "op_seq": op_seq,
               "interval_s": self.interval_s}
        path = os.path.join(self.directory, _RANK_FILE.format(rank=self.rank))
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)


@dataclass(frozen=True)
class PeerHealth:
    rank: int
    alive: bool
    age_s: float
    beat: int
    last_op: Optional[str]
    op_seq: int
    pid: int


class MembershipView:
    """Read-side of the membership store: classify every published rank
    alive / lost by heartbeat age. Stateless per call — each ``snapshot``
    re-reads the rank files, so any process can hold a view."""

    def __init__(self, directory: Optional[str] = None,
                 lost_after_s: float = 10.0,
                 expected_ranks: Optional[Sequence[int]] = None):
        self.directory = directory or default_membership_dir()
        self.lost_after_s = float(lost_after_s)
        self.expected_ranks = tuple(expected_ranks) if expected_ranks else None
        # expected-but-never-published ranks get the same staleness budget
        # from view creation before counting as lost — without this grace a
        # fast worker would declare its still-booting peers dead at startup
        self._created = time.monotonic()
        self._next_poll = 0.0

    def snapshot(self) -> Dict[int, PeerHealth]:
        out: Dict[int, PeerHealth] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        now = time.time()
        for name in names:
            if not (name.startswith("rank_") and name.endswith(".json")):
                continue
            path = os.path.join(self.directory, name)
            try:
                with open(path) as f:
                    rec = json.load(f)
                # age by the file's mtime — the store's single clock (set by
                # the filesystem at every atomic replace), immune to writer
                # wall-clock skew; the record's own ts is informational
                ts = os.stat(path).st_mtime
            except (OSError, ValueError):
                continue              # mid-replace race or junk file
            age = max(0.0, now - ts)
            rank = int(rec.get("rank", -1))
            out[rank] = PeerHealth(
                rank=rank, alive=age <= self.lost_after_s, age_s=age,
                beat=int(rec.get("beat", 0)), last_op=rec.get("last_op"),
                op_seq=int(rec.get("op_seq", 0)),
                pid=int(rec.get("pid", 0)))
        return out

    def _lost(self, snap: Dict[int, PeerHealth]) -> List[int]:
        # with an explicit membership (expected_ranks), only members can be
        # lost: a stale file from a rank OUTSIDE the set is a corpse from a
        # previous (pre-shrink) generation, not a dead peer — an elastic
        # relaunch at world M must not wedge on world-N leftovers. Without
        # an expected set every published rank counts (ad-hoc membership).
        lost = [r for r, h in snap.items() if not h.alive
                and (self.expected_ranks is None or r in self.expected_ranks)]
        if self.expected_ranks is not None and \
                time.monotonic() - self._created > self.lost_after_s:
            lost.extend(r for r in self.expected_ranks if r not in snap)
        return sorted(set(lost))

    def lost_peers(self) -> List[int]:
        """Ranks that published once and then went silent past
        ``lost_after_s`` — plus expected ranks that never published at
        all, when an expected set was given."""
        return self._lost(self.snapshot())

    def poll_lost(self) -> Optional[List[int]]:
        """Throttled ``lost_peers`` — THE form for hot callers (the
        runner's step boundary, the serve tick): at most one directory
        scan per half the ``lost_after_s`` window (floor 0.5 s), so the
        view owns its own cadence instead of every caller re-deriving it.
        Returns ``None`` between polls, the lost list when one ran."""
        now = time.monotonic()
        if now < self._next_poll:
            return None
        self._next_poll = now + max(self.lost_after_s / 2.0, 0.5)
        return self.lost_peers()

    def healthy(self) -> bool:
        return not self.lost_peers()

    def summary(self) -> dict:
        """The ``/healthz`` payload fragment: per-rank age/last-op plus the
        lost list (derived from ONE directory scan — this runs per health
        request, possibly against a remote-mounted store)."""
        snap = self.snapshot()
        return {
            "ranks": {str(r): {"alive": h.alive, "age_s": round(h.age_s, 3),
                               "beat": h.beat, "last_op": h.last_op,
                               "op_seq": h.op_seq}
                      for r, h in sorted(snap.items())},
            "lost": self._lost(snap),
        }


class StragglerDetector:
    """Rank-relative comm-duration outliers.

    Feed one op's per-rank durations (``observe``) or a batch of dstrace
    comm span events carrying a ``rank`` arg (``ingest_spans``); a rank
    whose duration exceeds ``median * factor`` (and the excess exceeds
    ``min_s``, filtering clock noise on fast ops) emits a
    ``comm/straggler`` instant and bumps ``count`` — the deterministic
    proof counter the tier-1 drill asserts on.
    """

    def __init__(self, factor: float = 3.0, min_s: float = 0.0):
        if factor <= 1.0:
            raise ValueError("straggler factor must be > 1.0")
        self.factor = float(factor)
        self.min_s = float(min_s)
        self.count = 0
        self.flagged: List[Tuple[str, int, float, float]] = []

    def observe(self, op: str, durations_by_rank: Dict[int, float]
                ) -> List[int]:
        """Returns the outlier ranks for this op (possibly empty)."""
        if len(durations_by_rank) < 2:
            return []
        durs = sorted(durations_by_rank.values())
        # LOWER median: identical to durs[n//2] for odd n, but at n=2 it
        # compares against the FASTER rank — the upper median would pick
        # the slower rank itself and make a 2-process straggler (the
        # MULTICHIP crossrank drill) mathematically unflaggable
        median = durs[(len(durs) - 1) // 2]
        if median <= 0:
            return []
        outliers = []
        tracer = get_tracer()
        for rank, d in sorted(durations_by_rank.items()):
            if d > median * self.factor and (d - median) > self.min_s:
                outliers.append(rank)
                self.count += 1
                self.flagged.append((op, rank, d, median))
                tracer.instant("comm/straggler", cat="comm", op=op,
                               rank=rank, duration_s=round(d, 6),
                               median_s=round(median, 6))
        return outliers

    def ingest_spans(self, events) -> List[int]:
        """Consume dstrace event tuples (the ``Tracer.events_snapshot``
        layout): complete ``comm/*`` spans whose args carry ``rank`` are
        grouped per op name and judged together."""
        by_op: Dict[str, Dict[int, float]] = {}
        for eid, name, cat, ph, ts, dur, tid, args in events:
            if ph != "X" or not name.startswith("comm/") or not args:
                continue
            if "rank" not in args:
                continue
            by_op.setdefault(name, {})[int(args["rank"])] = float(dur)
        flagged: List[int] = []
        for op, durs in sorted(by_op.items()):
            flagged.extend(self.observe(op, durs))
        return flagged
