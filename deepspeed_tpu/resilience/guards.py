"""Per-step health guards: non-finite detection, lr backoff, quarantine.

Reference analog: the engine's fp16 overflow skip (``fused_optimizer.py``
``_overflow_check_and_loss_scale_update``) generalized to bf16/fp32 — the
device-side skip itself lives in ``runtime/engine.py::_update`` (enabled via
``engine.set_nonfinite_guard``); this module is the host-side policy layer
that watches the step outputs and decides backoff / quarantine / abort.

Division of labor per bad step:
  device (engine)   : grads found non-finite -> update dropped, params kept,
                      ``skipped_steps`` incremented (fp16 additionally backs
                      off the loss scale — the existing scaler)
  host (this guard) : counts consecutive bad steps; after ``backoff_after``
                      shrinks the lr by ``lr_backoff_factor`` (re-tracing the
                      compiled step with the scaled schedule); after
                      ``quarantine_after`` raises ``QuarantineError`` so the
                      runner can emit a diagnostic bundle and stop burning
                      accelerator time on a poisoned run.
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.resilience.config import StepGuardConfig
from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.logging import logger


class BadStepError(RuntimeError):
    """A non-finite step under ``policy="abort"``."""


class QuarantineError(RuntimeError):
    """Too many consecutive bad steps — the run is quarantined.
    ``bundle_path`` (set by the runner) points at the diagnostic bundle."""

    def __init__(self, msg: str, bundle_path: Optional[str] = None):
        super().__init__(msg)
        self.bundle_path = bundle_path


def _finite_report(values: Dict[str, Any]) -> Dict[str, bool]:
    """Fused step-health readback: every device-resident value is reduced ON
    DEVICE (``isfinite(...).all()`` for floats, identity for the bool
    overflow flag), the flags are stacked, and ONE ``device_get`` moves them
    to host — replacing the per-tensor scalar transfers the old ``_finite``
    paid. Host scalars are checked locally; absent/non-numeric values count
    as finite (None overflow stays None so callers can tell "no flag" from
    False)."""
    out: Dict[str, Any] = {}
    names, flags = [], []
    for k, v in values.items():
        if v is None:
            out[k] = None
            continue
        if isinstance(v, jax.Array):
            if jnp.issubdtype(v.dtype, jnp.floating):
                names.append(k)
                flags.append(jnp.isfinite(v).all())
            elif jnp.issubdtype(v.dtype, jnp.bool_):
                names.append(k)      # flag VALUE (overflow), not finiteness
                flags.append(v.any())
            else:
                out[k] = True        # integer metrics are always finite
            continue
        # host values (python scalars, numpy scalars/0-d arrays from a prior
        # device_get, drained async-pipeline entries): dtype decides —
        # bools are flag values, everything else is finiteness-checked
        try:
            a = np.asarray(v)
        except Exception:
            out[k] = True
            continue
        if a.dtype == np.bool_:
            out[k] = bool(a.any())
        elif np.issubdtype(a.dtype, np.integer):
            out[k] = True            # ints can't be non-finite
        else:
            # cast-then-check covers ml_dtypes floats too (bf16/fp8 numpy
            # scalars fail np.issubdtype(..., np.floating) but a NaN there
            # is still a bad step); non-numerics count as finite
            try:
                out[k] = bool(np.isfinite(np.asarray(a, np.float64)).all())
            except (TypeError, ValueError):
                out[k] = True
    if flags:
        host = jax.device_get(jnp.stack(flags))
        out.update({k: bool(f) for k, f in zip(names, host)})
    return out


class StepGuard:
    def __init__(self, engine, config: Optional[StepGuardConfig] = None):
        self.engine = engine
        self.cfg = config or StepGuardConfig()
        self.consecutive_bad = 0
        self.total_bad = 0
        self.good_since_backoff = 0
        self.lr_scale = 1.0
        self._base_lr_schedule = engine.lr_schedule
        self._armed = False
        self._tx_wrapped = False
        if self.cfg.enabled and self.cfg.policy == "skip":
            if getattr(engine, "_param_offload", None) is not None:
                # the ZeRO-Infinity streamed step applies updates in the
                # fused host optimizer, outside the guarded jit path — a NaN
                # update there CANNOT be dropped, so don't advertise
                # clean-params semantics; detection/backoff/quarantine still
                # run on the observed loss
                logger.warning(
                    "step guard: on-device skip is not supported with "
                    "offload_param (fused host optimizer applies updates "
                    "outside the guarded path); bad steps are detected and "
                    "quarantined but their updates are NOT dropped")
            elif not getattr(engine, "_guard_nonfinite", False):
                # device-side skip: non-finite grads behave like an fp16
                # overflow (update dropped, params stay clean) in every
                # precision mode
                engine.set_nonfinite_guard(True)
                self._armed = True

    def detach(self):
        """Disarm the device-side guard IF this StepGuard armed it (an
        engine whose config armed it explicitly keeps it): after the runner
        closes, bf16/fp32 regain their default NaN-propagation semantics."""
        if self._armed:
            self.engine.set_nonfinite_guard(False)
            self._armed = False

    # ------------------------------------------------------------------
    def observe(self, loss, metrics: Dict[str, Any]) -> bool:
        """Inspect one completed step; returns True when the step was bad.
        Raises ``BadStepError`` (policy "abort") or ``QuarantineError``.
        Device-resident values cost ONE fused device check + host transfer
        for the whole health report (loss finiteness, grad-norm finiteness,
        overflow flag); host scalars (e.g. a drained async-pipeline entry)
        cost no transfer at all."""
        if not self.cfg.enabled:
            return False
        report = _finite_report({
            "loss": loss,
            "grad_norm": metrics.get("grad_norm", 0.0),
            "overflow": metrics.get("overflow"),
        })
        overflow = report["overflow"]
        bad = not report["loss"] or not report["grad_norm"]
        if not bad and overflow is not None:
            bad = bool(overflow)
            if bad and self.engine.config.fp16.enabled:
                # overflow-only with finite loss/grad-norm under fp16 is the
                # dynamic loss scaler doing its job (scale-search overflows
                # are routine, especially at run start) — the scaler owns
                # that path; counting it here would back off / quarantine a
                # healthy run
                bad = False
        if not bad:
            self._on_good_step()
            return False
        self.consecutive_bad += 1
        self.total_bad += 1
        self.good_since_backoff = 0
        logger.warning(
            f"step guard: non-finite step detected "
            f"(consecutive={self.consecutive_bad}, total={self.total_bad})")
        get_tracer().instant("resilience/bad_step", cat="resilience",
                             step=self.engine.global_steps,
                             consecutive=self.consecutive_bad,
                             total=self.total_bad)
        if self.cfg.policy == "abort":
            raise BadStepError(
                f"non-finite loss/grads at global step "
                f"{self.engine.global_steps} (policy=abort)")
        if (self.cfg.backoff_after
                and self.consecutive_bad % self.cfg.backoff_after == 0):
            self._backoff_lr()
        if (self.cfg.quarantine_after
                and self.consecutive_bad >= self.cfg.quarantine_after):
            get_tracer().instant("resilience/quarantine", cat="resilience",
                                 step=self.engine.global_steps,
                                 consecutive=self.consecutive_bad)
            raise QuarantineError(
                f"{self.consecutive_bad} consecutive non-finite steps "
                f"(quarantine_after={self.cfg.quarantine_after}); "
                + ("engine state preserved at the last good step"
                   if self._armed or self.engine.config.fp16.enabled
                   else "engine state may be poisoned (no on-device skip "
                        "active)"))
        return True

    # ------------------------------------------------------------------
    def _on_good_step(self):
        self.consecutive_bad = 0
        if self.lr_scale < 1.0 and self.cfg.lr_recovery_steps:
            self.good_since_backoff += 1
            if self.good_since_backoff >= self.cfg.lr_recovery_steps:
                self.good_since_backoff = 0
                self._set_lr_scale(
                    min(1.0, self.lr_scale / self.cfg.lr_backoff_factor))

    def _backoff_lr(self):
        new_scale = max(self.cfg.min_lr_scale,
                        self.lr_scale * self.cfg.lr_backoff_factor)
        if new_scale != self.lr_scale:
            self._set_lr_scale(new_scale)

    def _wrap_tx(self):
        """Wrap ``engine.tx`` so the lr scale reaches the REAL update, not
        just the reported metric: the schedule was baked into the optax
        chain at engine construction, so scaling must happen on the updates
        the chain emits. ``init`` is untouched — opt_state structure (and
        its shardings / the restore target) is unchanged. The scale is read
        at trace time; every change re-traces via _reset_compiled_fns."""
        if self._tx_wrapped:
            return
        import optax
        inner = self.engine.tx
        guard = self

        def update(grads, state, params=None):
            updates, new_state = inner.update(grads, state, params)
            s = guard.lr_scale            # trace-time constant
            if s != 1.0:
                updates = jax.tree.map(lambda u: u * s, updates)
            return updates, new_state

        self.engine.tx = optax.GradientTransformation(inner.init, update)
        self._tx_wrapped = True

    def _set_lr_scale(self, scale: float):
        if getattr(self.engine, "_param_offload", None) is not None:
            # the ZeRO-Infinity fused host optimizer captured its schedule at
            # construction and uses neither engine.tx nor engine.lr_schedule;
            # silently "scaling" here would report a backed-off lr while
            # updates keep applying at full rate — refuse instead so the
            # telemetry stays truthful
            logger.warning(
                "step guard: lr backoff is not supported with offload_param "
                "(fused host optimizer owns the schedule); lr unchanged")
            return
        # dslint: disable=DS002 -- scale is a python float from the backoff schedule, not an array
        self.lr_scale = float(scale)
        get_tracer().instant("resilience/lr_backoff", cat="resilience",
                             step=self.engine.global_steps,
                             lr_scale=self.lr_scale)
        base = self._base_lr_schedule
        s = self.lr_scale
        self._wrap_tx()
        # the reported/host-side lr (get_lr, monitor events, the host
        # offload optimizer's per-step lr) follows the same scale
        self.engine.lr_schedule = (base if s == 1.0
                                   else (lambda step: base(step) * s))
        # the fused step closed over the old tx/schedule — force a re-trace
        self.engine._reset_compiled_fns()
        logger.warning(f"step guard: lr scale now {self.lr_scale:g}")

    # ------------------------------------------------------------------
    # checkpointable state (rides in client_state so backoff survives resume)
    # ------------------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        return {"consecutive_bad": self.consecutive_bad,
                "total_bad": self.total_bad,
                "good_since_backoff": self.good_since_backoff,
                "lr_scale": self.lr_scale}

    def load_state_dict(self, sd: Dict[str, Any]):
        self.consecutive_bad = int(sd.get("consecutive_bad", 0))
        self.total_bad = int(sd.get("total_bad", 0))
        self.good_since_backoff = int(sd.get("good_since_backoff", 0))
        scale = float(sd.get("lr_scale", 1.0))
        if scale != self.lr_scale:
            self._set_lr_scale(scale)
