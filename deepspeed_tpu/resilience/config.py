"""Resilience configuration (the "resilience" config group).

Reference analogs: the engine-level skip-step / loss-scale backoff knobs
(``runtime/fp16/loss_scaler.py``), torchelastic's restart budget, and the
checkpoint-cadence keys scattered through ``runtime/config.py`` — gathered
here into one subsystem config the ``FaultTolerantRunner`` consumes.

Every knob is also reachable through the standard single-JSON engine config::

    {"resilience": {"step_guard": {...}, "autosave": {...}, "watchdog": {...}}}
"""

from typing import Optional

from pydantic import Field, model_validator

from deepspeed_tpu.config.config_utils import DeepSpeedTPUConfigModel


class StepGuardConfig(DeepSpeedTPUConfigModel):
    """Non-finite loss / grad-norm policy, layered on the engine's overflow
    path: with ``policy="skip"`` the engine treats non-finite grads exactly
    like an fp16 overflow (drop the update, keep params clean) even in
    bf16/fp32, and the runner layers backoff/quarantine on top."""
    enabled: bool = True
    # "skip": drop the bad update on-device (engine overflow path) and keep
    #         training; "abort": raise at the first bad step with a bundle
    policy: str = "skip"
    # after this many CONSECUTIVE bad steps, multiply the lr by
    # lr_backoff_factor (0 disables backoff)
    backoff_after: int = 3
    lr_backoff_factor: float = 0.5
    min_lr_scale: float = 1e-3
    # after this many consecutive GOOD steps, one backoff level is undone
    # (0 = never recover; backoff is permanent for the run)
    lr_recovery_steps: int = 0
    # consecutive bad steps before the runner gives up: raises
    # QuarantineError with a diagnostic bundle (0 disables)
    quarantine_after: int = 10

    @model_validator(mode="after")
    def _check(self):
        if self.policy not in ("skip", "abort"):
            raise ValueError(f"step_guard.policy must be skip|abort, "
                             f"got {self.policy}")
        if not 0.0 < self.lr_backoff_factor <= 1.0:
            raise ValueError("lr_backoff_factor must be in (0, 1]")
        return self


class AutosaveConfig(DeepSpeedTPUConfigModel):
    """Periodic + preemption-triggered checkpointing with retry."""
    every_steps: int = 0              # autosave every N global steps (0 = off)
    every_seconds: float = 0.0        # autosave every S wall seconds (0 = off)
    save_on_preemption: bool = True   # SIGTERM/SIGINT triggers a final save
    keep_last: int = 0                # prune committed tags beyond N (0 = all)
    # checkpoint I/O retry: attempt, then backoff_s, 2*backoff_s, ... between
    # up to io_retries re-attempts
    io_retries: int = 3
    io_backoff_s: float = 0.5


class WatchdogConfig(DeepSpeedTPUConfigModel):
    """Hung-step monitor: a step running past ``step_deadline_s`` gets a
    diagnostics snapshot (live stacks + last metrics) and escalates per
    ``policy``."""
    enabled: bool = False
    step_deadline_s: float = 1800.0
    poll_s: float = 1.0
    # "warn": log + snapshot only; "interrupt": request a preemption-style
    # stop — with the runner's handlers installed this sets the preempt
    # flag, so it takes effect when the slow step eventually RETURNS
    # (autosave + clean stop). It cannot break a step that never returns:
    # blocked calls are retried after the handler (PEP 475) and native XLA
    # code never reaches another bytecode. For hard hangs use "kill":
    # SIGKILL from the monitor thread (works regardless of what the main
    # thread is stuck in); the snapshot is already on disk and the elastic
    # agent relaunches with resume
    policy: str = "warn"

    @model_validator(mode="after")
    def _check(self):
        if self.policy not in ("warn", "interrupt", "kill"):
            raise ValueError(f"watchdog.policy must be warn|interrupt|kill, "
                             f"got {self.policy}")
        return self


class ResilienceConfig(DeepSpeedTPUConfigModel):
    step_guard: StepGuardConfig = Field(default_factory=StepGuardConfig)
    autosave: AutosaveConfig = Field(default_factory=AutosaveConfig)
    watchdog: WatchdogConfig = Field(default_factory=WatchdogConfig)
    # where quarantine/watchdog diagnostic bundles land
    diagnostics_dir: str = "./resilience_diagnostics"
    # history ring kept for diagnostic bundles (steps)
    history_steps: int = 64


def resolve_resilience_config(engine) -> ResilienceConfig:
    """The engine config's parsed "resilience" group (always present — a
    default-constructed group when the key was absent)."""
    cfg: Optional[ResilienceConfig] = getattr(engine.config, "resilience", None)
    return cfg if cfg is not None else ResilienceConfig()
