"""Preemption-safe checkpoint orchestration over ``checkpoint/engine.py``.

What ``checkpoint/engine.py`` provides (mechanism): atomic array write
(orbax), sidecar snapshot, integrity manifest, fsync'd atomic ``latest``
commit, verify-on-load. What this module adds (policy):

  - ``save_with_retry``    : exponential-backoff retry around transient
                             checkpoint I/O errors (chaos-injectable)
  - ``find_latest_committed``: newest tag whose manifest verifies — the
                             ``latest`` pointer is a hint, not an oracle; a
                             torn or corrupted tag falls back to the newest
                             clean one
  - ``resume_from_latest`` : restore engine + lr-schedule + data-schedule
                             state from that tag (never a torn checkpoint)
  - ``prune_checkpoints``  : bounded-disk retention (keep newest N committed)
  - ``Autosaver``          : step- and wall-clock-cadence save triggers
"""

import os
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.checkpoint.engine import (
    CheckpointCorruptionError, is_committed, read_latest_tag,
    wait_pending_checkpoint)
from deepspeed_tpu.utils.logging import logger


class CheckpointSaveError(RuntimeError):
    """A checkpoint save failed after exhausting its retry budget."""


def _tag_meta(save_dir: str, tag: str) -> Dict[str, Any]:
    import json
    try:
        with open(os.path.join(save_dir, tag, "ds_meta.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def list_tags(save_dir: str) -> List[str]:
    """Candidate checkpoint tags (subdirectories), newest first by the saved
    global step (mtime is the tiebreaker — step metadata can be missing on a
    torn save)."""
    save_dir = os.path.abspath(save_dir)
    if not os.path.isdir(save_dir):
        return []
    tags = [d for d in os.listdir(save_dir)
            if os.path.isdir(os.path.join(save_dir, d))]

    def key(tag):
        meta = _tag_meta(save_dir, tag)
        try:
            mtime = os.path.getmtime(os.path.join(save_dir, tag))
        except OSError:
            mtime = 0.0
        return (int(meta.get("global_steps", -1)), mtime)

    return sorted(tags, key=key, reverse=True)


def find_latest_committed(save_dir: str, verify: bool = True) -> Optional[str]:
    """The tag to resume from: the ``latest`` pointer when it names a clean
    committed checkpoint, else the newest other tag that qualifies. Returns
    None when no committed checkpoint exists at all. ``verify=False`` checks
    the commit marker only (for callers whose load path re-verifies anyway —
    skipping a redundant full-CRC read of a multi-GB checkpoint)."""
    save_dir = os.path.abspath(save_dir)
    pointed = read_latest_tag(save_dir)
    if pointed is not None and is_committed(save_dir, pointed, verify=verify):
        return pointed
    if pointed is not None:
        logger.warning(
            f"resume: 'latest' points at '{pointed}' which is missing "
            f"or fails integrity verification; scanning for the newest "
            f"committed tag")
    for tag in list_tags(save_dir):
        if tag != pointed and is_committed(save_dir, tag, verify=verify):
            return tag
    return None


def save_with_retry(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict[str, Any]] = None,
                    retries: int = 3, backoff_s: float = 0.5,
                    chaos=None) -> str:
    """``engine.save_checkpoint`` with exponential-backoff retry on I/O
    errors (reference pattern: object-store flakiness is the COMMON failure
    for long runs; one transient error must not kill the job). Retries are
    synchronous — a save that must survive preemption cannot ride an async
    finalizer whose error surfaces a step later."""
    step = engine.global_steps
    last_err: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            if chaos is not None:
                chaos.ckpt_io_check(step, attempt)
            path = engine.save_checkpoint(save_dir, tag=tag,
                                          client_state=client_state)
            # surface async-finalizer errors NOW, inside the retry loop
            wait_pending_checkpoint(engine)
            return path
        except (OSError, RuntimeError) as e:
            last_err = e
            if attempt >= retries:
                break
            delay = backoff_s * (2 ** attempt)
            logger.warning(
                f"checkpoint save attempt {attempt + 1}/{retries + 1} failed "
                f"({e!r}); retrying in {delay:.2f}s")
            time.sleep(delay)
    raise CheckpointSaveError(
        f"checkpoint save to {save_dir} failed after {retries + 1} "
        f"attempts") from last_err


def resume_from_latest(engine, save_dir: str,
                       load_optimizer_states: bool = True
                       ) -> Tuple[Optional[str], Dict[str, Any]]:
    """Discover the newest *committed* checkpoint and restore the engine
    from it — params, optimizer, loss-scale, step counter (which also pins
    the lr schedule: every schedule here is a pure function of the restored
    step), and the curriculum/random-LTD data schedules (resynced inside
    ``engine.load_checkpoint``). Returns ``(tag, client_state)``;
    ``(None, {})`` when nothing committed exists (fresh start).

    Torn checkpoints are never loaded: a tag only qualifies after its
    integrity manifest verifies, and a corruption race between discovery and
    load falls back to the next-newest clean tag."""
    save_dir = os.path.abspath(save_dir)
    tried: List[str] = []
    last_err: Optional[BaseException] = None
    while True:
        # commit-marker discovery only (verify=False): the load path's
        # verify_manifest is the single authoritative full-CRC gate — a torn
        # candidate raises there and the loop falls back, so discovery-time
        # verification would only double the resume I/O
        if not tried:
            tag = find_latest_committed(save_dir, verify=False)
        else:
            tag = next((c for c in list_tags(save_dir)
                        if c not in tried
                        and is_committed(save_dir, c, verify=False)),
                       None)
        if tag is None:
            if tried:
                raise CheckpointCorruptionError(
                    f"no loadable committed checkpoint in {save_dir} "
                    f"(tried {tried})") from last_err
            logger.info(f"resume: no committed checkpoint in {save_dir}; "
                        f"starting fresh")
            return None, {}
        tried.append(tag)
        try:
            _, client_state = engine.load_checkpoint(
                save_dir, tag=tag,
                load_optimizer_states=load_optimizer_states)
            logger.info(f"resume: restored checkpoint '{tag}' "
                        f"(global step {engine.global_steps})")
            return tag, client_state
        except (CheckpointCorruptionError, OSError, ValueError, KeyError) as e:
            # not just checksum mismatches: a tag torn BEFORE its manifest
            # landed (crash mid-ds_meta.json write, missing orbax files)
            # surfaces as JSONDecodeError / FileNotFoundError / ValueError —
            # all mean "this tag is unusable, try the next-newest commit"
            last_err = e
            logger.warning(f"resume: tag '{tag}' failed to load ({e!r}); "
                           f"trying an older commit")


def prune_checkpoints(save_dir: str, keep_last: int) -> List[str]:
    """Delete committed tags beyond the newest ``keep_last`` (the currently
    pointed-to tag is always kept). Uncommitted/torn tags are left alone —
    they are diagnostic evidence, not reclaimable state. Returns the tags
    removed."""
    if keep_last <= 0:
        return []
    save_dir = os.path.abspath(save_dir)
    # commit-marker check only (verify=False): pruning runs inside the
    # training loop on every autosave, and a full CRC re-read of every kept
    # multi-GB checkpoint there is pure waste — corruption is caught where
    # it matters, at load (verify_manifest)
    pointed = read_latest_tag(save_dir)
    committed = [t for t in list_tags(save_dir)
                 if is_committed(save_dir, t, verify=False)]
    keep = set(committed[:keep_last]) | ({pointed} if pointed else set())
    removed = []
    for tag in committed:
        if tag not in keep:
            shutil.rmtree(os.path.join(save_dir, tag), ignore_errors=True)
            removed.append(tag)
    if removed:
        logger.info(f"pruned checkpoints: {removed}")
    return removed


class Autosaver:
    """Step- and wall-clock-cadence trigger. ``due()`` is cheap enough to
    call every step; ``mark_saved()`` resets both clocks (any save counts —
    cadence, preemption, or user-initiated)."""

    def __init__(self, every_steps: int = 0, every_seconds: float = 0.0):
        self.every_steps = int(every_steps)
        self.every_seconds = float(every_seconds)
        self.last_save_step = 0
        self.last_save_time = time.monotonic()

    @property
    def enabled(self) -> bool:
        return self.every_steps > 0 or self.every_seconds > 0

    def due(self, step: int) -> bool:
        if self.every_steps > 0 and step - self.last_save_step >= self.every_steps:
            return True
        return (self.every_seconds > 0
                and time.monotonic() - self.last_save_time >= self.every_seconds)

    def mark_saved(self, step: int):
        self.last_save_step = int(step)
        self.last_save_time = time.monotonic()

    def state_dict(self) -> Dict[str, Any]:
        return {"last_save_step": self.last_save_step}

    def load_state_dict(self, sd: Dict[str, Any]):
        self.last_save_step = int(sd.get("last_save_step", 0))
        self.last_save_time = time.monotonic()
