"""Resilience subsystem: fault-tolerant training loop, preemption-safe
checkpointing, hung-step watchdog, and a deterministic fault-injection
harness. See docs/resilience.md.
"""

from deepspeed_tpu.resilience.chaos import (ChaosConfig, ChaosInjectedIOError,
                                            ChaosMonkey, monkey_from_env)
from deepspeed_tpu.resilience.checkpointing import (Autosaver,
                                                    CheckpointSaveError,
                                                    find_latest_committed,
                                                    list_tags,
                                                    prune_checkpoints,
                                                    resume_from_latest,
                                                    save_with_retry)
from deepspeed_tpu.resilience.config import (AutosaveConfig, ResilienceConfig,
                                             StepGuardConfig, WatchdogConfig)
from deepspeed_tpu.resilience.guards import (BadStepError, QuarantineError,
                                             StepGuard)
from deepspeed_tpu.resilience.membership import (Heartbeat, MembershipView,
                                                 PeerHealth, StragglerDetector,
                                                 default_membership_dir)
from deepspeed_tpu.resilience.runner import FaultTolerantRunner, RunResult
from deepspeed_tpu.resilience.watchdog import StepWatchdog, WatchdogEvent

__all__ = [
    "Autosaver",
    "AutosaveConfig",
    "BadStepError",
    "ChaosConfig",
    "ChaosInjectedIOError",
    "ChaosMonkey",
    "CheckpointSaveError",
    "FaultTolerantRunner",
    "Heartbeat",
    "MembershipView",
    "PeerHealth",
    "QuarantineError",
    "ResilienceConfig",
    "RunResult",
    "StepGuard",
    "StepGuardConfig",
    "StepWatchdog",
    "StragglerDetector",
    "WatchdogConfig",
    "WatchdogEvent",
    "default_membership_dir",
    "find_latest_committed",
    "list_tags",
    "monkey_from_env",
    "prune_checkpoints",
    "resume_from_latest",
    "save_with_retry",
]
