"""Deterministic fault-injection harness.

Every injection decision is a pure function of ``(seed, kind, step)`` — a
sha256-derived roll — so a chaos run replays bit-identically regardless of
call order, thread timing, or how many other chaos sites fire. That is what
lets the tier-1 chaos suite pin seeds and assert exact recovery behavior.

Faults covered (the failure modes the resilience subsystem exists for):
  - ``nan``   : poison the training batch so the step produces non-finite
                loss/grads (exercises the step guard + engine skip path)
  - ``ckpt``  : checkpoint I/O failure (exercises save retry-with-backoff)
  - ``slow``  : stall a step past the watchdog deadline
  - ``die``   : SIGKILL this worker at a step boundary (exercises the
                elastic agent's restart + resume-latest path)
  - ``comm``  : delay or wedge a guarded collective (``comm/guard.py``
                deadline + CommWedgeError + coordinated-abort path), or
                silence a rank's heartbeat (``peer_dead`` — membership
                marks it lost; the PERMANENT variant survives
                DSTPU_RESUME relaunches, so the elastic shrink drill is
                deterministic)
  - ``serve`` : serving-tick faults (``serving/server.py``): stall the
                serve tick (``DSTPU_CHAOS_SERVE_SLOW_TICK``), steal a
                fraction of usable KV blocks over a tick window so the
                degradation ladder + host KV tier drill end to end
                (``DSTPU_CHAOS_SERVE_KV_PRESSURE``), or make one request
                uid deterministically fault the engine step so the
                poison-quarantine path fires
                (``DSTPU_CHAOS_SERVE_POISON_UID``), or SIGKILL one fleet
                replica mid-decode (``DSTPU_CHAOS_REPLICA_KILL="RID[:TICK]"``
                — the replica whose ``DSTPU_REPLICA_ID`` matches dies at
                the first serve tick >= TICK that has decode work in
                flight; TICK omitted = sha-rolled from the seed; the
                die-once contract spares its DSTPU_RESUME relaunch, so
                the fleet failover drill is kill -> reroute -> rejoin,
                never a crash loop)

Knobs come from an explicit ``ChaosConfig`` or from the environment
(``ChaosConfig.from_env``), so a launcher can chaos-test an unmodified
training script:

  DSTPU_CHAOS_SEED=7 DSTPU_CHAOS_NAN_STEPS=3,5 DSTPU_CHAOS_CKPT_FAIL_FIRST=2 \
  DSTPU_CHAOS_SLOW_STEPS=9 DSTPU_CHAOS_SLOW_S=2.0 DSTPU_CHAOS_DIE_STEP=12 ...
"""

import hashlib
import os
import signal
import time
from dataclasses import dataclass
from typing import FrozenSet, Optional

import numpy as np

from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.logging import logger


#: set by the fleet launcher on every replica worker it spawns; the
#: replica-kill knob selects its victim by this id (and the fleet router
#: reports it back through /healthz for affinity + retirement decisions)
REPLICA_ID_ENV = "DSTPU_REPLICA_ID"


def _parse_steps(raw: str) -> FrozenSet[int]:
    return frozenset(int(s) for s in raw.replace(" ", "").split(",") if s)


def _parse_replica_kill(raw: str):
    """``"RID[:TICK]"`` -> (replica_id, tick); tick 0 = sha-rolled."""
    if not raw:
        return -1, 0
    head, _, tick = raw.partition(":")
    return int(head), int(tick or 0)


def _parse_slow_tick(raw: str):
    """``"N:SECS"`` (every Nth tick) or ``"pP:SECS"`` (probability P per
    tick via the sha roll) -> (every, prob, seconds)."""
    if not raw:
        return 0, 0.0, 0.0
    head, _, secs = raw.partition(":")
    s = float(secs or 0.0)
    if head.startswith("p"):
        return 0, float(head[1:]), s
    return int(head or 0), 0.0, s


def _parse_kv_pressure(raw: str):
    """``"FRAC[:FROM[:UNTIL]]"`` -> (frac, from_tick, until_tick);
    until < 0 means the pressure never lifts."""
    if not raw:
        return 0.0, 0, -1
    parts = raw.split(":")
    frac = float(parts[0])
    frm = int(parts[1]) if len(parts) > 1 else 0
    until = int(parts[2]) if len(parts) > 2 else -1
    return frac, frm, until


@dataclass(frozen=True)
class ChaosConfig:
    seed: int = 0
    # NaN-grad injection: explicit steps, a cadence, or a per-step probability
    nan_steps: FrozenSet[int] = frozenset()
    nan_every: int = 0
    nan_prob: float = 0.0
    # checkpoint I/O: fail the first K attempts of each save, plus a per-
    # attempt probability for steady-state flakiness
    ckpt_fail_first: int = 0
    ckpt_fail_prob: float = 0.0
    # slow/hung steps
    slow_steps: FrozenSet[int] = frozenset()
    slow_prob: float = 0.0
    slow_s: float = 0.0
    # worker death (SIGKILL — the uncatchable case) at a step boundary.
    # die_once (default): a relaunched worker (DSTPU_RESUME set by the
    # elastic agent) does NOT die again, so the kill→restart→resume path is
    # exercised once instead of crash-looping until the restart budget dies
    die_step: int = -1
    die_once: bool = True
    # device OOM (the catchable RESOURCE_EXHAUSTED case) at a step boundary
    # — drills the dsmem forensics path: engine classification, ledger +
    # sample embedding, the runner's oom diagnostic bundle
    oom_step: int = -1
    oom_once: bool = True
    # comm faults (consumed by comm/guard.py CommGuard + membership
    # Heartbeat). Call indices count GUARDED ops per CommGuard instance;
    # op patterns are exact names, "" / "*" match any op.
    comm_wedge_op: str = ""
    comm_wedge_call: int = -1         # guarded-call index that wedges
    comm_wedge_once: bool = True      # relaunched worker (DSTPU_RESUME) spared
    comm_delay_op: str = ""
    comm_delay_calls: FrozenSet[int] = frozenset()
    comm_delay_prob: float = 0.0
    comm_delay_s: float = 0.0
    # ranks whose heartbeat is silenced (membership marks them lost).
    # Default contract matches die_once/comm_wedge_once: a DSTPU_RESUME
    # relaunch of a silenced rank heartbeats again (the fault was
    # transient — capacity "came back"). The PERMANENT set survives
    # relaunches: that rank never heartbeats again in any generation,
    # which is what makes the elastic shrink drill deterministic (the
    # agent's same-world retry provably re-faults, so the membership
    # verdict "lost for good" is forced, never raced)
    peer_dead_ranks: FrozenSet[int] = frozenset()
    peer_dead_permanent_ranks: FrozenSet[int] = frozenset()
    # serving-tick faults (consumed by serving/server.py). slow_tick
    # stalls the serve tick (every Nth tick, or per-tick probability via
    # the sha roll); kv_pressure steals a fraction of usable KV blocks
    # over [from, until) ticks (until < 0 = forever); poison_uid makes
    # that request uid fault the engine step whenever it is resident
    serve_slow_tick_every: int = 0
    serve_slow_tick_prob: float = 0.0
    serve_slow_tick_s: float = 0.0
    serve_kv_pressure_frac: float = 0.0
    serve_kv_pressure_from: int = 0
    serve_kv_pressure_until: int = -1
    serve_poison_uid: int = -1
    # fleet replica death: SIGKILL the worker whose DSTPU_REPLICA_ID
    # matches, at the first serve tick >= replica_kill_tick that has
    # decode work in flight (mid-decode by construction — the router must
    # fail over live streams, not an idle process). tick 0 = sha-rolled
    # from the seed; replica_kill_once spares DSTPU_RESUME relaunches
    # (die_once contract), so kill -> reroute -> rejoin drills exactly once
    replica_kill_id: int = -1
    replica_kill_tick: int = 0
    replica_kill_once: bool = True

    @property
    def active(self) -> bool:
        return bool(self.nan_steps or self.nan_every or self.nan_prob
                    or self.ckpt_fail_first or self.ckpt_fail_prob
                    or self.slow_steps or self.slow_prob
                    or self.die_step >= 0
                    or self.oom_step >= 0
                    or self.comm_wedge_call >= 0
                    or (self.comm_delay_s > 0
                        and (self.comm_delay_calls or self.comm_delay_prob))
                    or self.peer_dead_ranks
                    or self.peer_dead_permanent_ranks
                    or (self.serve_slow_tick_s > 0
                        and (self.serve_slow_tick_every
                             or self.serve_slow_tick_prob))
                    or self.serve_kv_pressure_frac > 0
                    or self.serve_poison_uid >= 0
                    or self.replica_kill_id >= 0)

    @classmethod
    def from_env(cls, env=os.environ) -> "ChaosConfig":
        g = env.get
        return cls(
            seed=int(g("DSTPU_CHAOS_SEED", "0")),
            nan_steps=_parse_steps(g("DSTPU_CHAOS_NAN_STEPS", "")),
            nan_every=int(g("DSTPU_CHAOS_NAN_EVERY", "0")),
            nan_prob=float(g("DSTPU_CHAOS_NAN_PROB", "0")),
            ckpt_fail_first=int(g("DSTPU_CHAOS_CKPT_FAIL_FIRST", "0")),
            ckpt_fail_prob=float(g("DSTPU_CHAOS_CKPT_FAIL_PROB", "0")),
            slow_steps=_parse_steps(g("DSTPU_CHAOS_SLOW_STEPS", "")),
            slow_prob=float(g("DSTPU_CHAOS_SLOW_PROB", "0")),
            slow_s=float(g("DSTPU_CHAOS_SLOW_S", "0")),
            die_step=int(g("DSTPU_CHAOS_DIE_STEP", "-1")),
            die_once=g("DSTPU_CHAOS_DIE_ONCE", "1") not in ("0", "false"),
            oom_step=int(g("DSTPU_CHAOS_OOM_STEP", "-1")),
            oom_once=g("DSTPU_CHAOS_OOM_ONCE", "1") not in ("0", "false"),
            comm_wedge_op=g("DSTPU_CHAOS_COMM_WEDGE_OP", ""),
            comm_wedge_call=int(g("DSTPU_CHAOS_COMM_WEDGE_CALL", "-1")),
            comm_wedge_once=g("DSTPU_CHAOS_COMM_WEDGE_ONCE", "1")
            not in ("0", "false"),
            comm_delay_op=g("DSTPU_CHAOS_COMM_DELAY_OP", ""),
            comm_delay_calls=_parse_steps(g("DSTPU_CHAOS_COMM_DELAY_CALLS", "")),
            comm_delay_prob=float(g("DSTPU_CHAOS_COMM_DELAY_PROB", "0")),
            comm_delay_s=float(g("DSTPU_CHAOS_COMM_DELAY_S", "0")),
            peer_dead_ranks=_parse_steps(g("DSTPU_CHAOS_PEER_DEAD_RANKS", "")),
            peer_dead_permanent_ranks=_parse_steps(
                g("DSTPU_CHAOS_PEER_DEAD_PERMANENT_RANKS", "")),
            **dict(zip(("serve_slow_tick_every", "serve_slow_tick_prob",
                        "serve_slow_tick_s"),
                       _parse_slow_tick(g("DSTPU_CHAOS_SERVE_SLOW_TICK",
                                          "")))),
            **dict(zip(("serve_kv_pressure_frac", "serve_kv_pressure_from",
                        "serve_kv_pressure_until"),
                       _parse_kv_pressure(g("DSTPU_CHAOS_SERVE_KV_PRESSURE",
                                            "")))),
            serve_poison_uid=int(g("DSTPU_CHAOS_SERVE_POISON_UID", "-1")),
            **dict(zip(("replica_kill_id", "replica_kill_tick"),
                       _parse_replica_kill(g("DSTPU_CHAOS_REPLICA_KILL",
                                             "")))),
            replica_kill_once=g("DSTPU_CHAOS_REPLICA_KILL_ONCE", "1")
            not in ("0", "false"),
        )


class ChaosInjectedIOError(OSError):
    """A checkpoint write failed by injection (distinguishable from a real
    I/O error in logs, indistinguishable to the retry machinery)."""


class ChaosInjectedOOMError(RuntimeError):
    """An injected RESOURCE_EXHAUSTED (distinguishable in logs; its message
    classifies as OOM to ``telemetry.memory.is_oom_error`` exactly like a
    real XLA allocation failure)."""


class ChaosInjectedPoisonError(RuntimeError):
    """An injected per-request engine-step fault. The message says
    "aborted" so ``comm.guard.classify_exception`` calls it TRANSIENT —
    the serving layer must route it to the poison-quarantine path, NOT the
    sticky degraded latch (that asymmetry is exactly what the drill
    proves)."""


class ChaosMonkey:
    """Stateless-roll injector; the only mutable state is bookkeeping
    counters so tests can assert exactly what fired."""

    def __init__(self, config: Optional[ChaosConfig] = None):
        self.config = config if config is not None else ChaosConfig.from_env()
        self.injected = {"nan": 0, "ckpt": 0, "slow": 0, "oom": 0,
                         "comm_wedge": 0, "comm_delay": 0,
                         "serve_slow_tick": 0, "serve_kv_pressure": 0,
                         "serve_poison": 0, "replica_kill": 0}
        self._serve_kv_pressure_on = False   # edge detector for the instant
        # pre-SIGKILL hook (serving flight recorder): SIGKILL is
        # uncatchable, so a replica's last chance to dump its black box is
        # a synchronous callback BEFORE os.kill — registered by the
        # serving layer, called with the due tick; its failure must never
        # save the victim (the drill's contract is that the process dies)
        self.on_replica_kill: Optional[callable] = None

    # ------------------------------------------------------------------
    def _roll(self, kind: str, step: int, salt: int = 0) -> float:
        """Deterministic uniform [0, 1) from (seed, kind, step, salt)."""
        h = hashlib.sha256(
            f"{self.config.seed}:{kind}:{step}:{salt}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2 ** 64

    # ------------------------------------------------------------------
    # nan grads
    # ------------------------------------------------------------------
    def nan_due(self, step: int) -> bool:
        c = self.config
        if step in c.nan_steps:
            return True
        if c.nan_every and step > 0 and step % c.nan_every == 0:
            return True
        return c.nan_prob > 0 and self._roll("nan", step) < c.nan_prob

    def corrupt_batch(self, batch, step: int):
        """Poison the first float leaf of the batch with a NaN so the loss
        and every grad it touches go non-finite — the same blast radius as
        a real data-pipeline/numerics fault (nothing engine-internal is
        patched, so the full detect/skip path is exercised)."""
        if not self.nan_due(step):
            return batch
        self.injected["nan"] += 1
        get_tracer().instant("chaos/nan", cat="resilience", step=step)
        logger.warning(f"chaos: injecting NaN into batch at step {step}")
        poisoned = [False]

        def poison(x):
            x = np.asarray(x)
            if not poisoned[0] and np.issubdtype(x.dtype, np.floating):
                x = np.array(x, copy=True)
                x.reshape(-1)[0] = np.nan
                poisoned[0] = True
            return x

        import jax
        batch = jax.tree.map(poison, batch)
        if not poisoned[0]:
            logger.warning("chaos: batch has no float leaf; NaN injection "
                           "skipped (integer-only inputs)")
        return batch

    # ------------------------------------------------------------------
    # checkpoint I/O
    # ------------------------------------------------------------------
    def ckpt_io_check(self, step: int, attempt: int) -> None:
        """Raise ``ChaosInjectedIOError`` when this save attempt is chosen
        to fail. ``attempt`` is 0-based within one logical save."""
        c = self.config
        fail = attempt < c.ckpt_fail_first or (
            c.ckpt_fail_prob > 0
            and self._roll("ckpt", step, salt=attempt) < c.ckpt_fail_prob)
        if fail:
            self.injected["ckpt"] += 1
            get_tracer().instant("chaos/ckpt_io_fail", cat="resilience",
                                 step=step, attempt=attempt)
            raise ChaosInjectedIOError(
                f"chaos: injected checkpoint I/O failure "
                f"(step {step}, attempt {attempt})")

    # ------------------------------------------------------------------
    # slow / hung steps
    # ------------------------------------------------------------------
    def maybe_stall(self, step: int) -> float:
        c = self.config
        due = step in c.slow_steps or (
            c.slow_prob > 0 and self._roll("slow", step) < c.slow_prob)
        if due and c.slow_s > 0:
            self.injected["slow"] += 1
            logger.warning(f"chaos: stalling step {step} for {c.slow_s:.2f}s")
            time.sleep(c.slow_s)
            get_tracer().complete("chaos/stall", c.slow_s, cat="resilience",
                                  step=step)
            return c.slow_s
        return 0.0

    # ------------------------------------------------------------------
    # comm faults (CommGuard asks per guarded call; Heartbeat per publish)
    # ------------------------------------------------------------------
    @staticmethod
    def _op_match(pattern: str, op: str) -> bool:
        return pattern in ("", "*") or pattern == op

    def comm_fault(self, op: str, call_index: int) -> Optional[str]:
        """``"wedge"`` / ``"delay"`` / None for one guarded comm op.
        Wedge wins over delay (it is the fault being drilled); a
        relaunched worker (DSTPU_RESUME set) is spared the wedge under
        ``comm_wedge_once`` so the abort→restart→resume loop completes."""
        c = self.config
        if (c.comm_wedge_call >= 0 and call_index == c.comm_wedge_call
                and self._op_match(c.comm_wedge_op, op)
                and not (c.comm_wedge_once and os.environ.get("DSTPU_RESUME"))):
            self.injected["comm_wedge"] += 1
            get_tracer().instant("chaos/comm_wedge", cat="resilience", op=op,
                                 call=call_index)
            logger.warning(f"chaos: wedging guarded comm op '{op}' "
                           f"(call #{call_index})")
            return "wedge"
        if c.comm_delay_s > 0 and self._op_match(c.comm_delay_op, op):
            due = call_index in c.comm_delay_calls or (
                c.comm_delay_prob > 0
                and self._roll("comm_delay", call_index) < c.comm_delay_prob)
            if due:
                self.injected["comm_delay"] += 1
                get_tracer().instant("chaos/comm_delay", cat="resilience",
                                     op=op, call=call_index,
                                     delay_s=c.comm_delay_s)
                logger.warning(f"chaos: delaying guarded comm op '{op}' "
                               f"{c.comm_delay_s:.2f}s (call #{call_index})")
                return "delay"
        return None

    def peer_dead(self, rank: int) -> bool:
        """True when this rank's heartbeat is chaos-silenced (the
        membership view will see its file go stale — a simulated dead
        peer with no unpublish protocol to cheat through).

        Ranks in ``peer_dead_ranks`` are spared on a DSTPU_RESUME relaunch
        (the once-contract the die/wedge knobs already follow — the
        transient-loss drill); ``peer_dead_permanent_ranks`` never come
        back, across any number of relaunches — the permanent-capacity-loss
        drill the elastic shrink path is accepted against."""
        if rank in self.config.peer_dead_permanent_ranks:
            return True
        return (rank in self.config.peer_dead_ranks
                and not os.environ.get("DSTPU_RESUME"))

    # ------------------------------------------------------------------
    # device OOM (catchable RESOURCE_EXHAUSTED)
    # ------------------------------------------------------------------
    def maybe_oom(self, step: int) -> None:
        """Raise a RESOURCE_EXHAUSTED-shaped error at ``oom_step`` — the
        XLA message shape the dsmem classifier keys on, injected at the
        host layer so the whole forensics path (engine classification →
        ledger + samples stash → runner oom bundle) is exercised without
        actually exhausting a device. ``oom_once`` spares DSTPU_RESUME
        relaunches, mirroring ``die_once``."""
        if self.config.oom_step < 0 or step != self.config.oom_step:
            return
        if self.config.oom_once and os.environ.get("DSTPU_RESUME"):
            return
        self.injected["oom"] += 1
        get_tracer().instant("chaos/oom", cat="resilience", step=step)
        logger.warning(f"chaos: injecting RESOURCE_EXHAUSTED at step {step}")
        raise ChaosInjectedOOMError(
            f"RESOURCE_EXHAUSTED: chaos-injected out of memory allocating "
            f"16.00G at step {step} (fake buffer dump: this is the dsmem "
            "forensics drill)")

    # ------------------------------------------------------------------
    # serving-tick faults (serving/server.py asks per serve tick)
    # ------------------------------------------------------------------
    def serve_slow_tick(self, tick: int) -> float:
        """Stall this serve tick when due (cadence or sha-rolled
        probability); returns the injected stall seconds."""
        c = self.config
        if c.serve_slow_tick_s <= 0:
            return 0.0
        due = bool(c.serve_slow_tick_every and tick > 0
                   and tick % c.serve_slow_tick_every == 0)
        if not due and c.serve_slow_tick_prob > 0:
            due = self._roll("serve_slow", tick) < c.serve_slow_tick_prob
        if not due:
            return 0.0
        self.injected["serve_slow_tick"] += 1
        logger.warning(f"chaos: stalling serve tick {tick} for "
                       f"{c.serve_slow_tick_s:.3f}s")
        time.sleep(c.serve_slow_tick_s)
        get_tracer().complete("chaos/serve_slow_tick", c.serve_slow_tick_s,
                              cat="resilience", tick=tick)
        return c.serve_slow_tick_s

    def serve_kv_pressure(self, tick: int) -> float:
        """Fraction of usable KV blocks stolen at this tick (0 outside the
        configured window). Window edges emit a chaos instant so the whole
        pressure episode is reconstructible from the trace."""
        c = self.config
        if c.serve_kv_pressure_frac <= 0:
            return 0.0
        on = tick >= c.serve_kv_pressure_from and (
            c.serve_kv_pressure_until < 0
            or tick < c.serve_kv_pressure_until)
        if on != self._serve_kv_pressure_on:
            self._serve_kv_pressure_on = on
            if on:
                self.injected["serve_kv_pressure"] += 1
            get_tracer().instant("chaos/serve_kv_pressure", cat="resilience",
                                 tick=tick, state="on" if on else "off",
                                 frac=c.serve_kv_pressure_frac)
            logger.warning(
                f"chaos: serve KV pressure {'ON' if on else 'OFF'} at tick "
                f"{tick} (stealing {c.serve_kv_pressure_frac:.0%} of blocks)")
        return c.serve_kv_pressure_frac if on else 0.0

    def maybe_poison_serve(self, uids) -> None:
        """Raise when the poisoned request uid is resident in this engine
        step — a per-request transient engine fault the serving layer must
        isolate (evict + retry + quarantine), never latch degraded on."""
        uid = self.config.serve_poison_uid
        if uid < 0 or uid not in uids:
            return
        self.injected["serve_poison"] += 1
        get_tracer().instant("chaos/serve_poison", cat="resilience", uid=uid)
        logger.warning(f"chaos: poisoning engine step (request uid {uid})")
        raise ChaosInjectedPoisonError(
            f"chaos: poisoned request {uid} aborted the engine step")

    def maybe_kill_replica(self, tick: int, mid_decode: bool) -> None:
        """SIGKILL this serving replica when it is the configured victim
        and the due tick has arrived WITH decode work in flight
        (``mid_decode``) — the drill's contract is death mid-decode, so
        there are live streams for the router to fail over, never an idle
        process quietly disappearing. The victim is selected by
        ``DSTPU_REPLICA_ID`` (set by the fleet launcher); the due tick is
        sha-rolled from the seed when not pinned; ``replica_kill_once``
        spares the DSTPU_RESUME relaunch (die-once contract)."""
        c = self.config
        if c.replica_kill_id < 0 or not mid_decode:
            return
        try:
            rid = int(os.environ.get(REPLICA_ID_ENV, "-1") or "-1")
        except ValueError:
            return
        if rid != c.replica_kill_id:
            return
        due = c.replica_kill_tick or 1 + int(self._roll("replica_kill",
                                                        rid) * 32)
        if tick < due:
            return
        if c.replica_kill_once and os.environ.get("DSTPU_RESUME"):
            return
        self.injected["replica_kill"] += 1
        logger.warning(f"chaos: SIGKILL replica {rid} at serve tick {tick}")
        # breadcrumb only: SIGKILL is uncatchable — the router learns of
        # the death from its broken streams + healthz, which is the drill
        get_tracer().instant("chaos/replica_kill", cat="resilience",
                             tick=tick, replica=rid)
        if self.on_replica_kill is not None:
            try:
                self.on_replica_kill(tick)
            except Exception:
                logger.exception("chaos: pre-kill flight hook failed "
                                 "(the kill proceeds regardless)")
        os.kill(os.getpid(), signal.SIGKILL)

    # ------------------------------------------------------------------
    # worker death
    # ------------------------------------------------------------------
    def maybe_die(self, step: int) -> None:
        if self.config.die_step < 0 or step < self.config.die_step:
            return
        if self.config.die_once and os.environ.get("DSTPU_RESUME"):
            # this worker is a post-kill relaunch: let it live so the
            # restart+resume path actually completes
            return
        logger.warning(f"chaos: SIGKILL self at step {step}")
        # breadcrumb only: SIGKILL is uncatchable, so no dump follows — a
        # relaunched worker's trace starts fresh
        get_tracer().instant("chaos/die", cat="resilience", step=step)
        os.kill(os.getpid(), signal.SIGKILL)


def monkey_from_env() -> Optional[ChaosMonkey]:
    """A ``ChaosMonkey`` when any DSTPU_CHAOS_* knob is set, else None."""
    cfg = ChaosConfig.from_env()
    return ChaosMonkey(cfg) if cfg.active else None
