"""``FaultTolerantRunner`` — the fault-tolerant training loop.

Wraps a live ``DeepSpeedTPUEngine`` and hardens every step against the four
ways long TPU runs die (reference: the DeepSpeed engine treats skip-step,
checkpoint commit, and restart-with-resume as core engine duties):

  bad numerics   : step guard (engine-level skip + lr backoff + quarantine)
  preemption     : SIGTERM/SIGINT -> atomic autosave at the step boundary
  torn/flaky I/O : retry-with-backoff saves; only *committed* (manifest-
                   verified) checkpoints are ever resumed
  hung steps     : watchdog thread -> diagnostics snapshot + escalation

Typical worker::

    runner = FaultTolerantRunner(engine, save_dir=args.ckpt)
    runner.resume_from_latest()            # no-op on a fresh run (or use
                                           # maybe_resume() to resume only on
                                           # agent relaunches: DSTPU_RESUME)
    result = runner.run(num_steps=N, batch_fn=lambda step: next_batch(step))
    sys.exit(result.exit_code)             # classified status: 0 completed,
                                           # 128+sig preempted, 75 comm fault
                                           # — the elastic agent relaunches
                                           # non-zero exits with
                                           # DSTPU_RESUME=latest, for free

Chaos testing: pass a ``ChaosMonkey`` (or set DSTPU_CHAOS_* env knobs) and
the runner injects NaN batches, checkpoint I/O failures, stalls, and worker
death deterministically — the tier-1 chaos suite drives every recovery path
this module owns.
"""

import collections
import faulthandler
import json
import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, Optional

import jax

from deepspeed_tpu.comm.guard import (COMM_FAULT_EXIT_CODE, CommFaultError,
                                      CommGuard, CommPeerLostError,
                                      clear_active_guard, set_active_guard)
from deepspeed_tpu.resilience import checkpointing as ckpt
from deepspeed_tpu.resilience.membership import (Heartbeat, MembershipView,
                                                 StragglerDetector,
                                                 default_membership_dir)
from deepspeed_tpu.resilience.chaos import ChaosMonkey, monkey_from_env
from deepspeed_tpu.resilience.config import (ResilienceConfig,
                                             resolve_resilience_config)
from deepspeed_tpu.resilience.guards import (BadStepError, QuarantineError,
                                             StepGuard)
from deepspeed_tpu.resilience.watchdog import TRACE_TAIL_S, StepWatchdog
from deepspeed_tpu.telemetry.memory import is_oom_error
from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.logging import logger

_CLIENT_STATE_KEY = "_resilience"


@dataclass
class RunResult:
    steps_completed: int = 0
    # completed | preempted | watchdog | comm_fault
    stop_reason: str = "completed"
    last_loss: Optional[float] = None
    saved_tags: list = field(default_factory=list)
    # the signal that caused a "preempted" stop (SIGTERM/SIGINT), when known
    preempt_signal: Optional[int] = None

    @property
    def preempted(self) -> bool:
        """True when the agent should relaunch this worker with resume —
        the platform's fault (preemption/hang/comm wedge), not the code's."""
        return self.stop_reason in ("preempted", "watchdog", "comm_fault")

    @property
    def exit_code(self) -> int:
        """The classified exit status a worker should use (the module
        docstring's ``sys.exit(result.exit_code)`` idiom): comm faults get
        ``COMM_FAULT_EXIT_CODE`` (75) and preemption/watchdog stops the
        128+signal shell convention (default 143 = SIGTERM) — both land in
        the elastic agent's free-relaunch classes
        (``comm_fault_exit_codes`` / ``preemption_exit_codes``) so restart
        accounting treats them like preemptions, not budgeted crashes."""
        if self.stop_reason == "comm_fault":
            return COMM_FAULT_EXIT_CODE
        if self.stop_reason in ("preempted", "watchdog"):
            return 128 + (self.preempt_signal or signal.SIGTERM)
        return 0


class FaultTolerantRunner:
    def __init__(self, engine, save_dir: str,
                 config: Optional[ResilienceConfig] = None,
                 chaos: Optional[ChaosMonkey] = None,
                 install_signal_handlers: bool = True):
        self.engine = engine
        self.save_dir = os.path.abspath(save_dir)
        self.cfg = config if config is not None \
            else resolve_resilience_config(engine)
        self.chaos = chaos if chaos is not None else monkey_from_env()
        self.client_state: Dict[str, Any] = {}

        self.guard = StepGuard(engine, self.cfg.step_guard)
        self.autosaver = ckpt.Autosaver(self.cfg.autosave.every_steps,
                                        self.cfg.autosave.every_seconds)
        self.watchdog: Optional[StepWatchdog] = None
        # set from the watchdog monitor thread, read by the main loop
        self._watchdog_stop = threading.Event()
        if self.cfg.watchdog.enabled:
            self.watchdog = StepWatchdog(
                self.cfg.watchdog, diagnostics_dir=self.cfg.diagnostics_dir,
                on_flag=self._on_watchdog_flag,
                context_fn=self._watchdog_context).start()

        # comm fault-tolerance (the "comm_guard" config group): a CommGuard
        # for the engine's eager collectives, a heartbeat publishing this
        # worker's liveness + last comm op, and a membership view the step
        # boundary polls — a lost peer becomes CommPeerLostError BEFORE the
        # next collective wedges on it
        self.comm_guard: Optional[CommGuard] = None
        self.heartbeat: Optional[Heartbeat] = None
        self.membership: Optional[MembershipView] = None
        self.straggler: Optional[StragglerDetector] = None
        self._straggler_eid = 0        # last dstrace event id already judged
        gc = getattr(getattr(engine, "config", None), "comm_guard", None)
        if gc is not None and gc.enabled:
            self.comm_guard = CommGuard(gc, chaos=self.chaos)
            # the facade's eager host-driven ops (device_broadcast, ...)
            # route through the active guard with no caller change — the
            # chaos comm drill works against an unmodified training script
            set_active_guard(self.comm_guard)
            self.straggler = StragglerDetector(gc.straggler_factor,
                                               gc.straggler_min_s)
            mdir = gc.membership_dir or default_membership_dir()
            rank = jax.process_index()
            self.heartbeat = Heartbeat(
                rank, mdir, interval_s=gc.heartbeat_interval_s,
                chaos=self.chaos).start()
            expected = range(jax.process_count()) \
                if jax.process_count() > 1 else None
            self.membership = MembershipView(
                mdir, lost_after_s=gc.lost_after_s, expected_ranks=expected)

        self.history = collections.deque(maxlen=self.cfg.history_steps)
        self._last_host: Dict[str, Any] = {}
        self._dispatch_durations: Dict[int, float] = {}
        self.saved_tags: list = []
        self._comm_fault: Optional[CommFaultError] = None
        self._preempt_signal: Optional[int] = None
        self._preemption_saved = False
        self._closed = False
        self._old_handlers: Dict[int, Any] = {}
        if install_signal_handlers:
            self._install_signal_handlers()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def _install_signal_handlers(self):
        if threading.current_thread() is not threading.main_thread():
            logger.warning("resilience: not the main thread; SIGTERM/SIGINT "
                           "autosave handlers not installed")
            return
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._old_handlers[sig] = signal.signal(sig, self._on_signal)
            except (ValueError, OSError):    # exotic embedding contexts
                pass

    def _on_signal(self, signum, frame):
        # async-signal context: set the flag only; the save happens at the
        # step boundary (a save from inside a handler could re-enter orbax
        # mid-write — the torn-checkpoint case this subsystem exists to kill)
        # dslint: disable=DS004 -- handler runs ON the main thread between
        # bytecodes; taking a lock here could deadlock against the code it
        # interrupted, so a GIL-atomic int store is the only safe write
        self._preempt_signal = signum
        # fanout=False = append-only breadcrumb (no sink, no I/O, no locks)
        # — the signal-safe emission form; the trace itself is dumped later,
        # at the step-boundary autosave, never from handler context
        get_tracer().instant("resilience/preempt_signal", cat="resilience",
                             fanout=False, signum=signum)
        # dslint: disable=DS005 -- one best-effort log line: logging's RLock
        # is re-entrant on this same (main) thread, and operators need the
        # "preemption acknowledged" breadcrumb exactly at signal time
        logger.warning(f"resilience: caught signal {signum}; autosave + "
                       f"clean stop at the next step boundary")

    def close(self):
        if self._closed:
            return
        self._closed = True
        # drain any leftover deferred metrics (guard errors are logged, not
        # raised — close() must always complete)
        try:
            self.flush(raise_guard=False)
        except Exception:
            logger.exception("resilience: final metric drain failed")
        self.guard.detach()            # engine regains default NaN semantics
        if self.comm_guard is not None:
            clear_active_guard(self.comm_guard)
        if self.heartbeat is not None:
            self.heartbeat.stop()
        if self.watchdog is not None:
            self.watchdog.stop()
        for sig, old in self._old_handlers.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        from deepspeed_tpu.checkpoint.engine import wait_pending_checkpoint
        wait_pending_checkpoint(self.engine)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    # properties
    # ------------------------------------------------------------------
    @property
    def preempted(self) -> bool:
        return self._preempt_signal is not None

    @property
    def should_stop(self) -> bool:
        return self.preempted

    # ------------------------------------------------------------------
    # checkpointing
    # ------------------------------------------------------------------
    def save(self, tag: Optional[str] = None, reason: str = "manual") -> str:
        """Checkpoint with retry; the runner's own state (guard backoff,
        autosave cadence) rides in ``client_state`` so recovery behavior
        survives the restart too. The async-pipeline ring is drained and
        guard-replayed FIRST — a quarantine hiding in the un-drained window
        raises here, before anything is snapshotted, so committed
        checkpoints never capture un-guarded steps."""
        self.flush()
        state = dict(self.client_state)
        state[_CLIENT_STATE_KEY] = {
            "guard": self.guard.state_dict(),
            "autosave": self.autosaver.state_dict(),
            "reason": reason,
        }
        path = ckpt.save_with_retry(
            self.engine, self.save_dir, tag=tag, client_state=state,
            retries=self.cfg.autosave.io_retries,
            backoff_s=self.cfg.autosave.io_backoff_s,
            chaos=self.chaos)
        self.autosaver.mark_saved(self.engine.global_steps)
        self.saved_tags.append(os.path.basename(path))
        if self.cfg.autosave.keep_last:
            ckpt.prune_checkpoints(self.save_dir, self.cfg.autosave.keep_last)
        logger.info(f"resilience: checkpoint saved ({reason}) -> {path}")
        self._export_monitor_events()
        return path

    def maybe_resume(self) -> Optional[str]:
        """Resume iff this worker is an elastic-agent relaunch (the agent
        sets ``DSTPU_RESUME=latest`` on every relaunch env) — the one-line
        startup call that makes a training script restart-safe. Returns the
        restored tag, or None (fresh launch / nothing committed)."""
        if not os.environ.get("DSTPU_RESUME"):
            return None
        return self.resume_from_latest()

    def resume_from_latest(self, load_optimizer_states: bool = True
                           ) -> Optional[str]:
        """Restore from the newest committed checkpoint (never a torn one);
        returns the tag, or None for a fresh start. Engine step counter, lr
        schedule, loss scale, data schedules, and the runner's guard state
        all come back."""
        tag, client_state = ckpt.resume_from_latest(
            self.engine, self.save_dir,
            load_optimizer_states=load_optimizer_states)
        rs = client_state.pop(_CLIENT_STATE_KEY, None) or {}
        if rs.get("guard"):
            self.guard.load_state_dict(rs["guard"])
        if rs.get("autosave"):
            self.autosaver.load_state_dict(rs["autosave"])
        self.autosaver.mark_saved(self.engine.global_steps)
        self.client_state = client_state
        return tag

    # ------------------------------------------------------------------
    # the hardened step
    # ------------------------------------------------------------------
    def step(self, batch: Any = None,
             data_iter: Optional[Iterator] = None) -> jax.Array:
        """One guarded ``engine.train_batch``. Raises ``BadStepError`` /
        ``QuarantineError`` per the step-guard policy (with a diagnostic
        bundle written first); after a preemption signal the step completes,
        an autosave commits, and ``should_stop`` turns True.

        With the engine's async step pipeline enabled, step outputs are
        consumed from the drained metric ring instead of a per-step device
        fetch: the guard observes steps with up to ``sync_every`` steps of
        detection lag (replayed in order), and every save boundary forces a
        flush first so checkpoints never capture un-guarded steps. Params
        stay clean regardless of the lag — the engine's on-device skip drops
        bad updates at the step they happen."""
        if self._closed:
            raise RuntimeError("runner is closed")
        self._check_peers()
        engine = self.engine
        step_idx = engine.global_steps
        batch, stacked, feed_iter = self._prepare_batch(batch, data_iter,
                                                        step_idx)
        if self.chaos is not None:
            self.chaos.maybe_die(step_idx)
        if self.watchdog is not None:
            self.watchdog.begin_step(step_idx)
        t0 = time.monotonic()
        try:
            if self.chaos is not None:
                # inside the watchdog window: a chaos stall IS a hung step
                self.chaos.maybe_stall(step_idx)
                # the dsmem drill: a RESOURCE_EXHAUSTED-shaped raise that
                # exercises classify -> forensics -> oom bundle end to end
                self.chaos.maybe_oom(step_idx)
            loss = engine.train_batch(batch=batch, data_iter=feed_iter,
                                      stacked=stacked)
        finally:
            if self.watchdog is not None:
                self.watchdog.end_step()
        duration = time.monotonic() - t0
        if getattr(engine, "_async_enabled", False):
            # deferred readback: the engine drains its ring every sync_every
            # steps; replay whatever landed (possibly nothing this step)
            self._dispatch_durations[step_idx] = duration
            self._consume_drained()
        else:
            metrics = getattr(engine, "_last_metrics", {})
            # ONE host transfer for everything the host-side policy layer
            # needs (guard verdict, history ring, run()'s last_loss)
            fetch = {"loss": loss}
            for k in ("lr", "grad_norm", "overflow"):
                if metrics.get(k) is not None:
                    fetch[k] = metrics[k]
            host = self._last_host = jax.device_get(fetch)
            self._record_history(step_idx, host, duration)
            self._observe_guarded(host["loss"], host)
        self._maybe_save(engine.global_steps)
        return loss

    def _check_peers(self):
        """Step-boundary membership poll (the view throttles itself to half
        the lost_after window so the file reads stay off the hot cadence):
        a stale peer heartbeat raises ``CommPeerLostError`` HERE, on the
        host, instead of letting the next collective wedge on the dead rank
        forever."""
        if self.membership is None:
            return
        lost = self.membership.poll_lost()
        if lost is None:               # throttled — no scan this step
            return
        self._judge_stragglers()
        if lost:
            # elastic/ family instant: the worker-side start of the
            # loss -> autosave -> shrink -> resume episode (the agent stamps
            # shrink_planned/regrow; ckpt load stamps reshard) — the whole
            # sequence reconstructs from one timeline
            get_tracer().instant("elastic/peer_lost", cat="elastic",
                                 ranks=list(lost),
                                 step=self.engine.global_steps,
                                 lost_after_s=self.membership.lost_after_s)
            raise CommPeerLostError(
                f"peer rank(s) {lost} lost (heartbeat stale past "
                f"{self.membership.lost_after_s:.1f}s)", ranks=lost)

    def _judge_stragglers(self):
        """Feed fresh rank-tagged dstrace comm spans (e.g. the MULTICHIP
        harness's merged per-rank timings) to the config-tuned straggler
        detector (``straggler_factor`` / ``straggler_min_s``). Each event id
        is judged exactly once — overlapping tail windows never double-count
        an outlier."""
        if self.straggler is None:
            return
        tracer = get_tracer()
        if not tracer.enabled:
            return
        fresh = [e for e in tracer.tail(self.comm_guard.cfg.trace_tail_s)
                 if e[0] > self._straggler_eid]
        if fresh:
            self._straggler_eid = max(e[0] for e in fresh)
            self.straggler.ingest_spans(fresh)

    def _observe_guarded(self, loss, host: Dict[str, Any]):
        """guard.observe with the runner's bundle-on-raise contract."""
        try:
            if self.guard.observe(loss, host):
                self._export_monitor_events()
        except (QuarantineError, BadStepError) as e:
            bundle = self.write_diagnostic_bundle(
                "quarantine" if isinstance(e, QuarantineError) else "abort",
                error=e)
            if isinstance(e, QuarantineError):
                e.bundle_path = bundle
            raise

    def _consume_drained(self, raise_guard: bool = True) -> int:
        """Replay newly drained async-pipeline entries IN ORDER through the
        history ring and the step guard (bounded lag: entries arrive at most
        ``sync_every`` steps after their step ran). Returns the number of
        entries consumed."""
        take = getattr(self.engine, "take_drained_metrics", None)
        if take is None:
            return 0
        entries = take()
        for i, e in enumerate(entries):
            # ring entries carry the post-step global step; history keys by
            # the pre-step index (same convention as the synchronous path)
            pre_idx = int(e.get("step", self.engine.global_steps)) - 1
            duration = self._dispatch_durations.pop(pre_idx, None)
            self._record_history(pre_idx, e, duration)
            self._last_host = e
            try:
                self._observe_guarded(e.get("loss"), e)
            except (QuarantineError, BadStepError):
                if raise_guard:
                    # the unjudged tail goes back to the engine's queue so a
                    # later flush/save still replays it through the guard —
                    # nothing escapes judgment because an earlier entry blew up
                    self.engine.requeue_drained_metrics(entries[i + 1:])
                    raise
                logger.exception(
                    "resilience: guard raised during final drain")
        return len(entries)

    def flush(self, raise_guard: bool = True) -> int:
        """Force-drain the engine's deferred metric ring and replay it
        through the guard/history — the barrier ``save()`` and ``run()``
        use so no checkpoint or RunResult ever reflects un-guarded steps."""
        if hasattr(self.engine, "flush_metrics"):
            self.engine.flush_metrics()
        return self._consume_drained(raise_guard=raise_guard)

    def _prepare_batch(self, batch, data_iter, step_idx):
        """Materialize the step's batch (pulling gas microbatches when an
        iterator is given) and run chaos NaN injection on the result.

        With the engine's prefetch enabled and NO chaos monkey, the iterator
        is handed through untouched (third return value) so the engine's
        background staging engages — chaos batch corruption needs the host
        batch materialized here, so chaos runs keep the inline path."""
        stacked = None
        if batch is None:
            if data_iter is None:
                raise ValueError("step() needs batch or data_iter")
            if self.chaos is None and \
                    getattr(self.engine, "_prefetch_enabled", False):
                return None, None, data_iter
            batch = self.engine.stack_microbatches(
                data_iter, self.engine.gradient_accumulation_steps)
            stacked = True
        if self.chaos is not None:
            batch = self.chaos.corrupt_batch(batch, step_idx)
        return batch, stacked, None

    def _maybe_save(self, step: int):
        if self.preempted:
            if (self.cfg.autosave.save_on_preemption
                    and not self._preemption_saved):
                self._preemption_saved = True
                self.save(reason="preemption")
            return
        if self.autosaver.due(step):
            self.save(reason="autosave")

    def _record_history(self, step, host, duration):
        def f(v):
            try:
                # dslint: disable=DS002 -- host dict values: step() device_gets (sync) or drains (async) first
                return float(v) if v is not None else None
            except (TypeError, ValueError):
                return None
        self.history.append({
            "step": step, "loss": f(host.get("loss")),
            # async pipeline: per-step host duration is DISPATCH time (the
            # reconciled step time lives in the engine's TRAIN_BATCH_TIMER)
            "duration_s": round(duration, 4) if duration is not None else None,
            "lr": f(host.get("lr")), "grad_norm": f(host.get("grad_norm")),
            "overflow": bool(host["overflow"]) if host.get("overflow")
            is not None else None,
        })

    # ------------------------------------------------------------------
    # the loop
    # ------------------------------------------------------------------
    def run(self, num_steps: int, batch_fn=None,
            data_iter: Optional[Iterator] = None) -> RunResult:
        """Train for up to ``num_steps`` further global steps, stopping
        early (after a committed autosave) on preemption or a watchdog
        interrupt. ``batch_fn(global_step) -> batch`` or ``data_iter``
        supplies data."""
        result = RunResult()
        target = self.engine.global_steps + int(num_steps)
        while self.engine.global_steps < target:
            try:
                # the whole loop body is covered: a KeyboardInterrupt landing
                # in batch_fn or the loop head (watchdog interrupt_main
                # without installed handlers, bare Ctrl-C) still gets the
                # preemption contract — autosave + clean stop, never an
                # escape without a RunResult
                if self.should_stop:
                    result.stop_reason = self._stop_reason()
                    break
                batch = batch_fn(self.engine.global_steps) if batch_fn \
                    else None
                self.step(batch=batch, data_iter=data_iter)
            except KeyboardInterrupt:
                self._preempt_signal = signal.SIGINT
                self._maybe_save(self.engine.global_steps)
                result.stop_reason = self._stop_reason()
                break
            except CommFaultError as e:
                self._handle_comm_fault(e, result)
                break
            except Exception as e:
                # OOM forensics (dsmem): a RESOURCE_EXHAUSTED means the
                # device cannot run THIS config — bundle the evidence
                # (ledger + live samples + per-phase deltas + trace tail)
                # and re-raise; unlike a preemption there is nothing to
                # resume into, the config itself must change (the bundle's
                # ledger says which component to offload/shard)
                if is_oom_error(e):
                    logger.error(f"resilience: OOM at step "
                                 f"{self.engine.global_steps}: "
                                 f"{str(e).splitlines()[0]}")
                    self.write_diagnostic_bundle("oom", error=e)
                    raise
                # a raw collective failure (the fabric noticed the dead
                # peer before the membership poll did — gloo/ICI surfaces
                # connection errors mid-step): consult membership; a
                # confirmed lost peer reclassifies this as a comm fault so
                # the worker exits 75 (free relaunch, shrinkable) instead
                # of charging the crash budget for the platform's fault
                lost = self._peer_loss_after_error(e)
                if lost is None:
                    raise
                self._handle_comm_fault(CommPeerLostError(
                    f"peer rank(s) {lost} lost (collective failed with "
                    f"{type(e).__name__}: {str(e).splitlines()[0][:200]}; "
                    f"heartbeat confirms)", ranks=lost), result)
                break
            result.steps_completed += 1
            if "loss" in self._last_host:
                result.last_loss = float(self._last_host["loss"])
        else:
            if self.should_stop:
                result.stop_reason = self._stop_reason()
        # final drain: the tail of the async ring reaches the guard/history
        # before the RunResult is reported (and before any preemption save)
        self.flush()
        if self.should_stop and not self._preemption_saved \
                and self.cfg.autosave.save_on_preemption:
            self._preemption_saved = True
            self.save(reason="preemption")
        if "loss" in self._last_host:
            result.last_loss = float(self._last_host["loss"])
        result.preempt_signal = self._preempt_signal
        result.saved_tags = list(self.saved_tags)
        return result

    def _handle_comm_fault(self, e: CommFaultError, result: RunResult):
        """Coordinated recovery (the comm guard detected a wedge or peer
        loss): the communicator is suspect but this host is healthy, so
        drain the async ring WITHOUT letting a guard verdict mask the
        primary fault, bundle the evidence, commit an autosave where one
        is still possible, and stop with a classified reason — the worker
        exits COMM_FAULT_EXIT_CODE and the elastic agent relaunches it
        for free (preemption-style accounting, shrinkable on permanent
        loss)."""
        self._comm_fault = e
        logger.error(f"resilience: comm fault at step "
                     f"{self.engine.global_steps}: {e}")
        get_tracer().instant("resilience/comm_fault", cat="resilience",
                             step=self.engine.global_steps,
                             op=e.op, outcome=e.outcome.value)
        self.write_diagnostic_bundle("comm_fault", error=e)
        self.flush(raise_guard=False)
        if isinstance(e, CommPeerLostError) and jax.process_count() > 1:
            # a multi-process checkpoint save is a collective — it cannot
            # commit without the dead rank's participation and would wedge
            # this (healthy) survivor. The last committed periodic
            # autosave is the resume point; the shrunk relaunch restores
            # it mesh-portably at the surviving world.
            logger.warning(
                "resilience: peer lost at world > 1 — skipping the "
                "comm-fault autosave (a collective save cannot commit "
                "without the dead rank); the last committed autosave is "
                "the resume point")
        else:
            self.save(reason="comm_fault")
        result.stop_reason = "comm_fault"

    def _peer_loss_after_error(self, e: BaseException):
        """After a raw step/collective failure: is a peer actually gone?
        Only consulted for comm-shaped (TRANSIENT-classified) errors with
        real multi-process membership; polls the store up to the staleness
        horizon (the dead rank's file needs that long to age) and returns
        the lost ranks, or None (the error was not peer loss — re-raise)."""
        if self.membership is None or jax.process_count() <= 1:
            return None
        from deepspeed_tpu.comm.guard import CommOutcome, classify_exception
        if classify_exception(e) is CommOutcome.FATAL:
            return None
        deadline = time.monotonic() + self.membership.lost_after_s + 1.0
        while time.monotonic() < deadline:
            lost = self.membership.lost_peers()
            if lost:
                return lost
            time.sleep(0.1)
        return None

    def _on_watchdog_flag(self, event):
        # only an interrupt-policy flag stops the run; a warn-policy flag
        # earlier in the run must not relabel a later real preemption
        if self.cfg.watchdog.policy == "interrupt":
            self._watchdog_stop.set()

    def _stop_reason(self) -> str:
        return "watchdog" if self._watchdog_stop.is_set() else "preempted"

    def _export_monitor_events(self):
        """Resilience observability through the engine's monitor fan-out
        (exported on the rare events — bad steps and saves — not per step)."""
        mon = getattr(self.engine, "monitor", None)
        if mon is None or not mon.enabled:
            return
        samples = self.engine.global_samples
        try:
            mon.write_events([
                ("Train/Resilience/skipped_steps",
                 float(self.engine.skipped_steps), samples),
                ("Train/Resilience/consecutive_bad",
                 float(self.guard.consecutive_bad), samples),
                ("Train/Resilience/lr_scale",
                 float(self.guard.lr_scale), samples),
                ("Train/Resilience/checkpoints_saved",
                 float(len(self.saved_tags)), samples),
            ])
        except Exception:
            logger.exception("resilience: monitor export failed")

    # ------------------------------------------------------------------
    # diagnostics
    # ------------------------------------------------------------------
    def _watchdog_context(self) -> dict:
        engine = self.engine
        ctx = {"global_steps": engine.global_steps,
               "global_samples": engine.global_samples,
               "history_tail": list(self.history)[-5:]}
        if self.chaos is not None:
            ctx["chaos_injected"] = dict(self.chaos.injected)
        return ctx

    def write_diagnostic_bundle(self, reason: str,
                                error: Optional[BaseException] = None) -> str:
        """Everything an oncall needs from a dead run, in one directory:
        the failure reason, the recent step history (loss/lr/grad-norm/
        overflow per step), engine counters, resilience config, chaos
        bookkeeping, and live stacks of every thread."""
        engine = self.engine
        d = os.path.join(self.cfg.diagnostics_dir,
                         f"{reason}_step{engine.global_steps}")
        os.makedirs(d, exist_ok=True)
        diag = {
            "reason": reason,
            "error": repr(error) if error is not None else None,
            "global_steps": engine.global_steps,
            "global_samples": engine.global_samples,
            "skipped_steps": engine.skipped_steps,
            "guard": self.guard.state_dict(),
            "config": self.cfg.model_dump(),
            "history": list(self.history),
            "chaos_injected": dict(self.chaos.injected)
            if self.chaos is not None else None,
        }
        if isinstance(error, CommFaultError):
            # the comm-span tail rides in diag.json too (not only in the
            # Perfetto trace slice): a wedge diagnosis must survive even
            # when tracing was off and trace_tail.json is absent
            diag["comm_fault"] = {
                "op": error.op, "outcome": error.outcome.value,
                "elapsed_s": round(error.elapsed_s, 3),
                "comm_tail": getattr(error, "comm_tail", []),
            }
        # dsmem forensics: the ledger + last live samples + per-phase
        # plan-vs-observed deltas ride EVERY bundle (an OOM bundle's whole
        # point; for quarantine/watchdog it is the free context an oncall
        # checks first — "was the device near its limit when this died")
        try:
            if error is not None and is_oom_error(error) \
                    and getattr(engine, "last_oom", None):
                # the engine already snapshotted at the moment of failure
                diag["memory"] = engine.last_oom
            elif hasattr(engine, "memory_forensics"):
                diag["memory"] = engine.memory_forensics(
                    error=repr(error) if error is not None else None)
        except Exception:
            logger.exception("resilience: memory forensics embed failed")
        with open(os.path.join(d, "diag.json"), "w") as f:
            json.dump(diag, f, indent=2, default=str)
        with open(os.path.join(d, "stacks.txt"), "w") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
        # unified-timeline slice: the last minute of spans/instants (guard
        # trips, chaos injections, dispatch/drain cadence) before the
        # quarantine/abort — Perfetto-loadable straight from the bundle
        tracer = get_tracer()
        if tracer.enabled:
            try:
                tracer.export_chrome(os.path.join(d, "trace_tail.json"),
                                     tail_s=TRACE_TAIL_S)
            except Exception:
                logger.exception("resilience: trace-tail embed failed")
        logger.error(f"resilience: diagnostic bundle written -> {d}")
        return d
