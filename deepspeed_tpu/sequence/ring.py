"""Ring attention — blockwise context parallelism over a ``ppermute`` ring.

The reference has NO ring attention (SURVEY.md §2.2: Ulysses all-to-all is its only
long-context mechanism); this is the TPU-side improvement called out in the survey:
KV blocks rotate around the ``sequence`` mesh axis while each device's queries stay
put, with flash-style online-softmax accumulation — O(S/P) activation memory and
communication that overlaps with the per-block attention compute (XLA pipelines the
``ppermute`` with the einsums).

Causality is handled with *global* positions: device i holds queries
[i*S_l, (i+1)*S_l); at ring step t it holds the KV block originating on device
(i - t) mod P, masked by qpos >= kpos.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_lib
from deepspeed_tpu.ops.flash_attention import NEG_INF, _repeat_kv




def ring_attention_local(q_l, k_l, v_l, sp: int, causal: bool = True,
                         axis_name: str = "sequence"):
    """The per-device ring body — callable from any shard_map whose manual
    axes include ``axis_name`` (ring_attention below, and the Ulysses
    uneven-heads remainder path in ``ulysses.py``). q_l: [B, S_l, H_l, D]
    local shards; returns [B, S_l, H_l, D]."""
    b, s_l, h_l, d = q_l.shape
    k_l, v_l = _repeat_kv(k_l, v_l, h_l)
    idx = jax.lax.axis_index(axis_name)
    qpos = idx * s_l + jnp.arange(s_l)
    scale = 1.0 / np.sqrt(d)
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def step(carry, t):
        k_cur, v_cur, m, l, o = carry
        src = (idx - t) % sp
        kpos = src * s_l + jnp.arange(s_l)
        s = jnp.einsum("bqhd,bkhd->bhqk", q_l, k_cur,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = (qpos[:, None] >= kpos[None, :])[None, None]
            s = jnp.where(mask, s, NEG_INF)
        else:
            mask = jnp.bool_(True)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32)
        # rotate KV one hop around the ring (overlaps with next step's compute)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m_new, l_new, o_new), None

    m0 = jnp.full((b, h_l, s_l), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h_l, s_l), jnp.float32)
    o0 = jnp.zeros((b, h_l, s_l, d), jnp.float32)
    (_, _, m, l, o), _ = jax.lax.scan(step, (k_l, v_l, m0, l0, o0),
                                      jnp.arange(sp))
    out = o / jnp.maximum(l, 1e-30)[..., None]          # [B, H, S_l, D]
    return out.transpose(0, 2, 1, 3).astype(q_l.dtype)  # [B, S_l, H, D]


def ring_attention(q, k, v, causal: bool = True, mesh=None):
    """q,k,v: [B, S, H(kv), D] global, sequence-sharded. Returns [B, S, H, D]."""
    mesh = mesh or mesh_lib.get_global_mesh()
    sp = mesh.shape["sequence"]
    if sp == 1:
        from deepspeed_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal)

    h = q.shape[2]
    spec_q = P(mesh_lib.batch_axes(mesh), "sequence", "tensor", None)

    def body(q_l, k_l, v_l):
        return ring_attention_local(q_l, k_l, v_l, sp, causal=causal)

    return jax.shard_map(body, mesh=mesh, in_specs=(spec_q, spec_q, spec_q),
                         out_specs=spec_q, check_vma=False)(q, k, v)
