"""Ring attention — blockwise context parallelism over a ``ppermute`` ring.

The reference has NO ring attention (SURVEY.md §2.2: Ulysses all-to-all is its only
long-context mechanism); this is the TPU-side improvement called out in the survey:
KV blocks rotate around the ``sequence`` mesh axis while each device's queries stay
put, with flash-style online-softmax accumulation — O(S/P) activation memory and
communication that overlaps with the per-block attention compute (XLA pipelines the
``ppermute`` with the einsums).

Causality is handled with *global* positions: device i holds queries
[i*S_l, (i+1)*S_l); at ring step t it holds the KV block originating on device
(i - t) mod P, masked by qpos >= kpos.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_lib
from deepspeed_tpu.ops.flash_attention import NEG_INF, _repeat_kv




def ring_attention_local(q_l, k_l, v_l, sp: int, causal: bool = True,
                         axis_name: str = "sequence"):
    """The per-device ring body — callable from any shard_map whose manual
    axes include ``axis_name`` (ring_attention below, and the Ulysses
    uneven-heads remainder path in ``ulysses.py``). q_l: [B, S_l, H_l, D]
    local shards; returns [B, S_l, H_l, D]."""
    b, s_l, h_l, d = q_l.shape
    k_l, v_l = _repeat_kv(k_l, v_l, h_l)
    idx = jax.lax.axis_index(axis_name)
    qpos = idx * s_l + jnp.arange(s_l)
    scale = 1.0 / np.sqrt(d)
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    def step(carry, t):
        k_cur, v_cur, m, l, o = carry
        src = (idx - t) % sp
        kpos = src * s_l + jnp.arange(s_l)
        s = jnp.einsum("bqhd,bkhd->bhqk", q_l, k_cur,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = (qpos[:, None] >= kpos[None, :])[None, None]
            s = jnp.where(mask, s, NEG_INF)
        else:
            mask = jnp.bool_(True)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32)
        # rotate KV one hop around the ring (overlaps with next step's compute)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, m_new, l_new, o_new), None

    m0 = jnp.full((b, h_l, s_l), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h_l, s_l), jnp.float32)
    o0 = jnp.zeros((b, h_l, s_l, d), jnp.float32)
    (_, _, m, l, o), _ = jax.lax.scan(step, (k_l, v_l, m0, l0, o0),
                                      jnp.arange(sp))
    out = o / jnp.maximum(l, 1e-30)[..., None]          # [B, H, S_l, D]
    return out.transpose(0, 2, 1, 3).astype(q_l.dtype)  # [B, S_l, H, D]


# ---------------------------------------------------------------------------
# flash-kernel ring: the per-step [S_l, S_l] score panel never materializes
# ---------------------------------------------------------------------------

_SKIP_LSE = -1e30     # finite "no contribution" lse (a true -inf NaNs combine)


def _combine(o1, lse1, o2, lse2):
    """Merge two normalized partial attentions (o [B,S,H,D] f32,
    lse [B,H,S]) — the flash multi-block stitch."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    lse = m + jnp.log(w1 + w2)
    w1q = w1.transpose(0, 2, 1)[..., None]     # [B,S,H,1]
    w2q = w2.transpose(0, 2, 1)[..., None]
    o = (w1q * o1 + w2q * o2) / (w1q + w2q)
    return o, lse


def _ring_blocks(s_l: int):
    blk = 256
    while blk > s_l and blk > 8:
        blk //= 2
    return blk


# Striped layout (load balance): contiguous causal ring is skewed — device i
# computes i+1 live blocks of P, so the last device works every step while
# the first idles. With positions striped at stride P (device i holds global
# positions ≡ i mod P in blocks of S_l/P), qpos = m_q*P + i and
# kpos = m_k*P + src, so the causal test reduces to LOCAL causal with a
# one-row shift: m_q >= m_k + (1 if src > idx else 0) — every ring step on
# every device is one (shifted-)causal flash block of identical cost, and
# the kernel's diagonal skipping drops the dead half. Resharding is one
# all_to_all each way, which JAX differentiates through (its transpose is
# the inverse all_to_all).


def _stripe(x, sp, axis_name):
    """Contiguous seq shard -> striped shard (positions ≡ idx mod sp)."""
    b, s_l = x.shape[:2]
    y = x.reshape(b, s_l // sp, sp, *x.shape[2:])
    y = jax.lax.all_to_all(y, axis_name, split_axis=2, concat_axis=2)
    return jnp.swapaxes(y, 1, 2).reshape(x.shape)


def _unstripe(x, sp, axis_name):
    b, s_l = x.shape[:2]
    y = x.reshape(b, sp, s_l // sp, *x.shape[2:])
    y = jax.lax.all_to_all(y, axis_name, split_axis=1, concat_axis=1)
    return jnp.swapaxes(y, 1, 2).reshape(x.shape)


# One fwd/bwd scaffold serves both ring layouts; ``mode`` picks the
# per-step block policy (static, hashable -> one trace per mode):
#   "causal":  contiguous layout — diagonal step causal, earlier steps full,
#              later steps skipped (the skew the striped layout removes)
#   "full":    non-causal — every step a full block
#   "striped": striped layout — every step causal, with a one-row shift on
#              strictly-future stripes (src > idx)


def _step_fwd(mode, src, idx, block, skip):
    """block(causal, shift) -> (o, lse); skip() -> zero contribution."""
    if mode == "full":
        return block(False, 0)
    if mode == "striped":
        return jax.lax.cond(src > idx,
                            lambda: block(True, 1), lambda: block(True, 0))
    return jax.lax.cond(
        src == idx, lambda: block(True, 0),
        lambda: jax.lax.cond(src < idx, lambda: block(False, 0), skip))


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _ring_core(q_l, k_l, v_l, seg_l, sp: int, mode: str, axis_name: str,
               interpret: bool):
    """Ring attention whose per-step block attention is the Pallas flash
    kernel: fwd stitches the blocks' (o, lse) online; bwd re-rotates KV and
    runs the flash backward per block against the FINAL lse (the standard
    multi-block decomposition — per-block probabilities under the global
    softmax), with dk/dv accumulators riding the ring home. q_l [B,S_l,H,D],
    k_l/v_l [B,S_l,Hkv,D] (GQA handled inside the kernel). ``seg_l``
    [B, S_l] packed-sequence ids or None; the KV block's ids ride the ring
    with it (local queries keep their own)."""
    out, _ = _ring_fwd(q_l, k_l, v_l, seg_l, sp, mode, axis_name, interpret)
    return out


def _ring_fwd(q_l, k_l, v_l, seg_l, sp, mode, axis_name, interpret):
    from deepspeed_tpu.ops.pallas.flash_attention import _pallas_flash_fwd_impl
    b, s_l, h, d = q_l.shape
    blk = _ring_blocks(s_l)
    idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % sp) for j in range(sp)]
    has_seg = seg_l is not None
    kseg0 = seg_l if has_seg else jnp.zeros((b, s_l), jnp.int32)

    def step(carry, t):
        k_cur, v_cur, kseg_cur, o_acc, lse_acc = carry
        src = (idx - t) % sp

        def block(kv_causal, shift):
            o, lse = _pallas_flash_fwd_impl(
                q_l, k_cur, v_cur, kv_causal, blk, blk, interpret, None,
                causal_shift=shift,
                segment_ids=(seg_l, kseg_cur) if has_seg else None)
            return (o.astype(jnp.float32),
                    lse[:, :s_l, 0].reshape(b, h, s_l))

        def skip():
            return (jnp.zeros((b, s_l, h, d), jnp.float32),
                    jnp.full((b, h, s_l), _SKIP_LSE, jnp.float32))

        o_t, lse_t = _step_fwd(mode, src, idx, block, skip)
        o_acc, lse_acc = _combine(o_acc, lse_acc, o_t, lse_t)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        kseg_next = jax.lax.ppermute(kseg_cur, axis_name, perm)
        return (k_next, v_next, kseg_next, o_acc, lse_acc), None

    o0 = jnp.zeros((b, s_l, h, d), jnp.float32)
    lse0 = jnp.full((b, h, s_l), _SKIP_LSE, jnp.float32)
    (_, _, _, o, lse), _ = jax.lax.scan(step, (k_l, v_l, kseg0, o0, lse0),
                                        jnp.arange(sp))
    return o.astype(q_l.dtype), lse


def _ring_fwd_vjp(q_l, k_l, v_l, seg_l, sp, mode, axis_name, interpret):
    out, lse = _ring_fwd(q_l, k_l, v_l, seg_l, sp, mode, axis_name, interpret)
    return out, (q_l, k_l, v_l, seg_l, out, lse)


def _ring_bwd(sp, mode, axis_name, interpret, res, g):
    from deepspeed_tpu.ops.pallas.flash_attention import _pallas_flash_bwd_impl
    q_l, k_l, v_l, seg_l, out, lse = res
    b, s_l, h, d = q_l.shape
    blk = _ring_blocks(s_l)
    # the bwd impl consumes lse in its folded padded layout [B*H, S_pad, 1]
    pad = (-s_l) % blk
    lse_f = lse.reshape(b * h, s_l, 1)
    if pad:
        lse_f = jnp.pad(lse_f, ((0, 0), (0, pad), (0, 0)))
    idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % sp) for j in range(sp)]
    has_seg = seg_l is not None
    b2 = q_l.shape[0]
    kseg0 = seg_l if has_seg else jnp.zeros((b2, s_l), jnp.int32)

    def step(carry, t):
        k_cur, v_cur, kseg_cur, dk_acc, dv_acc, dq_acc = carry
        src = (idx - t) % sp

        def block(kv_causal, shift):
            return _pallas_flash_bwd_impl(
                q_l, k_cur, v_cur, out, lse_f, g, kv_causal, blk, blk,
                interpret, None, causal_shift=shift,
                segment_ids=(seg_l, kseg_cur) if has_seg else None)

        def skip():
            return (jnp.zeros_like(q_l), jnp.zeros_like(k_cur),
                    jnp.zeros_like(v_cur))

        dq_c, dk_c, dv_c = _step_fwd(mode, src, idx, block, skip)
        dq_acc = dq_acc + dq_c.astype(jnp.float32)
        dk_acc = dk_acc + dk_c.astype(jnp.float32)
        dv_acc = dv_acc + dv_c.astype(jnp.float32)
        # dk/dv accumulators ride the ring WITH their block; after sp hops
        # every block (and its gradient) is back on its home device
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        kseg_next = jax.lax.ppermute(kseg_cur, axis_name, perm)
        dk_next = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_next = jax.lax.ppermute(dv_acc, axis_name, perm)
        return (k_next, v_next, kseg_next, dk_next, dv_next, dq_acc), None

    (_, _, _, dk, dv, dq), _ = jax.lax.scan(
        step, (k_l, v_l, kseg0, jnp.zeros(k_l.shape, jnp.float32),
               jnp.zeros(v_l.shape, jnp.float32),
               jnp.zeros(q_l.shape, jnp.float32)),
        jnp.arange(sp))
    return (dq.astype(q_l.dtype), dk.astype(k_l.dtype),
            dv.astype(v_l.dtype), None)


_ring_core.defvjp(_ring_fwd_vjp, _ring_bwd)


def ring_attention_local_flash(q_l, k_l, v_l, sp: int, causal: bool,
                               axis_name: str = "sequence",
                               interpret: bool = False, seg_l=None):
    """Contiguous-layout flash ring (see _ring_core)."""
    return _ring_core(q_l, k_l, v_l, seg_l, sp,
                      "causal" if causal else "full", axis_name, interpret)


def ring_attention_local_striped(q_l, k_l, v_l, sp: int,
                                 axis_name: str = "sequence",
                                 interpret: bool = False, seg_l=None):
    """Load-balanced causal ring: stripe q/k/v (and the segment ids), run
    the shifted-causal flash ring, unstripe the output. Requires
    S_l % sp == 0 (checked by caller)."""
    q_s = _stripe(q_l, sp, axis_name)
    k_s = _stripe(k_l, sp, axis_name)
    v_s = _stripe(v_l, sp, axis_name)
    seg_s = _stripe(seg_l, sp, axis_name) if seg_l is not None else None
    out = _ring_core(q_s, k_s, v_s, seg_s, sp, "striped", axis_name,
                     interpret)
    return _unstripe(out, sp, axis_name)


def ring_attention(q, k, v, causal: bool = True, mesh=None,
                   impl: Optional[str] = None, segment_ids=None):
    """q,k,v: [B, S, H(kv), D] global, sequence-sharded. Returns [B, S, H, D].

    ``impl``: ``"flash"`` (Pallas kernel per ring block — O(block) memory,
    MXU-tiled; causal runs STRIPED for load balance when S_l % sp == 0;
    TPU default), ``"flash_contiguous"`` (skew-causal flash ring, no
    resharding), ``"xla"`` (the jnp online-softmax body — any backend),
    ``"interpret"`` / ``"interpret_contiguous"`` (the flash paths in
    interpreter mode, for CPU tests). Default picks flash on TPU, xla
    elsewhere.
    """
    mesh = mesh or mesh_lib.get_global_mesh()
    sp = mesh.shape["sequence"]
    if sp == 1:
        from deepspeed_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal,
                               segment_ids=segment_ids)
    if impl is None:
        impl = "flash" if jax.default_backend() == "tpu" else "xla"
    if segment_ids is not None and impl == "xla":
        raise NotImplementedError(
            "packed-sequence segment_ids need the flash ring (the jnp body "
            "does not carry segment ids) — impl='flash' or 'interpret'")

    spec_q = P(mesh_lib.batch_axes(mesh), "sequence", "tensor", None)
    seg_spec = P(mesh_lib.batch_axes(mesh), "sequence")
    s_l = q.shape[1] // sp
    striped = causal and s_l % sp == 0 and impl in ("flash", "interpret")

    if impl == "xla":
        def body(q_l, k_l, v_l, seg_l=None):
            return ring_attention_local(q_l, k_l, v_l, sp, causal=causal)
    elif striped:
        interpret = impl == "interpret"

        def body(q_l, k_l, v_l, seg_l=None):
            return ring_attention_local_striped(q_l, k_l, v_l, sp,
                                                "sequence", interpret,
                                                seg_l=seg_l)
    else:
        interpret = impl.startswith("interpret")

        def body(q_l, k_l, v_l, seg_l=None):
            return ring_attention_local_flash(q_l, k_l, v_l, sp, causal,
                                              "sequence", interpret,
                                              seg_l=seg_l)

    if segment_ids is not None:
        return jax.shard_map(
            body, mesh=mesh, in_specs=(spec_q, spec_q, spec_q, seg_spec),
            out_specs=spec_q, check_vma=False)(
                q, k, v, jnp.asarray(segment_ids, jnp.int32))
    return jax.shard_map(body, mesh=mesh, in_specs=(spec_q, spec_q, spec_q),
                         out_specs=spec_q, check_vma=False)(q, k, v)
