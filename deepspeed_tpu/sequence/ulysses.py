"""DeepSpeed-Ulysses sequence parallelism — head-scatter all-to-all attention.

Reference analog: ``deepspeed/sequence/layer.py:271`` (``DistributedAttention``):
q/k/v arrive sequence-sharded [B, S/P, H, D]; an all-to-all scatters heads and
gathers sequence -> [B, S, H/P, D]; local attention runs over the full sequence with
a slice of heads; an inverse all-to-all restores sequence sharding
(``_SeqAllToAll`` layer.py:216, ``single_all_to_all`` :153).

TPU-native: one ``shard_map`` over the mesh with ``lax.all_to_all`` on the
``sequence`` axis — 4 all-to-alls per attention (q,k,v + output), riding ICI.
Composes with TP: heads are already split over ``tensor``; Ulysses further splits
the local heads over ``sequence``. When heads/tp is not divisible by the
sequence-parallel degree, the reference redistributes heads unevenly with an
explicit padded all-to-all (``uneven_heads_all2all`` layer.py:43); here the head
dimension is zero-padded up to the next multiple of sp (GQA KV heads densified
first so q/kv pad identically), the same even all-to-all runs, and the pad heads
are sliced off after the inverse all-to-all — identical comm pattern and
numerics, with at most (sp-1)/H wasted head-compute on the corner case.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_lib
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention_auto




def ulysses_attention(q, k, v, causal: bool = True, mesh=None,
                      use_flash: bool = True):
    """q: [B, S, H, D] global (sequence-sharded on the mesh); returns same shape.

    Inside the shard_map each device holds [B, S/sp, H_local, D]; after the
    all-to-all it holds [B, S, H_local/sp, D] and runs full-sequence attention.
    """
    mesh = mesh or mesh_lib.get_global_mesh()
    sp = mesh.shape["sequence"]
    if sp == 1:
        return flash_attention_auto(q, k, v, causal=causal) if use_flash else \
            _local_attn(q, k, v, causal)

    tp = max(mesh.shape["tensor"], 1)
    uneven = (q.shape[2] // tp) % sp != 0 or (k.shape[2] // tp) % sp != 0

    spec = P(mesh_lib.batch_axes(mesh), "sequence", "tensor", None)

    def body(q_l, k_l, v_l):
        h_local = q_l.shape[2]
        if uneven:
            # densify GQA so q/kv share a head count, then zero-pad heads to a
            # multiple of sp (reference: uneven_heads_all2all layer.py:43)
            rep = q_l.shape[2] // k_l.shape[2]
            if rep > 1:
                k_l = jnp.repeat(k_l, rep, axis=2)
                v_l = jnp.repeat(v_l, rep, axis=2)
            pad = (-h_local) % sp
            if pad:
                padw = ((0, 0), (0, 0), (0, pad), (0, 0))
                q_l, k_l, v_l = (jnp.pad(a, padw) for a in (q_l, k_l, v_l))
        # [B, S/sp, Hl, D] -> scatter heads / gather sequence -> [B, S, Hl/sp, D]
        a2a = partial(jax.lax.all_to_all, axis_name="sequence",
                      split_axis=2, concat_axis=1, tiled=True)
        qg, kg, vg = a2a(q_l), a2a(k_l), a2a(v_l)
        # Pallas kernel on TPU (runs inside the shard_map), lax elsewhere
        out = flash_attention_auto(qg, kg, vg, causal=causal) if use_flash else \
            _local_attn(qg, kg, vg, causal)
        # inverse: scatter sequence / gather heads
        out = jax.lax.all_to_all(out, axis_name="sequence", split_axis=1,
                                 concat_axis=2, tiled=True)
        return out[:, :, :h_local]

    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


def _local_attn(q, k, v, causal):
    from deepspeed_tpu.ops.flash_attention import attention_reference
    return attention_reference(q, k, v, causal=causal)
