"""DeepSpeed-Ulysses sequence parallelism — head-scatter all-to-all attention.

Reference analog: ``deepspeed/sequence/layer.py:271`` (``DistributedAttention``):
q/k/v arrive sequence-sharded [B, S/P, H, D]; an all-to-all scatters heads and
gathers sequence -> [B, S, H/P, D]; local attention runs over the full sequence with
a slice of heads; an inverse all-to-all restores sequence sharding
(``_SeqAllToAll`` layer.py:216, ``single_all_to_all`` :153).

TPU-native: one ``shard_map`` over the mesh with ``lax.all_to_all`` on the
``sequence`` axis — 4 all-to-alls per attention (q,k,v + output), riding ICI.
Composes with TP: heads are already split over ``tensor``; Ulysses further splits
the local heads over ``sequence``. When heads/tp is not divisible by the
sequence-parallel degree, the reference redistributes heads unevenly with an
explicit padded all-to-all (``uneven_heads_all2all`` layer.py:43) — which leaves
the ranks holding ``ceil(H/sp)`` heads as stragglers. Here, with the built-in
attention, the uneven case is EXACT and balanced instead: the largest
sp-divisible head group takes the normal head-scatter all-to-all, and the
remainder ``H mod sp`` heads stay sequence-sharded and run ring attention over
the same axis (``ring.ring_attention_local``) — every device computes exactly
``H/sp`` heads' worth of attention, no padded compute, no straggler rank. With
a custom ``attn_fn`` (whose semantics the ring remainder could not honor), the
heads are instead padded to the next sp multiple and ALL run through the
all-to-all + ``attn_fn`` — ``ceil(H/sp)`` heads per device, SPMD-uniform.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_lib
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention_auto




def ulysses_attention(q, k, v, causal: bool = True, mesh=None,
                      use_flash: bool = True, attn_fn=None, segment_ids=None,
                      ring_impl=None):
    """q: [B, S, H, D] global (sequence-sharded on the mesh); returns same shape.

    Inside the shard_map each device holds [B, S/sp, H_local, D]; after the
    all-to-all it holds [B, S, H_local/sp, D] and runs full-sequence attention.
    ``attn_fn(q, k, v)`` overrides the local attention computed on the
    gathered sequence (the reference DistributedAttention's pluggable
    ``local_attention``); default: flash kernel / reference attention.
    ``segment_ids`` [B, S] (packed sequences): the seq-sharded ids are
    all-gathered inside the shard_map — after the head-scatter every device
    holds the FULL sequence, so segment masking happens in the local
    attention (flash kernel's in-kernel mask).
    """
    mesh = mesh or mesh_lib.get_global_mesh()
    sp = mesh.shape["sequence"]

    def local(qq, kk, vv, seg=None):
        if attn_fn is not None:
            if seg is not None:
                raise NotImplementedError(
                    "segment_ids with a custom local_attention is "
                    "unsupported — mask inside your attn_fn instead")
            return attn_fn(qq, kk, vv)
        if use_flash:
            return flash_attention_auto(qq, kk, vv, causal=causal,
                                        segment_ids=seg)
        return _local_attn(qq, kk, vv, causal, seg)

    if sp == 1:
        return local(q, k, v, segment_ids)

    tp = max(mesh.shape["tensor"], 1)
    uneven = (q.shape[2] // tp) % sp != 0 or (k.shape[2] // tp) % sp != 0

    spec = P(mesh_lib.batch_axes(mesh), "sequence", "tensor", None)
    seg_spec = P(mesh_lib.batch_axes(mesh), "sequence")
    if segment_ids is not None and uneven:
        raise NotImplementedError(
            "segment_ids with an sp-indivisible head count (uneven-heads "
            "ulysses) is unsupported — pad heads or use the flash/xla "
            "backend")

    def a2a_attention(q_l, k_l, v_l, seg_l=None):
        # [B, S/sp, Hl, D] -> scatter heads / gather sequence -> [B, S, Hl/sp, D]
        a2a = partial(jax.lax.all_to_all, axis_name="sequence",
                      split_axis=2, concat_axis=1, tiled=True)
        qg, kg, vg = a2a(q_l), a2a(k_l), a2a(v_l)
        seg = jax.lax.all_gather(seg_l, "sequence", axis=1, tiled=True) \
            if seg_l is not None else None
        # Pallas kernel on TPU (runs inside the shard_map), lax elsewhere
        out = local(qg, kg, vg, seg)
        # inverse: scatter sequence / gather heads
        return jax.lax.all_to_all(out, axis_name="sequence", split_axis=1,
                                  concat_axis=2, tiled=True)

    def body(q_l, k_l, v_l, seg_l=None):
        if not uneven:
            return a2a_attention(q_l, k_l, v_l, seg_l)
        # uneven heads: densify GQA so q/kv share a head count, then
        h_local = q_l.shape[2]
        rep = q_l.shape[2] // k_l.shape[2]
        if rep > 1:
            k_l = jnp.repeat(k_l, rep, axis=2)
            v_l = jnp.repeat(v_l, rep, axis=2)
        if attn_fn is not None:
            # a custom local_attention must see EVERY head (it may not be
            # plain softmax — softcap, sliding windows, a Pallas kernel with
            # its own options), so pad heads to the next sp multiple and run
            # them all through the normal head-scatter all-to-all: each
            # device computes ceil(H/sp) heads under attn_fn semantics, the
            # padded zero heads are sliced off after the inverse all-to-all.
            # This is the reference's padded uneven redistribution
            # (uneven_heads_all2all, layer.py:43) — but SPMD-uniform, so no
            # straggler rank. Note kv are densified to q's head count above:
            # proportional GQA padding cannot keep the q->kv group alignment
            # through the scatter.
            pad = (-h_local) % sp
            def pz(x):
                z = jnp.zeros((*x.shape[:2], pad, x.shape[3]), x.dtype)
                return jnp.concatenate([x, z], axis=2)
            out = a2a_attention(pz(q_l), pz(k_l), pz(v_l))
            return out[:, :, :h_local]
        # built-in attention: exact balanced split — the sp-divisible head
        # group takes the normal all-to-all (flash kernel on the gathered
        # sequence), the H mod sp remainder runs ring attention on the same
        # axis — exactly H/sp heads of compute per device, no padding, no
        # straggler (improves on the reference's uneven redistribution,
        # layer.py:43, whose ceil(H/sp) ranks bound the step)
        from deepspeed_tpu.sequence.ring import (ring_attention_local,
                                                 ring_attention_local_flash)
        h_even = (h_local // sp) * sp
        parts = []
        if h_even:
            parts.append(a2a_attention(q_l[:, :, :h_even], k_l[:, :, :h_even],
                                       v_l[:, :, :h_even]))
        if h_local - h_even:  # GQA-only unevenness can leave no remainder
            rem = (q_l[:, :, h_even:], k_l[:, :, h_even:], v_l[:, :, h_even:])
            impl = ring_impl or ("flash" if jax.default_backend() == "tpu"
                                 else "xla")
            if impl in ("flash", "interpret"):
                # remainder heads ride the flash ring (no [S_l,S_l] panel)
                parts.append(ring_attention_local_flash(
                    *rem, sp, causal, "sequence",
                    interpret=impl == "interpret"))
            else:
                parts.append(ring_attention_local(*rem, sp, causal=causal))
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=2)

    if segment_ids is not None:
        return jax.shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec, seg_spec),
            out_specs=spec, check_vma=False)(
                q, k, v, jnp.asarray(segment_ids, jnp.int32))
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


def _local_attn(q, k, v, causal, segment_ids=None):
    from deepspeed_tpu.ops.flash_attention import attention_reference
    return attention_reference(q, k, v, causal=causal,
                               segment_ids=segment_ids)
