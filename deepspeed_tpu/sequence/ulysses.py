"""DeepSpeed-Ulysses sequence parallelism — head-scatter all-to-all attention.

Reference analog: ``deepspeed/sequence/layer.py:271`` (``DistributedAttention``):
q/k/v arrive sequence-sharded [B, S/P, H, D]; an all-to-all scatters heads and
gathers sequence -> [B, S, H/P, D]; local attention runs over the full sequence with
a slice of heads; an inverse all-to-all restores sequence sharding
(``_SeqAllToAll`` layer.py:216, ``single_all_to_all`` :153).

TPU-native: one ``shard_map`` over the mesh with ``lax.all_to_all`` on the
``sequence`` axis — 4 all-to-alls per attention (q,k,v + output), riding ICI.
Composes with TP: heads are already split over ``tensor``; Ulysses further splits
the local heads over ``sequence``. Constraint (same as reference default path):
heads/tp must be divisible by the sequence-parallel degree; the reference's
uneven-heads fallback (``uneven_heads_all2all`` layer.py:43) is approximated by
falling back to ring attention when heads don't divide.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_lib
from deepspeed_tpu.ops.flash_attention import flash_attention




def ulysses_attention(q, k, v, causal: bool = True, mesh=None,
                      use_flash: bool = True):
    """q: [B, S, H, D] global (sequence-sharded on the mesh); returns same shape.

    Inside the shard_map each device holds [B, S/sp, H_local, D]; after the
    all-to-all it holds [B, S, H_local/sp, D] and runs full-sequence attention.
    """
    mesh = mesh or mesh_lib.get_global_mesh()
    sp = mesh.shape["sequence"]
    if sp == 1:
        return flash_attention(q, k, v, causal=causal) if use_flash else \
            _local_attn(q, k, v, causal)

    h_local = q.shape[2] // (mesh.shape["tensor"] * sp) * sp  # sanity below
    if (q.shape[2] // mesh.shape["tensor"]) % sp != 0 or \
            (k.shape[2] // max(mesh.shape["tensor"], 1)) % sp != 0:
        from deepspeed_tpu.sequence.ring import ring_attention
        return ring_attention(q, k, v, causal=causal, mesh=mesh)

    spec = P(mesh_lib.batch_axes(mesh), "sequence", "tensor", None)

    def body(q_l, k_l, v_l):
        # [B, S/sp, Hl, D] -> scatter heads / gather sequence -> [B, S, Hl/sp, D]
        a2a = partial(jax.lax.all_to_all, axis_name="sequence",
                      split_axis=2, concat_axis=1, tiled=True)
        qg, kg, vg = a2a(q_l), a2a(k_l), a2a(v_l)
        out = flash_attention(qg, kg, vg, causal=causal) if use_flash else \
            _local_attn(qg, kg, vg, causal)
        # inverse: scatter sequence / gather heads
        return jax.lax.all_to_all(out, axis_name="sequence", split_axis=1,
                                  concat_axis=2, tiled=True)

    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


def _local_attn(q, k, v, causal):
    from deepspeed_tpu.ops.flash_attention import attention_reference
    return attention_reference(q, k, v, causal=causal)
