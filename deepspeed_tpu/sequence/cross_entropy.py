"""Vocab-parallel cross entropy.

Reference analog: ``deepspeed/sequence/cross_entropy.py``
(``vocab_parallel_cross_entropy`` — CE over a vocab-sharded lm head without
gathering the full logits, Megatron-style).

TPU shape: inside ``shard_map`` over the ``tensor`` axis each device holds
``logits_local [*, V/P]``; the softmax statistics compose across shards with
two psums (max, sum-exp) and the target logit is recovered with a masked local
lookup + psum — the full ``[*, V]`` logits never materialize, which matters
when V is 128k+ and the sequence is long.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_lib


def _local_vocab_ce(logits_local, labels, axis_name: str):
    """logits_local: [N, V/P] fp32; labels: [N] global vocab ids.
    Returns per-token loss [N] (replicated across the axis)."""
    vp = logits_local.shape[-1]
    rank = jax.lax.axis_index(axis_name)
    lo = rank * vp

    lmax = jax.lax.pmax(jnp.max(logits_local, axis=-1), axis_name)     # [N]
    shifted = logits_local - lmax[..., None]
    sumexp = jax.lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), axis_name)

    local_idx = labels - lo
    in_shard = (local_idx >= 0) & (local_idx < vp)
    safe_idx = jnp.clip(local_idx, 0, vp - 1)
    tgt = jnp.take_along_axis(shifted, safe_idx[..., None], axis=-1)[..., 0]
    tgt = jax.lax.psum(jnp.where(in_shard, tgt, 0.0), axis_name)

    return jnp.log(sumexp) - tgt


def vocab_parallel_cross_entropy(logits, labels, mesh=None,
                                 axis_name: str = "tensor"):
    """logits: [B, S, V] sharded on V over ``axis_name``; labels: [B, S].
    Returns per-token loss [B, S]. Degrades to dense CE when the axis is 1."""
    mesh = mesh or mesh_lib.get_global_mesh()
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]

    def body(logits_l, labels_l):
        b, s, vp = logits_l.shape
        loss = _local_vocab_ce(logits_l.astype(jnp.float32).reshape(b * s, vp),
                               labels_l.reshape(b * s), axis_name)
        return loss.reshape(b, s)

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None, axis_name), P()),
        out_specs=P(), check_vma=False)(logits, labels)
