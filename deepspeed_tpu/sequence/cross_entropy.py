"""Vocab-parallel cross entropy.

Reference analog: ``deepspeed/sequence/cross_entropy.py``
(``vocab_parallel_cross_entropy`` — CE over a vocab-sharded lm head without
gathering the full logits, Megatron-style).

TPU shape: inside ``shard_map`` over the ``tensor`` axis each device holds
``logits_local [*, V/P]``; the softmax statistics compose across shards with
two psums (max, sum-exp) and the target logit is recovered with a masked local
lookup + psum — the full ``[*, V]`` logits never materialize, which matters
when V is 128k+ and the sequence is long.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_lib


def _local_vocab_ce(logits_local, labels, axis_name: str):
    """logits_local: [N, V/P] fp32; labels: [N] global vocab ids.
    Returns per-token loss [N] (replicated across the axis)."""
    vp = logits_local.shape[-1]
    rank = jax.lax.axis_index(axis_name)
    lo = rank * vp

    lmax = jax.lax.pmax(jnp.max(logits_local, axis=-1), axis_name)     # [N]
    shifted = logits_local - lmax[..., None]
    sumexp = jax.lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), axis_name)

    local_idx = labels - lo
    in_shard = (local_idx >= 0) & (local_idx < vp)
    safe_idx = jnp.clip(local_idx, 0, vp - 1)
    tgt = jnp.take_along_axis(shifted, safe_idx[..., None], axis=-1)[..., 0]
    tgt = jax.lax.psum(jnp.where(in_shard, tgt, 0.0), axis_name)

    return jnp.log(sumexp) - tgt


def vocab_parallel_cross_entropy(logits, labels, mesh=None,
                                 axis_name: str = "tensor"):
    """logits: [B, S, V] sharded on V over ``axis_name``; labels: [B, S].
    Returns per-token loss [B, S]. Degrades to dense CE when the axis is 1."""
    mesh = mesh or mesh_lib.get_global_mesh()
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]

    def body(logits_l, labels_l):
        b, s, vp = logits_l.shape
        loss = _local_vocab_ce(logits_l.astype(jnp.float32).reshape(b * s, vp),
                               labels_l.reshape(b * s), axis_name)
        return loss.reshape(b, s)

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None, axis_name), P()),
        out_specs=P(), check_vma=False)(logits, labels)


def chunked_cross_entropy(hidden, labels, mask, *, kernel=None, embedding=None,
                          chunk_size: int = 1024,
                          soft_cap=None, compute_dtype=jnp.bfloat16,
                          unroll: bool = False):
    """Next-token CE from *hidden states* without materializing [B*S, V] fp32.

    The reference computes full logits and feeds them to torch CE (its fused
    vocab kernel lives in Megatron, not DeepSpeed); on TPU the fp32 logits tensor
    is the single largest HBM temp of a training step (B*S*V*4 bytes — 1 GB at
    B=4, S=2k, V=32k), and it is written + re-read across the fwd/bwd boundary.
    Here the head matmul and the softmax-CE reduction run fused per token-chunk
    under ``jax.checkpoint`` inside a ``lax.scan``: peak logits memory drops to
    ``chunk_size * V`` and the backward recomputes each chunk's logits instead
    of fetching them from HBM (one extra head matmul — ~3% of model FLOPs for
    a 0.7B Llama — traded for ~3 GB of temps).

    hidden: [B, S, H]; labels/mask: [B, S]; exactly one of
    ``kernel`` [H, V] / ``embedding`` [V, H] (tied) supplies the head weights.
    Returns mean CE over masked tokens (same contract as the dense path).
    """
    if (kernel is None) == (embedding is None):
        raise ValueError("pass exactly one of kernel / embedding")
    b, s, h = hidden.shape
    n = b * s
    c = min(chunk_size, n)
    pad = (-n) % c
    xf = hidden.reshape(n, h)
    lf = labels.reshape(n).astype(jnp.int32)
    mf = mask.reshape(n).astype(jnp.float32)
    if pad:
        xf = jnp.pad(xf, ((0, pad), (0, 0)))
        lf = jnp.pad(lf, (0, pad))
        mf = jnp.pad(mf, (0, pad))
    nc = (n + pad) // c
    w = (kernel if kernel is not None else embedding).astype(compute_dtype)
    contract = "ch,hv->cv" if kernel is not None else "ch,vh->cv"

    def body(total, inp):
        xc, lc, mc = inp
        logits = jnp.einsum(contract, xc.astype(compute_dtype), w,
                            preferred_element_type=jnp.float32)
        if soft_cap:
            logits = soft_cap * jnp.tanh(logits / soft_cap)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, lc[:, None], axis=-1)[:, 0]
        return total + jnp.sum((lse - tgt) * mc), None

    xs = xf.reshape(nc, c, h)
    ls = lf.reshape(nc, c)
    ms = mf.reshape(nc, c)
    ck = jax.checkpoint(body)
    if unroll:
        # unrolled chunk loop: nc is small and static (B*S/chunk ~ 4-16), so
        # XLA sees nc copies of one fused matmul+CE block instead of a
        # scan-of-checkpoint — the structure suspected of the pathological
        # XLA:TPU compile time when this scan nests inside the engine's gas
        # scan (>20 min observed; see VERDICT round 2). Same memory bound:
        # each chunk's logits are rematerialized in the backward.
        total = jnp.zeros((), jnp.float32)
        for i in range(nc):
            total, _ = ck(total, (xs[i], ls[i], ms[i]))
    else:
        total, _ = jax.lax.scan(ck, jnp.zeros((), jnp.float32), (xs, ls, ms))
    return total / jnp.maximum(jnp.sum(mf), 1.0)
