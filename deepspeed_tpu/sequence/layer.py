"""API-compat sequence-parallel attention layer.

Reference analog: ``deepspeed/sequence/layer.py:271`` —
``DistributedAttention(local_attention, sequence_process_group)``: a module
wrapping any local attention; sequence-sharded q/k/v are head-scattered via
all-to-all, the wrapped attention runs on the full sequence with a head
slice, and the inverse all-to-all restores sequence sharding. Here the
process group is the mesh's ``sequence`` axis and the machinery is
``ulysses_attention`` (incl. the exact uneven-heads hybrid).
"""

from typing import Callable, Optional

from deepspeed_tpu.sequence.ulysses import ulysses_attention


class DistributedAttention:
    """Drop-in analog of the reference class: call with sequence-sharded
    [B, S, H, D] q/k/v; extra positional/keyword args flow to the wrapped
    ``local_attention(q, k, v, *args, **kwargs)`` which sees the gathered
    sequence and its head slice (kv keep their GQA head count — densify
    inside the fn if needed). ``local_attention=None`` uses the built-in
    flash/reference attention (``causal`` applies only to the built-in).
    When heads don't divide the sequence degree, they are padded to the
    next multiple and every head still runs through ``local_attention``
    (``ceil(H/sp)`` per device; kv densified to q's head count first)."""

    def __init__(self, local_attention: Optional[Callable] = None,
                 mesh=None, causal: bool = True):
        self.local_attention = local_attention
        self.mesh = mesh
        self.causal = causal

    def __call__(self, query, key, value, *args, **kwargs):
        attn_fn = None
        if self.local_attention is not None:
            attn_fn = lambda q, k, v: self.local_attention(  # noqa: E731
                q, k, v, *args, **kwargs)
        return ulysses_attention(query, key, value, causal=self.causal,
                                 mesh=self.mesh, attn_fn=attn_fn)
