"""Flops profiler.

Reference analog: ``FlopsProfiler`` (``deepspeed/profiling/flops_profiler/profiler.py:29``),
which monkey-patches ``torch.nn.functional`` to count MACs/flops per module and prints
per-depth / top-module tables at ``profile_step``.

TPU-native redesign: no monkey-patching — JAX gives us the whole computation as a jaxpr.
We trace the step function once (abstractly — zero device work), walk the jaxpr with a
per-primitive flop-rule table, and attribute every equation's cost to the flax module
that emitted it via the equation's ``name_stack`` (flax wraps each module method in
``jax.named_scope``). Control-flow primitives are recursed: ``scan`` multiplies its body
cost by the trip count, ``pjit``/``remat``/``custom_*`` are flattened, ``cond`` takes the
max across branches (upper bound), ``while`` counts one iteration (trip count is
data-dependent). XLA's own ``compiled.cost_analysis()`` is exposed as a cross-check
(post-fusion, so it can legitimately be lower than the analytic count).
"""

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax import core as jax_core

from deepspeed_tpu.utils.logging import logger

# ---------------------------------------------------------------------------
# Per-primitive flop rules.  Each rule: (eqn) -> (flops, macs)
# ---------------------------------------------------------------------------


def _size(aval) -> int:
    try:
        return int(np.prod(aval.shape)) if aval.shape else 1
    except Exception:
        return 0


def _dot_general_flops(eqn) -> Tuple[int, int]:
    # flops = 2 * batch * M * N * K  (reference counts MACs = flops / 2)
    lhs = eqn.invars[0].aval
    (lhs_contract, _), _ = eqn.params["dimension_numbers"]
    out = eqn.outvars[0].aval
    k = 1
    for d in lhs_contract:
        k *= lhs.shape[d]
    macs = _size(out) * k
    return 2 * macs, macs


def _conv_flops(eqn) -> Tuple[int, int]:
    rhs = eqn.invars[1].aval  # kernel
    out = eqn.outvars[0].aval
    dn = eqn.params["dimension_numbers"]
    # kernel shape: spatial dims + in-feature dim (already /group_count) per rhs_spec
    rhs_spec = dn.rhs_spec  # (out_feature, in_feature, *spatial) indices
    k = 1
    for i, d in enumerate(rhs.shape):
        if i != rhs_spec[0]:  # everything but the out-feature dim
            k *= d
    macs = _size(out) * k
    return 2 * macs, macs


_ELEMENTWISE_1 = {
    "add", "sub", "mul", "max", "min", "and", "or", "xor", "neg", "sign",
    "floor", "ceil", "round", "abs", "not", "is_finite",
    "clamp", "nextafter", "rem", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "population_count",
    "eq", "ne", "lt", "le", "gt", "ge", "real", "imag", "conj",
}
_ELEMENTWISE_K = {  # transcendental — count a few flops each
    "div": 4, "sqrt": 4, "rsqrt": 4, "exp": 8, "exp2": 8, "expm1": 8,
    "log": 8, "log1p": 8, "log2": 8, "sin": 8, "cos": 8, "tan": 8,
    "tanh": 8, "logistic": 8, "erf": 8, "erfc": 8, "erf_inv": 8,
    "pow": 10, "atan2": 10, "cbrt": 6, "asin": 8, "acos": 8, "atan": 8,
    "sinh": 8, "cosh": 8, "asinh": 8, "acosh": 8, "atanh": 8, "digamma": 10,
    "lgamma": 10, "regularized_incomplete_beta": 20, "igamma": 20, "igammac": 20,
}
_REDUCTIONS = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
               "reduce_and", "reduce_or", "argmax", "argmin",
               "reduce_precision", "cumsum", "cummax", "cummin", "cumprod",
               "cumlogsumexp"}
# layout/data-movement primitives (reshape, transpose, slice, gather, iota, …)
# fall through _flops_of_eqn's default and count as 0 flops.


def _flops_of_eqn(eqn) -> Tuple[int, int]:
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_general_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    if name in _ELEMENTWISE_1:
        return _size(eqn.outvars[0].aval), 0
    if name in _ELEMENTWISE_K:
        return _ELEMENTWISE_K[name] * _size(eqn.outvars[0].aval), 0
    if name in _REDUCTIONS:
        return _size(eqn.invars[0].aval), 0
    if name == "integer_pow":
        return 2 * _size(eqn.outvars[0].aval), 0
    if name in ("scatter-add", "scatter_add"):
        return _size(eqn.invars[-1].aval), 0
    if name == "sort":
        n = _size(eqn.invars[0].aval)
        return int(n * max(1, np.log2(max(n, 2)))), 0
    return 0, 0  # layout/comm/unknown primitives: free for flop purposes


# ---------------------------------------------------------------------------
# Jaxpr walk with module attribution
# ---------------------------------------------------------------------------


def _sub_jaxprs(eqn) -> List[Tuple[Any, int]]:
    """Return [(jaxpr, multiplier)] for control-flow / call primitives."""
    name = eqn.primitive.name
    p = eqn.params
    if name == "scan":
        return [(p["jaxpr"].jaxpr, int(p["length"]))]
    if name == "while":
        return [(p["body_jaxpr"].jaxpr, 1), (p["cond_jaxpr"].jaxpr, 1)]
    if name == "cond":
        branches = p["branches"]
        costed = [(b.jaxpr, 1) for b in branches]
        return costed  # caller takes the max
    if "jaxpr" in p:
        j = p["jaxpr"]
        return [(getattr(j, "jaxpr", j), 1)]
    if "call_jaxpr" in p:
        j = p["call_jaxpr"]
        return [(getattr(j, "jaxpr", j), 1)]
    return []


def _scope_of(eqn) -> str:
    si = getattr(eqn, "source_info", None)
    stack = getattr(si, "name_stack", None)
    return str(stack) if stack is not None else ""


def _walk(jaxpr, mult: int, acc: Dict[str, List[int]],
          prefix: str = "") -> Tuple[int, int]:
    total_f = total_m = 0
    for eqn in jaxpr.eqns:
        subs = _sub_jaxprs(eqn)
        scope = _scope_of(eqn)
        full_scope = f"{prefix}/{scope}".strip("/") if scope else prefix
        if subs:
            if eqn.primitive.name == "cond":
                # upper bound: charge the most expensive branch
                best_f = best_m = 0
                best_acc: Dict[str, List[int]] = {}
                for sub, m in subs:
                    branch_acc: Dict[str, List[int]] = {}
                    f, mm = _walk(sub, mult * m, branch_acc, full_scope)
                    if f >= best_f:
                        best_f, best_m, best_acc = f, mm, branch_acc
                for scope2, (f2, m2) in best_acc.items():
                    b = acc.setdefault(scope2, [0, 0])
                    b[0] += f2
                    b[1] += m2
                total_f += best_f
                total_m += best_m
            else:
                for sub, m in subs:
                    f, mm = _walk(sub, mult * m, acc, full_scope)
                    total_f += f
                    total_m += mm
        else:
            f, m = _flops_of_eqn(eqn)
            f, m = f * mult, m * mult
            if f or m:
                bucket = acc.setdefault(full_scope, [0, 0])
                bucket[0] += f
                bucket[1] += m
                total_f += f
                total_m += m
    return total_f, total_m


def count_flops(fn: Callable, *args, **kwargs) -> Tuple[int, int, Dict[str, Tuple[int, int]]]:
    """Abstractly trace ``fn(*args, **kwargs)`` and return
    ``(flops, macs, {module_scope: (flops, macs)})``. No device computation runs."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    acc: Dict[str, List[int]] = {}
    f, m = _walk(closed.jaxpr, 1, acc)
    return f, m, {k: (v[0], v[1]) for k, v in acc.items()}


def xla_cost_analysis(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """XLA's own cost analysis from the *lowered* (not compiled) computation —
    no second compilation of the step function."""
    ca = jax.jit(fn).lower(*args, **kwargs).cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


# ---------------------------------------------------------------------------
# Pretty-printing helpers (reference: profiler.py number_to_string family)
# ---------------------------------------------------------------------------


def _to_string(num: float, units: Optional[str], precision: int,
               steps: List[Tuple[float, str]], suffix: str = "") -> str:
    if units is not None:
        for scale, name in steps:
            if name == units:
                return f"{round(num / scale, precision)} {units}{suffix}"
    for scale, name in steps:
        if abs(num) >= scale:
            return f"{round(num / scale, precision)} {name}{suffix}"
    return f"{round(num, precision)}{(' ' + suffix) if suffix else ''}"


_DEC = [(1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K"), (1, "")]


def flops_to_string(flops: float, units=None, precision=2) -> str:
    return _to_string(flops, units, precision, _DEC, suffix="FLOPS")


def macs_to_string(macs: float, units=None, precision=2) -> str:
    return _to_string(macs, units, precision, _DEC, suffix="MACs")


def params_to_string(n: float, units=None, precision=2) -> str:
    return _to_string(n, units, precision, _DEC)


def number_to_string(n: float, units=None, precision=2) -> str:
    return _to_string(n, units, precision, _DEC)


def duration_to_string(t: float, units=None, precision=2) -> str:
    steps = [(1, "s"), (1e-3, "ms"), (1e-6, "us")]
    return _to_string(t, units, precision, steps)


# ---------------------------------------------------------------------------
# FlopsProfiler — reference-shaped API
# ---------------------------------------------------------------------------


class FlopsProfiler:
    """Profiles a jittable step function.

    Usage (matches the reference's start/stop/print protocol)::

        prof = FlopsProfiler(fn)          # fn(params, batch, ...) -> loss
        prof.start_profile()
        fn(*args)                          # timed, real execution
        prof.stop_profile(*args)           # traces + counts
        prof.print_model_profile()
        prof.end_profile()
    """

    def __init__(self, fn: Optional[Callable] = None, params: Any = None):
        self.fn = fn
        # count params eagerly — keeping the live tree would pin device buffers
        # (which the engine's donated train step later invalidates anyway)
        self._n_params = 0 if params is None else sum(
            int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        self.reset()

    def reset(self):
        self._t0 = None
        self._duration = 0.0
        self._flops = 0
        self._macs = 0
        self._per_module: Dict[str, Tuple[int, int]] = {}
        self._xla: Dict[str, float] = {}

    # -- reference API surface ------------------------------------------------
    def start_profile(self, **_):
        self.reset()
        self._t0 = time.perf_counter()

    def stop_profile(self, *args, **kwargs):
        if self._t0 is not None:
            self._duration = time.perf_counter() - self._t0
            self._t0 = None
        if self.fn is not None and (args or kwargs):
            self._flops, self._macs, self._per_module = count_flops(
                self.fn, *args, **kwargs)
            try:
                self._xla = xla_cost_analysis(self.fn, *args, **kwargs)
            except Exception:  # cost analysis is best-effort (backend-dependent)
                self._xla = {}

    def end_profile(self):
        self.reset()

    def get_total_flops(self, as_string: bool = False):
        return flops_to_string(self._flops) if as_string else self._flops

    def get_total_macs(self, as_string: bool = False):
        return macs_to_string(self._macs) if as_string else self._macs

    def get_total_duration(self, as_string: bool = False):
        return duration_to_string(self._duration) if as_string else self._duration

    def get_total_params(self, as_string: bool = False):
        return params_to_string(self._n_params) if as_string else self._n_params

    def get_xla_flops(self) -> float:
        return float(self._xla.get("flops", 0.0))

    # -- tables ---------------------------------------------------------------
    def aggregate_by_depth(self, depth: int = -1) -> Dict[str, Tuple[int, int]]:
        """Collapse module scopes to ``depth`` path components (-1: leaf scopes)."""
        if depth < 0:
            return dict(self._per_module)
        out: Dict[str, List[int]] = {}
        for scope, (f, m) in self._per_module.items():
            key = "/".join(scope.split("/")[:depth]) if scope else ""
            b = out.setdefault(key, [0, 0])
            b[0] += f
            b[1] += m
        return {k: (v[0], v[1]) for k, v in out.items()}

    def print_model_profile(self, profile_step: int = 1, module_depth: int = -1,
                            top_modules: int = 1, detailed: bool = True,
                            output_file: Optional[str] = None):
        lines = [
            "-------------------------- DeepSpeed-TPU Flops Profiler "
            "--------------------------",
            f"Profile at step: {profile_step}",
            f"params:                 {self.get_total_params(as_string=True)}",
            f"fwd MACs:               {self.get_total_macs(as_string=True)}",
            f"fwd flops (analytic):   {self.get_total_flops(as_string=True)}",
        ]
        if self._xla.get("flops"):
            lines.append(f"fwd flops (XLA fused):  "
                         f"{flops_to_string(self._xla['flops'])}")
        if self._duration:
            lines.append(f"step latency:           "
                         f"{self.get_total_duration(as_string=True)}")
            lines.append(
                f"fwd FLOPS/s:            "
                f"{flops_to_string(self._flops / max(self._duration, 1e-12))}")
        if detailed and self._per_module:
            lines.append("")
            lines.append("per-module breakdown "
                         f"(depth={module_depth}, top {top_modules} per level):")
            table = self.aggregate_by_depth(module_depth)
            ranked = sorted(table.items(), key=lambda kv: -kv[1][0])
            shown = ranked if top_modules <= 0 else ranked[:top_modules]
            for scope, (f, m) in shown:
                pct = 100.0 * f / max(self._flops, 1)
                lines.append(f"  {scope or '<top-level>':<60} "
                             f"{flops_to_string(f):>14}  ({pct:4.1f}%)")
        text = "\n".join(lines)
        if jax.process_index() == 0:  # rank-gated, like the reference's log path
            if output_file:
                with open(output_file, "a") as fh:
                    fh.write(text + "\n")
            else:
                logger.info("\n" + text)
        return text


def get_model_profile(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
                      params: Any = None, print_profile: bool = True,
                      as_string: bool = False):
    """One-shot profile (reference: ``get_model_profile`` profiler.py:~1100):
    returns ``(flops, macs, params)``."""
    kwargs = kwargs or {}
    prof = FlopsProfiler(fn, params=params)
    prof.stop_profile(*args, **kwargs)  # abstract trace; no latency to report
    if print_profile:
        prof.print_model_profile()
    out = (prof.get_total_flops(as_string), prof.get_total_macs(as_string),
           prof.get_total_params(as_string))
    return out
