"""Profiling subsystem (reference: ``deepspeed/profiling/flops_profiler``)."""

from deepspeed_tpu.profiling.flops_profiler import (  # noqa: F401
    FlopsProfiler,
    count_flops,
    duration_to_string,
    flops_to_string,
    get_model_profile,
    macs_to_string,
    number_to_string,
    params_to_string,
    xla_cost_analysis,
)
