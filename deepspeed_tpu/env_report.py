"""``dstpu_report`` — environment/compatibility report (reference: ``bin/ds_report``
→ ``deepspeed/env_report.py``: op compatibility table + version/platform dump).

``--ckpt RUN_DIR`` additionally reports checkpoint/resume status for a run
directory: the ``latest`` pointer, which tag ``resume_from_latest`` would
actually restore (newest *committed*, integrity-verified), and a per-tag
commit/verification table — the first thing to look at when deciding whether
a preempted run can resume.
"""

import argparse
import importlib
import platform
import shutil
import subprocess
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
NO = f"{RED}[NO]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"


def _try_version(mod_name):
    try:
        mod = importlib.import_module(mod_name)
        return getattr(mod, "__version__", "unknown")
    except Exception:
        return None


def op_report():
    """Native/Pallas op compatibility table (reference env_report.py:op_report)."""
    from deepspeed_tpu.ops.op_builder import OPS, OpBuilder
    rows = []
    for name, builder in OPS.items():
        try:
            compatible = builder.is_compatible()
        except Exception:
            compatible = False
        rows.append((name, OKAY if compatible else NO))
    return rows


def debug_report():
    import jax
    rows = [
        ("python", platform.python_version()),
        ("platform", platform.platform()),
        ("jax", jax.__version__),
        ("jaxlib", _try_version("jaxlib") or "unknown"),
        ("flax", _try_version("flax")),
        ("optax", _try_version("optax")),
        ("orbax", _try_version("orbax.checkpoint")),
        ("numpy", _try_version("numpy")),
        ("deepspeed_tpu", _try_version("deepspeed_tpu")),
        ("g++", shutil.which("g++") or "not found"),
    ]
    try:
        devices = jax.devices()
        rows.append(("jax backend", devices[0].platform))
        rows.append(("device count", str(len(devices))))
        rows.append(("device kind", devices[0].device_kind))
    except Exception as e:  # no devices available
        rows.append(("jax backend", f"unavailable ({e})"))
    rows.extend(dslint_report())
    rows.extend(trace_report())
    rows.extend(plan_report())
    rows.extend(serve_plan_report())
    rows.extend(crossrank_report())
    rows.extend(reqtrace_report())
    rows.extend(memory_report())
    rows.extend(serving_report())
    rows.extend(fleet_report())
    rows.extend(elastic_report())
    rows.extend(comms_report())
    return rows


def elastic_report():
    """Elastic supervisor status from the agent's ``elastic_status.json``
    artifact ($DSTPU_ELASTIC_STATUS or ./elastic_status.json): current vs
    target vs checkpoint world, restart budget consumed, the last
    generation's exit classification, and the last shrink/regrow event."""
    import json
    import os
    import time
    try:
        from deepspeed_tpu.elasticity.agent import (DEFAULT_STATUS_PATH,
                                                    STATUS_ENV)
        artifact = os.environ.get(STATUS_ENV) or (
            DEFAULT_STATUS_PATH if os.path.exists(DEFAULT_STATUS_PATH)
            else None)
        hint = ("no artifact (run ElasticAgent with WorkerSpec.status_path "
                f"or set ${STATUS_ENV})")
        if not artifact or not os.path.exists(artifact):
            return [("elastic", hint)]
        with open(artifact) as f:
            st = json.load(f)
        rows = [("elastic world",
                 f"current {st.get('current_world')} / target "
                 f"{st.get('target_world')} / checkpoint "
                 f"{st.get('checkpoint_world') or '?'}")]
        rows.append(("elastic budget",
                     f"crashes {st.get('crash_restarts', 0)}/"
                     f"{st.get('max_restarts', '?')}, total relaunches "
                     f"{st.get('total_restarts', 0)}/"
                     f"{st.get('max_total_restarts', '?')}"))
        last = st.get("last_exit") or {}
        if last:
            rows.append(("elastic last exit",
                         f"{last.get('classification')} (codes "
                         f"{last.get('codes')}"
                         + (f", lost ranks {last['lost_ranks']}"
                            if last.get("lost_ranks") else "") + ")"))
        ev = st.get("last_event") or {}
        if ev:
            ago = ""
            if ev.get("at"):
                ago = f", {time.time() - ev['at']:.0f}s ago"
            rows.append(("elastic last event",
                         f"{ev.get('type')} world {ev.get('from_world')} -> "
                         f"{ev.get('to_world')} at gen "
                         f"{ev.get('generation')}{ago}"))
        pf = st.get("preflight") or {}
        if pf:
            rows.append(("elastic preflight",
                         f"world {pf.get('world')}: "
                         f"{'fits' if pf.get('fits') else 'DOES NOT FIT'}"
                         + (f", ladder: {pf['escalations']}"
                            if pf.get("escalations") else "")))
        return rows
    except Exception as e:   # the report must never die on tooling drift
        return [("elastic", f"unavailable ({e})")]


def memory_report():
    """dsmem status: per-device limit/in-use/peak, host RSS, ledger
    availability, and the watermark baseline's ratchet size — the memory
    counterpart of the dstrace/plan rows."""
    import os
    rows = []
    try:
        # single source for stat collection (utils.memory, the reference
        # see_memory_usage substrate) — this report only renders it
        from deepspeed_tpu.utils.memory import get_memory_stats
        stats = get_memory_stats()
        dev_rows = [
            (f"memory {dev}",
             f"{s['bytes_in_use_gb']:.2f}GB in use / "
             f"peak {s['peak_bytes_in_use_gb']:.2f}GB / "
             f"limit {s['bytes_limit_gb']:.2f}GB")
            for dev, s in stats.items()
            if dev != "host" and any(v > 0 for v in s.values())]
        rows.extend(dev_rows or [("memory devices",
                                  "no allocator stats (CPU backend)")])
        if "host" in stats:
            rows.append(("memory host rss",
                         f"{stats['host']['rss_gb']:.2f}GB"))
    except Exception as e:
        rows.append(("memory devices", f"unavailable ({e})"))
    try:
        from deepspeed_tpu.telemetry.memory import (MEM_BASELINE_NAME,
                                                    find_mem_baseline,
                                                    load_mem_baseline)
        rows.append(("mem ledger", "available (bin/dstpu mem --preflight "
                                   "CONFIG --params N)"))
        bl = find_mem_baseline(os.path.dirname(os.path.abspath(__file__)))
        if bl is None:
            rows.append(("mem baseline", f"not found ({MEM_BASELINE_NAME})"))
        else:
            n = len(load_mem_baseline(bl).get("entries", {}))
            rows.append(("mem baseline",
                         f"{n} phase{'s' if n != 1 else ''} ratcheted "
                         f"({bl})"))
    except Exception as e:   # the report must never die on tooling drift
        rows.append(("dsmem", f"unavailable ({e})"))
    return rows


def trace_report():
    """dstrace status: whether tracing is active (DSTPU_TRACE or
    programmatic) and how full the event ring is."""
    try:
        from deepspeed_tpu.telemetry import TRACE_ENV, get_tracer
        import os
        t = get_tracer()
        if not t.enabled:
            return [("dstrace", f"off (set {TRACE_ENV}=trace.json)")]
        dest = os.environ.get(TRACE_ENV, "<programmatic>")
        return [("dstrace", f"on -> {dest} ({len(t.events_snapshot())}/"
                            f"{t.capacity} events, {t.dropped()} dropped)")]
    except Exception as e:
        return [("dstrace", f"unavailable ({e})")]


def plan_report():
    """Step-time planning status: the last ``dstpu plan`` artifact (path +
    headline attribution) and how many stages the regression baseline
    ratchets — the measurement-discipline counterpart to the dstrace row."""
    import json
    import os
    rows = []
    try:
        from deepspeed_tpu.telemetry.attribution import (
            PLAN_ARTIFACT_ENV, DEFAULT_PLAN_ARTIFACT, PLAN_BASELINE_NAME,
            STAGES, find_plan_baseline, load_plan_baseline)
        artifact = os.environ.get(PLAN_ARTIFACT_ENV) or (
            DEFAULT_PLAN_ARTIFACT if os.path.exists(DEFAULT_PLAN_ARTIFACT)
            else None)
        if artifact and os.path.exists(artifact):
            with open(artifact) as f:
                rep = json.load(f)
            agg = rep.get("aggregate", {})
            if agg:
                dominant = max(
                    (s for s in STAGES if s in agg),
                    key=lambda s: agg[s].get("share", 0.0))
                rows.append(("dstpu plan", f"{artifact} ({dominant} "
                             f"{agg[dominant]['share'] * 100:.0f}% of step "
                             f"time, p50 step {rep.get('step_ms_p50')}ms, "
                             f"{len(rep.get('proposals', []))} proposals)"))
            else:
                rows.append(("dstpu plan", f"{artifact} (no aggregate)"))
        else:
            rows.append(("dstpu plan",
                         f"no artifact (bin/dstpu plan trace.json --out "
                         f"{DEFAULT_PLAN_ARTIFACT}, or set "
                         f"${PLAN_ARTIFACT_ENV})"))
        bl = find_plan_baseline(os.path.dirname(os.path.abspath(__file__)))
        if bl is None:
            rows.append(("plan baseline", f"not found ({PLAN_BASELINE_NAME})"))
        else:
            n = len(load_plan_baseline(bl).get("entries", {}))
            rows.append(("plan baseline",
                         f"{n} stage{'s' if n != 1 else ''} ratcheted ({bl})"))
        return rows
    except Exception as e:   # the report must never die on tooling drift
        return [("dstpu plan", f"unavailable ({e})")]


def serve_plan_report():
    """Serving-tick planning status: the last ``dstpu plan --serve``
    artifact (dominant stage + p50 tick ms + proposal count + the
    proposal->verify verdict tally) and the serve-plan baseline's ratchet
    size — the serving counterpart of the plan rows."""
    import json
    import os
    rows = []
    try:
        from deepspeed_tpu.telemetry.serve_attribution import (
            DEFAULT_SERVE_PLAN_ARTIFACT, SERVE_PLAN_ARTIFACT_ENV,
            SERVE_PLAN_BASELINE_NAME, STAGES, find_serve_plan_baseline,
            load_serve_plan_baseline)
        artifact = os.environ.get(SERVE_PLAN_ARTIFACT_ENV) or (
            DEFAULT_SERVE_PLAN_ARTIFACT
            if os.path.exists(DEFAULT_SERVE_PLAN_ARTIFACT) else None)
        if artifact and os.path.exists(artifact):
            with open(artifact) as f:
                rep = json.load(f)
            agg = rep.get("aggregate", {})
            if agg:
                dominant = max(
                    (s for s in STAGES if s in agg),
                    key=lambda s: agg[s].get("share", 0.0))
                tally = ""
                verdicts = rep.get("verifications") or []
                if verdicts:
                    counts = {}
                    for v in verdicts:
                        key = v.get("verdict", "?")
                        counts[key] = counts.get(key, 0) + 1
                    tally = (", verdicts "
                             f"{counts.get('verified', 0)} verified/"
                             f"{counts.get('refuted', 0)} refuted/"
                             f"{counts.get('unverified', 0)} unverified")
                rows.append(("serve plan", f"{artifact} ({dominant} "
                             f"{agg[dominant]['share'] * 100:.0f}% of tick "
                             f"time, p50 tick {rep.get('tick_ms_p50')}ms, "
                             f"{len(rep.get('proposals', []))} proposals"
                             f"{tally})"))
            else:
                rows.append(("serve plan", f"{artifact} (no aggregate)"))
        else:
            rows.append(("serve plan",
                         f"no artifact (bin/dstpu plan --serve report.json "
                         f"--out {DEFAULT_SERVE_PLAN_ARTIFACT}, or set "
                         f"${SERVE_PLAN_ARTIFACT_ENV})"))
        bl = find_serve_plan_baseline(os.path.dirname(
            os.path.abspath(__file__)))
        if bl is None:
            rows.append(("serve plan baseline",
                         f"not found ({SERVE_PLAN_BASELINE_NAME})"))
        else:
            n = len(load_serve_plan_baseline(bl).get("entries", {}))
            rows.append(("serve plan baseline",
                         f"{n} stage{'s' if n != 1 else ''} ratcheted "
                         f"({bl})"))
        return rows
    except Exception as e:   # the report must never die on tooling drift
        return [("serve plan", f"unavailable ({e})")]


def crossrank_report():
    """Cross-rank merged-trace status: the last ``dstpu plan --cross-rank``
    artifact ($DSTPU_CROSSRANK_ARTIFACT or ./crossrank.json — ranks
    joined, max residual clock skew, dominant straggler) and the crossrank
    baseline's ratchet size — the multi-process counterpart of the
    plan/serve-plan rows."""
    import json
    import os
    rows = []
    try:
        from deepspeed_tpu.telemetry.crossrank import (
            CROSSRANK_ARTIFACT_ENV, CROSSRANK_BASELINE_NAME,
            DEFAULT_CROSSRANK_ARTIFACT, find_crossrank_baseline,
            load_crossrank_baseline)
        artifact = os.environ.get(CROSSRANK_ARTIFACT_ENV) or (
            DEFAULT_CROSSRANK_ARTIFACT
            if os.path.exists(DEFAULT_CROSSRANK_ARTIFACT) else None)
        if artifact and os.path.exists(artifact):
            with open(artifact) as f:
                rep = json.load(f)
            dom = rep.get("dominant_straggler")
            rows.append(("cross-rank",
                         f"{artifact} (ranks {rep.get('ranks')}, "
                         f"{rep.get('matched', 0)} matched collectives, "
                         f"max residual skew "
                         f"{rep.get('max_residual_skew_us', 0.0):.0f}us, "
                         f"dominant straggler "
                         f"{'rank ' + str(dom) if dom is not None else 'none'})"
                         ))
        else:
            rows.append(("cross-rank",
                         "no artifact (bin/dstpu trace merge r0.json "
                         "r1.json, then bin/dstpu plan --cross-rank "
                         f"merged_trace.json --out "
                         f"{DEFAULT_CROSSRANK_ARTIFACT}, or set "
                         f"${CROSSRANK_ARTIFACT_ENV})"))
        bl = find_crossrank_baseline(os.path.dirname(
            os.path.abspath(__file__)))
        if bl is None:
            rows.append(("cross-rank baseline",
                         f"not found ({CROSSRANK_BASELINE_NAME})"))
        else:
            n = len(load_crossrank_baseline(bl).get("entries", {}))
            rows.append(("cross-rank baseline",
                         f"{n} rank{'s' if n != 1 else ''} ratcheted "
                         f"({bl})"))
        return rows
    except Exception as e:   # the report must never die on tooling drift
        return [("cross-rank", f"unavailable ({e})")]


def reqtrace_report():
    """Per-request fleet-timeline status: the last ``dstpu reqtrace``
    artifact ($DSTPU_REQTRACE_ARTIFACT or ./reqtrace.json — requests
    stitched, orphan spans, flight dumps folded, worst tie-out error)
    plus the dstpu_req_* SLO histogram family inventory — the
    request-scoped counterpart of the cross-rank rows."""
    import json
    import os
    rows = []
    try:
        from deepspeed_tpu.telemetry.reqtrace import (
            DEFAULT_REQTRACE_ARTIFACT, REQTRACE_ARTIFACT_ENV,
            TIE_OUT_TOLERANCE)
        artifact = os.environ.get(REQTRACE_ARTIFACT_ENV) or (
            DEFAULT_REQTRACE_ARTIFACT
            if os.path.exists(DEFAULT_REQTRACE_ARTIFACT) else None)
        if artifact and os.path.exists(artifact):
            with open(artifact) as f:
                rep = json.load(f)
            err = rep.get("max_tie_out_error", 0.0)
            verdict = (f"{err * 100:.2f}% max tie-out"
                       + ("" if err <= TIE_OUT_TOLERANCE
                          else f" (OVER {TIE_OUT_TOLERANCE * 100:.0f}%)"))
            rows.append(("reqtrace",
                         f"{artifact} ({rep.get('requests_stitched', 0)} "
                         f"requests stitched from "
                         f"{len(rep.get('sources', []))} dumps, "
                         f"{rep.get('orphan_spans', 0)} orphan spans, "
                         f"{rep.get('flight_dumps', 0)} flight dumps / "
                         f"{rep.get('recovered_requests', 0)} requests "
                         f"recovered, {verdict})"))
        else:
            rows.append(("reqtrace",
                         "no artifact (bin/dstpu reqtrace router.json "
                         "replica*.json flight_replica*.json --out "
                         f"{DEFAULT_REQTRACE_ARTIFACT}, or set "
                         f"${REQTRACE_ARTIFACT_ENV})"))
        # the SLO histogram families /metrics exports (and bench_serve
        # proves conservation over) — inventory, not live values
        from deepspeed_tpu.serving.metrics import REQ_HIST_FAMILIES
        rows.append(("slo histograms",
                     f"{len(REQ_HIST_FAMILIES)} dstpu_req_* families ("
                     + ", ".join(f.split("dstpu_req_")[1].rsplit(
                         "_seconds", 1)[0]
                         for f, _attr, _h in REQ_HIST_FAMILIES) + ")"))
        return rows
    except Exception as e:   # the report must never die on tooling drift
        return [("reqtrace", f"unavailable ({e})")]


def serving_report():
    """Serving capacity-efficiency status: the prefix-cache hit ratio
    and host-tier compression from the last bench_serve artifact
    (``$DSTPU_SERVE_REPORT`` or ./bench_serve.json) — the serving
    counterpart of the plan/mem artifact rows."""
    import json
    import os
    artifact = os.environ.get("DSTPU_SERVE_REPORT") or (
        "bench_serve.json" if os.path.exists("bench_serve.json") else None)
    hint = ("no artifact (bin/dstpu_bench_serve --scenario multi_turn "
            "--json bench_serve.json, or set $DSTPU_SERVE_REPORT)")
    try:
        if not artifact or not os.path.exists(artifact):
            return [("prefix cache", hint)]
        with open(artifact) as f:
            rep = json.load(f)
        prefix = rep.get("prefix") or {}
        if not prefix:
            return [("prefix cache",
                     f"{artifact} (no prefix section — cache disabled?)")]
        name = (rep.get("scenario") or {}).get("name", "?")
        rows = [("prefix cache",
                 f"{artifact} ({name}: hit ratio "
                 f"{prefix.get('prefix_hit_ratio', 0.0) * 100:.0f}%, "
                 f"{prefix.get('prefill_tokens_saved', 0)}/"
                 f"{prefix.get('prefill_tokens_total', 0)} prefill "
                 f"tokens saved)")]
        comp = prefix.get("host_compression_ratio", 1.0)
        rows.append(("host kv tier",
                     f"compression {comp:.1f}x"
                     f"{' (full width)' if comp == 1.0 else ''}"))
        return rows
    except Exception as e:   # the report must never die on tooling drift
        return [("prefix cache", f"unavailable ({e})")]


def fleet_report():
    """Fleet-router status from the router's status artifact
    ($DSTPU_FLEET_STATUS or ./fleet_status.json): replicas in rotation /
    draining / lost, and the failover-proof counters (reroutes with zero
    requests_lost is the zero-loss invariant holding in production)."""
    import json
    import os
    try:
        from deepspeed_tpu.serving.fleet import FLEET_STATUS_ENV
        artifact = os.environ.get(FLEET_STATUS_ENV) or (
            "fleet_status.json" if os.path.exists("fleet_status.json")
            else None)
        hint = (f"no artifact (bin/dstpu_fleet --status-path "
                f"fleet_status.json, or set ${FLEET_STATUS_ENV})")
        if not artifact or not os.path.exists(artifact):
            return [("fleet", hint)]
        with open(artifact) as f:
            st = json.load(f)
        reps = st.get("replicas") or []
        c = st.get("counters") or {}
        rows = [("fleet replicas",
                 f"{sum(1 for r in reps if r.get('in_rotation'))} in "
                 f"rotation / {sum(1 for r in reps if r.get('draining'))} "
                 f"draining / {sum(1 for r in reps if r.get('lost'))} lost "
                 f"of {len(reps)} ({artifact})")]
        rows.append(("fleet routing",
                     f"{c.get('completed', 0)}/{c.get('submitted', 0)} "
                     f"completed, {c.get('affinity_hits', 0)} affinity "
                     f"hits, {c.get('spills', 0)} spills "
                     f"({c.get('client_sheds', 0)} client 429s of "
                     f"{c.get('first_choice_sheds', 0)} first-choice "
                     f"sheds)"))
        rows.append(("fleet failover",
                     f"{c.get('reroutes', 0)} reroutes "
                     f"({c.get('recomputed_tokens', 0)} tokens recomputed), "
                     f"{c.get('requests_lost', 0)} requests lost, "
                     f"{c.get('replicas_lost', 0)} replicas lost / "
                     f"{c.get('relaunches', 0)} relaunched, "
                     f"{c.get('handoffs', 0)} prefix handoffs"))
        return rows
    except Exception as e:   # the report must never die on tooling drift
        return [("fleet", f"unavailable ({e})")]


def comms_report():
    """Per-op communication totals recorded by the CommsLogger in THIS
    process (traced analytic volume + eager timed ops)."""
    try:
        from deepspeed_tpu.comm.comms_logging import get_comms_logger
        return get_comms_logger().env_report_rows()
    except Exception as e:
        return [("comms", f"unavailable ({e})")]


def dslint_report():
    """Static-analysis surface: how many rules enforce the TPU bug classes
    and how much grandfathered debt the checked-in baseline carries (0 is
    the healthy steady state — new findings fail tier-1)."""
    import os
    try:
        from deepspeed_tpu.tools.dslint import (find_default_baseline,
                                                get_rules, load_baseline)
        rows = [("dslint rules", str(len(get_rules())))]
        bl = find_default_baseline(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        if bl is None:
            rows.append(("dslint baseline", "not found (installed package?)"))
        else:
            n = len(load_baseline(bl).get("entries", []))
            rows.append(("dslint baseline",
                         f"{n} grandfathered finding{'s' if n != 1 else ''} "
                         f"({bl})"))
        try:
            from deepspeed_tpu.tools.dslint.callgraph import \
                build_graph_from_sources
            from deepspeed_tpu.tools.dslint.engine import iter_python_files
            from deepspeed_tpu.tools.dslint.hotpath import (ESCAPE_HATCHES,
                                                            HOT_ROOTS)
            pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            files = []
            for p in iter_python_files(
                    [os.path.join(pkg, "deepspeed_tpu")]):
                rel = os.path.relpath(p, pkg).replace(os.sep, "/")
                with open(p, encoding="utf-8") as fh:
                    files.append((rel, fh.read()))
            g = build_graph_from_sources(files)
            st = g.stats()
            roots = sorted(k for k in (g.resolve(r.path, r.qualname)
                                       for r in HOT_ROOTS) if k)
            prune = {k for k in (g.resolve(h.path, h.qualname)
                                 for h in ESCAPE_HATCHES
                                 if h.mode == "prune") if k}
            reached = g.reachable_from(roots, prune=prune)
            rows.append(("dslint callgraph",
                         f"{st['functions']} functions, {st['edges']} "
                         f"edges, {st['unresolved_calls']} dynamic calls "
                         f"degraded to stats"))
            rows.append(("dslint hot taint",
                         f"{len(roots)}/{len(HOT_ROOTS)} roots resolved -> "
                         f"{len(reached)} functions "
                         f"({100 * len(reached) // max(st['functions'], 1)}"
                         f"% of package) under DS002"))
        except Exception as e:    # graph stats are best-effort decoration
            rows.append(("dslint callgraph", f"unavailable ({e})"))
        return rows
    except Exception as e:   # the report must never die on tooling drift
        return [("dslint", f"unavailable ({e})")]


def checkpoint_report(run_dir):
    """Latest-committed-checkpoint status for a run directory. Returns
    ``(summary_rows, tag_rows)``: the resume decision up top, then one row
    per tag — committed+verified / torn (never loaded) / legacy."""
    import os

    from deepspeed_tpu.checkpoint.engine import (
        LATEST_FILE, MANIFEST_FILE, CheckpointCorruptionError,
        read_latest_tag, verify_manifest)
    from deepspeed_tpu.resilience.checkpointing import _tag_meta, list_tags
    run_dir = os.path.abspath(run_dir)
    pointed = read_latest_tag(run_dir)
    # one verification pass over every tag; the resume decision derives
    # from the same results (a second find_latest_committed scan would
    # re-read every multi-GB checkpoint end to end)
    tags, clean = [], []
    for tag in list_tags(run_dir):
        path = os.path.join(run_dir, tag)
        meta = _tag_meta(run_dir, tag)
        step = meta.get("global_steps", "?")
        if not os.path.exists(os.path.join(path, "ds_meta.json")):
            status = f"{NO} uncommitted (no ds_meta.json)"
        elif not os.path.exists(os.path.join(path, MANIFEST_FILE)):
            status = f"{WARNING} committed, no manifest (legacy, unverified)"
        else:
            try:
                verify_manifest(path)
                status = f"{OKAY} committed + verified"
                clean.append(tag)
            except CheckpointCorruptionError as e:
                status = f"{NO} TORN ({e})"
        tags.append((f"{tag} (step {step})", status))
    # mirror find_latest_committed's preference: the pointer when clean,
    # else the newest clean tag (list_tags is already newest-first)
    resume_tag = pointed if pointed in clean else (clean[0] if clean else None)
    summary = [
        ("run dir", run_dir),
        ("latest pointer", pointed or f"{NO} (no '{LATEST_FILE}' file)"),
        ("resume_from_latest would load",
         resume_tag if resume_tag else f"{NO} (no committed checkpoint)"),
    ]
    if pointed and resume_tag and pointed != resume_tag:
        summary.append(("pointer status",
                        f"{WARNING} latest points at a torn/missing tag; "
                        f"falling back to '{resume_tag}'"))
    return summary, tags


def main(hide_operator_status=False, hide_errors_and_warnings=False,
         ckpt_dir=None):
    print("-" * 60)
    print("DeepSpeed-TPU C++/Pallas op report")
    print("-" * 60)
    if not hide_operator_status:
        for name, status in op_report():
            print(f"{name:.<40} {status}")
    print("-" * 60)
    print("DeepSpeed-TPU general environment info:")
    print("-" * 60)
    for key, val in debug_report():
        print(f"{key:.<30} {val}")
    if ckpt_dir is not None:
        print("-" * 60)
        print("Checkpoint / resume status:")
        print("-" * 60)
        summary, tags = checkpoint_report(ckpt_dir)
        for key, val in summary:
            print(f"{key:.<34} {val}")
        for tag, status in tags:
            print(f"  {tag:.<32} {status}")
        if not tags:
            print("  (no checkpoint tags found)")
    return 0


def cli_main():
    parser = argparse.ArgumentParser(prog="dstpu_report")
    parser.add_argument("--ckpt", metavar="RUN_DIR", default=None,
                        help="also report latest-committed-checkpoint status "
                             "for this run/checkpoint directory")
    args = parser.parse_args()
    sys.exit(main(ckpt_dir=args.ckpt))


if __name__ == "__main__":
    cli_main()
