"""``dstpu_report`` — environment/compatibility report (reference: ``bin/ds_report``
→ ``deepspeed/env_report.py``: op compatibility table + version/platform dump).
"""

import importlib
import platform
import shutil
import subprocess
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
NO = f"{RED}[NO]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"


def _try_version(mod_name):
    try:
        mod = importlib.import_module(mod_name)
        return getattr(mod, "__version__", "unknown")
    except Exception:
        return None


def op_report():
    """Native/Pallas op compatibility table (reference env_report.py:op_report)."""
    from deepspeed_tpu.ops.op_builder import OPS, OpBuilder
    rows = []
    for name, builder in OPS.items():
        try:
            compatible = builder.is_compatible()
        except Exception:
            compatible = False
        rows.append((name, OKAY if compatible else NO))
    return rows


def debug_report():
    import jax
    rows = [
        ("python", platform.python_version()),
        ("platform", platform.platform()),
        ("jax", jax.__version__),
        ("jaxlib", _try_version("jaxlib") or "unknown"),
        ("flax", _try_version("flax")),
        ("optax", _try_version("optax")),
        ("orbax", _try_version("orbax.checkpoint")),
        ("numpy", _try_version("numpy")),
        ("deepspeed_tpu", _try_version("deepspeed_tpu")),
        ("g++", shutil.which("g++") or "not found"),
    ]
    try:
        devices = jax.devices()
        rows.append(("jax backend", devices[0].platform))
        rows.append(("device count", str(len(devices))))
        rows.append(("device kind", devices[0].device_kind))
    except Exception as e:  # no devices available
        rows.append(("jax backend", f"unavailable ({e})"))
    return rows


def main(hide_operator_status=False, hide_errors_and_warnings=False):
    print("-" * 60)
    print("DeepSpeed-TPU C++/Pallas op report")
    print("-" * 60)
    if not hide_operator_status:
        for name, status in op_report():
            print(f"{name:.<40} {status}")
    print("-" * 60)
    print("DeepSpeed-TPU general environment info:")
    print("-" * 60)
    for key, val in debug_report():
        print(f"{key:.<30} {val}")
    return 0


def cli_main():
    sys.exit(main())


if __name__ == "__main__":
    cli_main()
