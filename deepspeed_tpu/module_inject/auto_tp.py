"""Automatic tensor parallelism.

Reference analog: ``deepspeed/module_inject/auto_tp.py:189`` (``AutoTP``) — for models
without a hand-written policy it walks the module graph, classifies every ``Linear``
as all-reduce (row) or partitioned (column) by name heuristics
(``tp_parser``/``update_policy_list``), and swaps in ``LinearAllreduce``/
``LinearLayer`` shards.

TPU redesign: the classifier runs over the *parameter pytree* (there is no module
graph to mutate — sharding specs do the work). ``AutoTP.infer_rules`` first tries the
per-arch policy registry, then falls back to generic name heuristics covering the
common transformer vocabulary; anything unmatched stays replicated, which is always
correct (just not sharded).
"""

from typing import Any, Callable, Optional

import jax

from deepspeed_tpu.module_inject.policies import (
    POLICIES,
    TENSOR_AXIS,
    TPPolicy,
    get_policy,
)
from deepspeed_tpu.utils.logging import logger

# Generic fallback vocabulary (reference: auto_tp.py tp_parser's allreduce-name list,
# e.g. 'o_proj', 'out_proj', 'down_proj', 'dense_4h_to_h', 'attention.dense' ...)
GENERIC_POLICY = TPPolicy(
    "generic",
    column=("q_proj", "k_proj", "v_proj", "query", "key", "value",
            "gate_proj", "up_proj", "fc1", "fc_in", "dense_h_to_4h",
            "wq/", "wk/", "wv/", "w_gate", "w_up", "wi/", "col_"),
    row=("o_proj", "out_proj", "down_proj", "fc2", "fc_out", "dense_4h_to_h",
         "attention/dense", "self_attention/dense", "wo/", "w_down", "row_"),
    fused_qkv=("query_key_value", "qkv_proj", "c_attn", "W_pack"),
)


class AutoTP:
    """Policy resolution + generic fallback (reference: AutoTP auto_tp.py:189)."""

    @staticmethod
    def get_policy(model_or_arch) -> Optional[TPPolicy]:
        if isinstance(model_or_arch, str):
            return get_policy(model_or_arch)
        # flax module / any object: try class name, then HF-style config.model_type
        pol = get_policy(type(model_or_arch).__name__)
        if pol is None:
            mt = getattr(getattr(model_or_arch, "config", None), "model_type", None)
            if isinstance(mt, str):
                pol = get_policy(mt)
        return pol

    @staticmethod
    def infer_rules(model_or_arch=None, params: Any = None) -> Callable:
        """Return a ``tensor_rules`` callable: the arch policy when known, else the
        generic heuristic. With ``params`` given, logs how much matched (the
        reference prints the resolved policy list the same way)."""
        policy = None
        if model_or_arch is not None:
            policy = AutoTP.get_policy(model_or_arch)
        if policy is None:
            policy = GENERIC_POLICY
        rules = policy.tensor_rules()
        if params is not None:
            leaves = jax.tree_util.tree_flatten_with_path(params)[0]
            matched = sum(1 for path, leaf in leaves
                          if rules(path, leaf) is not None)
            logger.info(f"AutoTP[{policy.arch}]: sharding {matched}/{len(leaves)} "
                        f"parameter tensors over the '{TENSOR_AXIS}' axis")
        return rules

    @staticmethod
    def supported_archs() -> list:
        return sorted(POLICIES)
