"""Tensor-parallel injection policies.

Reference analog: ``deepspeed/module_inject/replace_policy.py`` + the per-arch
containers (``module_inject/containers/{llama,bloom,gptneox,opt,...}.py``) — each
policy tells the injector which sub-layers are column-parallel (qkv/up projections),
which are row-parallel (output/down projections), and how fused-QKV weights split.

TPU redesign: a policy compiles down to a ``tensor_rules(path, leaf) -> PartitionSpec``
function (the contract consumed by ``runtime/zero/partition.py build_param_shardings``
and the engines) instead of swapping ``nn.Module`` objects — XLA inserts the
all-gather/all-reduce collectives that ``LinearLayer``/``LinearAllreduce`` hand-code
in the reference (``module_inject/layers.py``).
"""

import dataclasses
from typing import Callable, Optional, Tuple

import numpy as np
from jax.sharding import PartitionSpec

TENSOR_AXIS = "tensor"


@dataclasses.dataclass(frozen=True)
class TPPolicy:
    """Name-pattern driven TP sharding policy for one model architecture.

    Patterns are substrings matched against the '/'-joined parameter path.
    ``column``: shard the output (last) dim; ``row``: shard the input (first) dim;
    ``vocab_in``: embedding tables [vocab, embed] sharded on dim 0;
    ``vocab_out``: lm-head kernels [embed, vocab] sharded on the last dim;
    ``fused_qkv``: column-parallel fused QKV weights — need
    ``fusedqkv_utils.split_fused_qkv`` at weight-load time when head counts differ
    (GQA), sharded on the last dim like any column layer.
    """

    arch: str
    column: Tuple[str, ...] = ()
    row: Tuple[str, ...] = ()
    vocab_in: Tuple[str, ...] = ("embed_tokens", "word_embeddings", "wte", "embed/embedding")
    vocab_out: Tuple[str, ...] = ("lm_head", "embed_out")
    fused_qkv: Tuple[str, ...] = ()

    def tensor_rules(self) -> Callable:
        """Compile to the ``tensor_rules(path, leaf)`` contract."""

        def rules(path, leaf) -> Optional[PartitionSpec]:
            name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                            for k in path)
            ndim = np.ndim(leaf)
            if ndim == 0:
                return None
            if any(p in name for p in self.fused_qkv) or \
                    any(p in name for p in self.column):
                if ndim == 1:  # bias of a column layer: sharded with the outputs
                    return PartitionSpec(TENSOR_AXIS)
                return PartitionSpec(*([None] * (ndim - 1)), TENSOR_AXIS)
            if any(p in name for p in self.row):
                if ndim == 1:  # bias of a row layer: added post-reduce, replicated
                    return None
                return PartitionSpec(TENSOR_AXIS, *([None] * (ndim - 1)))
            if any(p in name for p in self.vocab_in) and ndim >= 2:
                return PartitionSpec(TENSOR_AXIS, *([None] * (ndim - 1)))
            if any(p in name for p in self.vocab_out) and ndim >= 2:
                return PartitionSpec(*([None] * (ndim - 1)), TENSOR_AXIS)
            return None

        return rules


# ---------------------------------------------------------------------------
# Registry (reference: replace_policy.py replace_policies list + containers/)
# Patterns include both HF module names and our native model zoo's names.
# ---------------------------------------------------------------------------

_LLAMA_LIKE = dict(
    column=("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj",
            "wq/", "wk/", "wv/", "w_gate", "w_up"),
    row=("o_proj", "down_proj", "wo/", "w_down"),
)

POLICIES = {
    "llama": TPPolicy("llama", **_LLAMA_LIKE),
    "mistral": TPPolicy("mistral", **_LLAMA_LIKE),
    "internlm": TPPolicy("internlm", **_LLAMA_LIKE),
    "baichuan": TPPolicy("baichuan", fused_qkv=("W_pack",), **_LLAMA_LIKE),
    "qwen2": TPPolicy("qwen2", **_LLAMA_LIKE),
    "mixtral": TPPolicy(
        "mixtral",
        column=_LLAMA_LIKE["column"] + ("w1/", "w3/", "experts/wi"),
        row=_LLAMA_LIKE["row"] + ("w2/", "experts/wo")),
    "qwen2_moe": TPPolicy(
        "qwen2_moe",
        column=_LLAMA_LIKE["column"] + ("w1/", "w3/", "experts/wi", "shared_expert"),
        row=_LLAMA_LIKE["row"] + ("w2/", "experts/wo")),
    "phi": TPPolicy(
        "phi",
        column=("q_proj", "k_proj", "v_proj", "fc1"),
        row=("dense", "fc2")),
    "phi3": TPPolicy(
        "phi3",
        column=("gate_up_proj",),
        row=("o_proj", "down_proj"),
        fused_qkv=("qkv_proj",)),
    "falcon": TPPolicy(
        "falcon",
        column=("dense_h_to_4h",),
        row=("self_attention/dense", "dense_4h_to_h"),
        fused_qkv=("query_key_value",)),
    "gpt_neox": TPPolicy(
        "gpt_neox",
        column=("dense_h_to_4h",),
        row=("attention/dense", "dense_4h_to_h"),
        fused_qkv=("query_key_value",)),
    "bloom": TPPolicy(
        "bloom",
        column=("dense_h_to_4h",),
        row=("self_attention/dense", "dense_4h_to_h"),
        fused_qkv=("query_key_value",)),
    "gpt2": TPPolicy(
        "gpt2",
        column=("c_fc",),
        row=("attn/c_proj", "mlp/c_proj"),
        fused_qkv=("c_attn",)),
    "gptj": TPPolicy(
        "gptj",
        column=("q_proj", "k_proj", "v_proj", "fc_in"),
        row=("out_proj", "fc_out")),
    "opt": TPPolicy(
        "opt",
        column=("q_proj", "k_proj", "v_proj", "fc1"),
        row=("out_proj", "fc2")),
    "bert": TPPolicy(
        "bert",
        column=("query", "key", "value", "intermediate/dense"),
        row=("attention/output/dense", "output/dense")),
    "distilbert": TPPolicy(
        "distilbert",
        column=("q_lin", "k_lin", "v_lin", "lin1"),
        row=("out_lin", "lin2")),
    "gpt_neo": TPPolicy(
        "gpt_neo",
        column=("q_proj", "k_proj", "v_proj", "c_fc"),
        row=("out_proj", "c_proj")),
    "gpt_bigcode": TPPolicy(      # starcoder: MQA fused qkv
        "gpt_bigcode",
        column=("c_fc",),
        row=("attn/c_proj", "mlp/c_proj"),
        fused_qkv=("c_attn",)),
    "codegen": TPPolicy(
        "codegen",
        column=("fc_in",),
        row=("out_proj", "fc_out"),
        fused_qkv=("qkv_proj",)),
    "gemma": TPPolicy("gemma", **_LLAMA_LIKE),
    "stablelm": TPPolicy("stablelm", **_LLAMA_LIKE),
    "chatglm": TPPolicy(
        "chatglm",
        column=("dense_h_to_4h",),
        row=("self_attention/dense", "dense_4h_to_h"),
        fused_qkv=("query_key_value",)),
    "megatron_gpt": TPPolicy(
        "megatron_gpt",
        column=("dense_h_to_4h",),
        row=("attention/dense", "dense_4h_to_h"),
        fused_qkv=("query_key_value",)),
    "clip": TPPolicy(
        "clip",
        column=("q_proj", "k_proj", "v_proj", "fc1"),
        row=("out_proj", "fc2")),
    "t5": TPPolicy(
        "t5",
        # scoped patterns: bare "k/" would false-match "block/0"
        column=("SelfAttention/q", "SelfAttention/k", "SelfAttention/v",
                "EncDecAttention/q", "EncDecAttention/k", "EncDecAttention/v",
                "DenseReluDense/wi"),
        row=("SelfAttention/o", "EncDecAttention/o", "DenseReluDense/wo"),
        vocab_in=("shared/", "embed_tokens"),
        vocab_out=("lm_head",)),
    "whisper": TPPolicy(
        "whisper",
        column=("q_proj", "k_proj", "v_proj", "fc1"),
        row=("out_proj", "fc2")),
    # diffusers UNet2DConditionModel (reference containers/unet.py): only the
    # cross/self-attention projections and GEGLU net shard; convs replicate
    "unet": TPPolicy(
        "unet",
        column=("to_q", "to_k", "to_v", "ff/net_0/proj", "net/0/proj"),
        row=("to_out/0", "to_out_0", "ff/net_2", "net/2"),
        vocab_in=(), vocab_out=()),
    # diffusers AutoencoderKL (reference containers/vae.py): attention block
    # projections shard, conv encoder/decoder replicates
    "vae": TPPolicy(
        "vae",
        column=("to_q", "to_k", "to_v", "attention/query", "attention/key",
                "attention/value"),
        row=("to_out/0", "attention/proj_attn"),
        vocab_in=(), vocab_out=()),
}

# aliases: HF model_type / class-name spellings -> canonical key
_ALIASES = {
    "llamaforcausallm": "llama", "llamamodel": "llama",
    "mistralforcausallm": "mistral",
    "mixtralforcausallm": "mixtral",
    "qwen2forcausallm": "qwen2",
    "qwen2moeforcausallm": "qwen2_moe",
    "phiforcausallm": "phi", "phi3forcausallm": "phi3",
    "falconforcausallm": "falcon", "rwforcausallm": "falcon",
    "gptneoxforcausallm": "gpt_neox",
    "bloomforcausallm": "bloom",
    "gpt2lmheadmodel": "gpt2",
    "gptjforcausallm": "gptj",
    "optforcausallm": "opt",
    "bertmodel": "bert", "bertforsequenceclassification": "bert",
    "distilbertmodel": "distilbert",
    "gptneoforcausallm": "gpt_neo",
    "gptbigcodeforcausallm": "gpt_bigcode", "starcoder": "gpt_bigcode",
    "codegenforcausallm": "codegen",
    "gemmaforcausallm": "gemma", "gemma2forcausallm": "gemma",
    "stablelmforcausallm": "stablelm",
    "chatglmforconditionalgeneration": "chatglm", "glm": "chatglm",
    "megatrongptmodel": "megatron_gpt", "megatron": "megatron_gpt",
    "clipmodel": "clip", "cliptextmodel": "clip", "clipvisionmodel": "clip",
    "t5forconditionalgeneration": "t5", "mt5forconditionalgeneration": "t5",
    "whisperforconditionalgeneration": "whisper",
    "unet2dconditionmodel": "unet",
    "autoencoderkl": "vae",
}


def get_policy(arch: str) -> Optional[TPPolicy]:
    """Look up by canonical name, HF ``model_type``, or model class name."""
    key = arch.lower().replace("-", "_")
    if key in POLICIES:
        return POLICIES[key]
    return POLICIES.get(_ALIASES.get(key.replace("_", ""), ""))
