"""Megatron-style parallel layers.

Reference analog: ``deepspeed/module_inject/layers.py`` (``LinearLayer``,
``LinearAllreduce``, ``EmbeddingLayer``) — the building blocks AutoTP swaps in, with
hand-written all-reduces after row-parallel matmuls.

TPU redesign: the same blocks as flax modules whose parameter names carry the
``col_``/``row_`` markers the generic AutoTP policy recognizes, plus activation
sharding constraints; under jit, XLA inserts the reduce (psum) a row-parallel matmul
needs — there is no explicit ``dist.all_reduce`` call to write.
"""

from typing import Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from deepspeed_tpu.comm.mesh import get_global_mesh

TENSOR_AXIS = "tensor"


def _constrain(x, spec: Tuple):
    mesh = get_global_mesh()
    if mesh is None or mesh.shape.get(TENSOR_AXIS, 1) == 1:
        return x
    return jax.lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, PartitionSpec(*spec)))


class ColumnParallelLinear(nn.Module):
    """Output-dim sharded linear (reference: LinearLayer). The kernel parameter is
    named ``col_kernel`` so AutoTP's generic rules shard its last dim on the
    ``tensor`` axis; the activation constraint keeps the output sharded (the
    following RowParallelLinear consumes it without a gather)."""

    features: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        kernel = self.param("col_kernel", self.kernel_init,
                            (x.shape[-1], self.features))
        y = jnp.dot(x.astype(self.dtype or x.dtype),
                    kernel.astype(self.dtype or kernel.dtype))
        if self.use_bias:
            bias = self.param("col_bias", nn.initializers.zeros, (self.features,))
            y = y + bias.astype(y.dtype)
        return _constrain(y, (None,) * (y.ndim - 1) + (TENSOR_AXIS,))


class RowParallelLinear(nn.Module):
    """Input-dim sharded linear (reference: LinearAllreduce). The contraction over
    the sharded input dim makes XLA emit the psum the reference writes as
    ``dist.inference_all_reduce``; bias is added after the reduce (replicated)."""

    features: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x):
        kernel = self.param("row_kernel", self.kernel_init,
                            (x.shape[-1], self.features))
        y = jnp.dot(x.astype(self.dtype or x.dtype),
                    kernel.astype(self.dtype or kernel.dtype))
        y = _constrain(y, (None,) * y.ndim)  # post-reduce: replicated
        if self.use_bias:
            bias = self.param("row_bias", nn.initializers.zeros, (self.features,))
            y = y + bias.astype(y.dtype)
        return y


class VocabParallelEmbedding(nn.Module):
    """Vocab-dim sharded embedding table (reference: EmbeddingLayer sharded by
    AutoTP's vocab rule). Lookup over a sharded table is a gather XLA handles."""

    num_embeddings: int
    features: int
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, ids):
        table = self.param("embedding", nn.initializers.normal(stddev=0.02),
                           (self.num_embeddings, self.features))
        out = jnp.take(table.astype(self.dtype or table.dtype), ids, axis=0)
        return out
