"""Module injection / automatic tensor parallelism
(reference: ``deepspeed/module_inject/``)."""

from deepspeed_tpu.module_inject.auto_tp import GENERIC_POLICY, AutoTP  # noqa: F401
from deepspeed_tpu.module_inject.fusedqkv_utils import (  # noqa: F401
    shard_qkv_param,
    split_fused_qkv,
    unfuse_qkv,
)
from deepspeed_tpu.module_inject.layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from deepspeed_tpu.module_inject.policies import (  # noqa: F401
    POLICIES,
    TPPolicy,
    get_policy,
)
