"""Fused-QKV weight splitting for tensor parallelism.

Reference analog: ``deepspeed/module_inject/fusedqkv_utils.py`` — fused QKV matrices
can't be naively chunked across TP ranks because q/k/v (and, under GQA, differently
sized k/v) interleave along the fused output dim; the reference ships per-layout
splitters (``_glm_type_transpose``, ``_bloom_type_transpose``, ``_qwen_type_transpose``
dispatched by ``prepare_tp_fused_qkvw``).

Layouts here:
- ``"concat"``  — [*, q_out | k_out | v_out] (llama-style qkv_proj, falcon new,
  qwen): split each of q/k/v into tp chunks and take chunk[rank] of each.
- ``"interleaved"`` — [*, heads x (q|k|v) x head_dim] (bloom/gpt2 c_attn style,
  per-head interleave): heads divide across ranks, so a plain chunk of the
  head-major dim is correct after viewing as [heads, 3*head_dim].
"""

from typing import Tuple

import numpy as np


def qkv_sizes(n_heads: int, n_kv_heads: int, head_dim: int) -> Tuple[int, int, int]:
    return n_heads * head_dim, n_kv_heads * head_dim, n_kv_heads * head_dim


def unfuse_qkv(w: np.ndarray, n_heads: int, n_kv_heads: int, head_dim: int,
               layout: str = "concat"):
    """Split a fused [in, q+k+v] (or [q+k+v] bias) into (q, k, v) arrays."""
    q_sz, k_sz, v_sz = qkv_sizes(n_heads, n_kv_heads, head_dim)
    if w.shape[-1] != q_sz + k_sz + v_sz:
        raise ValueError(f"fused dim {w.shape[-1]} != q+k+v = {q_sz + k_sz + v_sz} "
                         f"(heads={n_heads}, kv_heads={n_kv_heads}, hd={head_dim})")
    if layout == "concat":
        return (w[..., :q_sz], w[..., q_sz:q_sz + k_sz], w[..., q_sz + k_sz:])
    if layout == "interleaved":
        if n_kv_heads != n_heads:
            raise ValueError("interleaved layout requires MHA (kv_heads == heads)")
        per = w.reshape(*w.shape[:-1], n_heads, 3, head_dim)
        q, k, v = per[..., 0, :], per[..., 1, :], per[..., 2, :]
        flat = lambda t: t.reshape(*w.shape[:-1], n_heads * head_dim)  # noqa: E731
        return flat(q), flat(k), flat(v)
    raise ValueError(f"unknown fused-qkv layout {layout!r}")


def split_fused_qkv(w: np.ndarray, n_heads: int, n_kv_heads: int, head_dim: int,
                    tp_size: int, rank: int, layout: str = "concat") -> np.ndarray:
    """Return ``rank``'s shard of a fused QKV weight, still fused
    (reference: ``prepare_tp_fused_qkvw``). Output fused dim = (q+k+v)/tp.

    Under GQA, ``n_kv_heads`` must divide ``tp_size``-evenly; replicating kv heads
    across ranks (tp > kv_heads) is not supported — mirror of the reference's
    uneven-head constraint.
    """
    if n_heads % tp_size or n_kv_heads % tp_size:
        raise ValueError(f"heads ({n_heads}, kv={n_kv_heads}) must divide tp={tp_size}")
    if layout == "interleaved":
        # heads are the interleave-major unit: chunking the head dim preserves the
        # per-head (q|k|v) interleave within each shard
        if n_kv_heads != n_heads:
            raise ValueError("interleaved layout requires MHA (kv_heads == heads)")
        per = w.reshape(*w.shape[:-1], n_heads, 3 * head_dim)
        shard = np.split(per, tp_size, axis=-2)[rank]
        return shard.reshape(*w.shape[:-1], (n_heads // tp_size) * 3 * head_dim)
    q, k, v = unfuse_qkv(w, n_heads, n_kv_heads, head_dim, layout)
    qs = np.split(q, tp_size, axis=-1)
    ks = np.split(k, tp_size, axis=-1)
    vs = np.split(v, tp_size, axis=-1)
    return np.concatenate([qs[rank], ks[rank], vs[rank]], axis=-1)


def shard_qkv_param(w: np.ndarray, n_heads: int, n_kv_heads: int, head_dim: int,
                    tp_size: int, layout: str = "concat") -> np.ndarray:
    """All shards stacked on a new leading axis — convenient for
    ``jax.device_put`` with a per-shard sharding or for host-side scatter."""
    return np.stack([
        split_fused_qkv(w, n_heads, n_kv_heads, head_dim, tp_size, r, layout)
        for r in range(tp_size)])
