"""bench_serve — open/closed-loop synthetic load for the serving stack.

ROADMAP item 1's load harness: drive an ``InferenceServer`` with
deterministic synthetic traffic (burst, multi-turn, slow-client,
low-priority mixes; chaos scenarios ride on ``DSTPU_CHAOS_SERVE_*``) and
report

* p50/p99 TTFT/TPOT derived STRAIGHT from the dstrace request spans
  (``serve/queued`` + ``serve/prefill`` durations per uid; decode span /
  (tokens-1)) — PR 5 pinned trace == metric, so the span-derived numbers
  tie out against ``ServingMetrics``;
* the deterministic counter set that is the real proof on a CPU container
  where wall-clock is noise: demotions/promotions/bytes through the KV
  tiers, sheds and ladder transitions, recomputed tokens from fault
  evictions, quarantines, drift recalibrations, and — the availability
  headline — ``degraded_latches`` (sticky 503s), which a healthy siege
  run must keep at ZERO;
* the prefix proof set (``report["prefix"]``): cache-hit ratio and the
  prefill-work conservation identity ``saved + computed == total``,
  asserted against the workload's ground-truth shareable-token
  denominator (multi-turn conversation continuations are TRUE prefix
  extensions; ``shared_prefix_frac`` cuts every prompt's head from one
  seeded pool), plus host-tier compression and bytes-per-resident-token
  from the quantized offload tier.

Closed-loop mode models N concurrent users each waiting for their reply
(lane i issues its requests sequentially); open-loop mode submits on a
fixed arrival schedule regardless of completions (the overload generator:
rejections are counted, not retried). Prompt/token shapes are seeded per
request INDEX, so the workload is identical regardless of thread timing.

CLI: ``bin/dstpu_bench_serve --scenario micro`` (tiny CPU llama,
hermetic). The tier-1 ``serve_load`` test runs the micro scenario and
asserts the counter invariants.
"""

import dataclasses
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.serving.request import RequestState
from deepspeed_tpu.serving.server import (BackpressureError, InferenceServer,
                                          ServerClosedError)
from deepspeed_tpu.telemetry import hist as dshist
from deepspeed_tpu.telemetry.compiles import compiles_total
from deepspeed_tpu.telemetry.tracer import _quantile, get_tracer


def _slo_section(snapshots: List[Dict[str, dict]],
                 pre_snapshots: List[Dict[str, dict]]) -> Dict[str, dict]:
    """The SLO proof set: the deterministic ``dstpu_req_*`` log-bucket
    histograms (``telemetry/hist.py``), folded across replicas and stated
    as measured-window deltas (the warmed-run discipline every counter in
    the report follows). Quantiles are bucket upper edges — exact and
    platform-independent, unlike the wall-clock percentile sketches."""
    merged: Dict[str, dshist.LogHistogram] = {}
    for i, snap in enumerate(snapshots):
        pre = pre_snapshots[i] if i < len(pre_snapshots) else {}
        for family, h_snap in snap.items():
            h = dshist.LogHistogram.from_snapshot(h_snap)
            if family in pre:
                h = h.delta_from(dshist.LogHistogram.from_snapshot(
                    pre[family]))
            if family in merged:
                merged[family].merge(h)
            else:
                merged[family] = h
    return {family: {"count": h.count, "sum_s": round(h.sum, 6),
                     "p50_le_s": h.quantile(0.5),
                     "p99_le_s": h.quantile(0.99)}
            for family, h in merged.items()}


@dataclasses.dataclass
class ServeScenario:
    name: str = "micro"
    mode: str = "closed"                 # "closed" | "open"
    num_requests: int = 100
    concurrency: int = 8                 # closed-loop lanes
    prompt_len: Tuple[int, int] = (4, 12)       # [lo, hi) per request
    max_new_tokens: Tuple[int, int] = (2, 5)    # [lo, hi) per request
    turns: int = 1                       # >1: lanes carry history forward
    arrival_interval_s: float = 0.0      # open-loop fixed interarrival
    burst: int = 0                       # open-loop: first K back-to-back
    slow_client_every: int = 0           # every Kth request streams slowly
    slow_client_token_s: float = 0.005
    low_priority_every: int = 0          # every Kth request priority=-1
    # fraction of each prompt drawn from ONE seeded shared pool (the
    # "shared system prompt" of real traffic): request i's prompt starts
    # with pool[:round(frac * len_i)] — deterministic per index, so the
    # shareable-token sum is a ground-truth denominator the report can
    # assert the prefix cache's savings against
    shared_prefix_frac: float = 0.0
    timeout_s: Optional[float] = None
    submit_retry_limit: int = 200        # closed-loop 429 retries/request
    result_timeout_s: float = 300.0
    vocab: int = 128
    seed: int = 0


#: named presets; chaos scenarios are the same workloads run under
#: DSTPU_CHAOS_SERVE_* env knobs (the harness never sets env itself)
SCENARIOS: Dict[str, ServeScenario] = {
    "micro": ServeScenario(name="micro", num_requests=100, concurrency=8),
    "burst": ServeScenario(name="burst", mode="open", num_requests=64,
                           burst=32, arrival_interval_s=0.005,
                           max_new_tokens=(2, 6),
                           prompt_len=(24, 48), shared_prefix_frac=0.5),
    "multi_turn": ServeScenario(name="multi_turn", num_requests=48,
                                concurrency=6, turns=4,
                                prompt_len=(4, 10)),
    "slow_client": ServeScenario(name="slow_client", num_requests=32,
                                 concurrency=4, slow_client_every=2,
                                 max_new_tokens=(4, 8)),
    "overload": ServeScenario(name="overload", mode="open",
                              num_requests=200, arrival_interval_s=0.001,
                              max_new_tokens=(4, 10),
                              low_priority_every=3,
                              prompt_len=(24, 48), shared_prefix_frac=0.5),
    # decode-first scheduling proof workload: a first burst starts
    # decoding, then seeded LONG prompts (several KV blocks each, larger
    # than the tiny engine's 64-token step budget) keep landing mid-decode
    # — unchunked, each arrival serializes every decode behind a full
    # prefill tick; with `serving.scheduler.prefill_chunk_tokens` set, the
    # tick ledger proves prefill never exceeds the cap
    "long_prompt": ServeScenario(name="long_prompt", mode="open",
                                 num_requests=16, burst=4,
                                 arrival_interval_s=0.01,
                                 max_new_tokens=(8, 16),
                                 prompt_len=(48, 96)),
}


def _stats(vals: List[float]) -> Dict[str, float]:
    s = sorted(vals)
    n = len(s)
    return {"count": n,
            "mean_s": (sum(s) / n) if n else 0.0,
            "p50_s": _quantile(s, 0.5),
            "p99_s": _quantile(s, 0.99),
            "max_s": s[-1] if n else 0.0}


def _shared_pool(scenario: ServeScenario) -> List[int]:
    """The one shared token pool every request's shared prefix is cut
    from — seeded by the scenario seed ONLY (identical across indices,
    the definition of 'shared')."""
    rng = np.random.default_rng(scenario.seed * 7_919 + 1)
    return [int(t) for t in rng.integers(1, scenario.vocab, 256)]


def _request_shape(scenario: ServeScenario, index: int
                   ) -> Tuple[List[int], int, int, int]:
    """Deterministic (prompt, max_new, priority, shared_len) for request
    ``index`` — a pure function of (seed, index), independent of thread
    timing. ``shared_len`` is the prompt's leading run drawn from the
    shared pool (0 when ``shared_prefix_frac`` is off): summed over the
    run it is the ground-truth shareable-token denominator the prefix
    counters are asserted against."""
    rng = np.random.default_rng(scenario.seed * 100_003 + index)
    lo, hi = scenario.prompt_len
    n = int(rng.integers(lo, max(hi, lo + 1)))
    prompt = [int(t) for t in rng.integers(1, scenario.vocab, n)]
    shared_len = 0
    if scenario.shared_prefix_frac > 0.0:
        pool = _shared_pool(scenario)
        shared_len = min(int(round(n * scenario.shared_prefix_frac)),
                         len(pool))
        prompt = pool[:shared_len] + prompt[shared_len:]
    mlo, mhi = scenario.max_new_tokens
    max_new = int(rng.integers(mlo, max(mhi, mlo + 1)))
    priority = (-1 if scenario.low_priority_every
                and index % scenario.low_priority_every == 0 else 0)
    return prompt, max_new, priority, shared_len


def _span_latencies(events, exclude_uids=()) -> Tuple[List[float], List[float]]:
    """Rebuild per-request TTFT/TPOT from the dstrace request spans: TTFT
    = queued.dur + prefill.dur; TPOT = decode.dur / (tokens - 1).
    ``exclude_uids`` drops warm-wave requests — they pay the XLA compiles
    on purpose and must never land in the measured percentiles."""
    queued: Dict[int, float] = {}
    prefill: Dict[int, float] = {}
    decode: Dict[int, Tuple[float, int]] = {}
    exclude = set(exclude_uids)
    for e in events:
        _eid, name, _cat, ph, _ts, dur, _tid, args = e
        if ph != "X" or not args or "uid" not in args:
            continue
        uid = args["uid"]
        if uid in exclude:
            continue
        if name == "serve/queued":
            queued[uid] = dur
        elif name == "serve/prefill":
            prefill[uid] = dur
        elif name == "serve/decode":
            decode[uid] = (dur, int(args.get("tokens", 0)))
    ttft = [queued[u] + prefill[u] for u in prefill if u in queued]
    tpot = [dur / (tokens - 1) for dur, tokens in decode.values()
            if tokens > 1]
    return ttft, tpot


def warm_scenario(server: InferenceServer, scenario: ServeScenario
                  ) -> Tuple[int, List[int]]:
    """Warm the XLA compile caches with the scenario's exact shape space
    BEFORE the measured run — the "warm the exact shapes first" discipline
    (PR 10/13), mechanized. One wave per decode-batch bucket the measured
    concurrency can reach (all wave members share the same max_new so they
    decode TOGETHER at exactly that bucket), prompts from a shifted seed
    space with the shared-prefix pool disabled: warming must compile the
    same prefill/decode buckets WITHOUT pre-populating the prefix reuse
    the measured run's ground-truth accounting is asserted against.
    Returns the number of warm requests (their tokens land in the
    server's cumulative counters; every proof identity is
    conservation-shaped, so totals stay consistent). Returns ``(issued,
    uids)`` so the caller can subtract the warm wave from the measured
    report. Shapes that only appear mid-run (multi-turn histories growing
    past the declared prompt range) are out of warm's reach — a
    ``--warm`` check tripping there is the discipline surfacing a real
    coverage gap, not noise."""
    from deepspeed_tpu.inference.v2.scheduler import snap_bucket
    warm_sc = dataclasses.replace(scenario, seed=scenario.seed + 104_729,
                                  shared_prefix_frac=0.0)
    conc = max(scenario.concurrency, 1)
    try:
        buckets = sorted({snap_bucket(
            n, server.engine.config.decode_batch_buckets)
            for n in range(1, conc + 1)})
    except AttributeError:        # engine without decode buckets: one wave
        buckets = [conc]
    # the LONGEST declared shapes: prompts stretched to the range max and
    # the max generation length, so the deepest context bucket (and every
    # shallower one passed through while decoding) compiles now
    max_prompt = max(scenario.prompt_len[1] - 1, scenario.prompt_len[0], 1)
    warm_new = max(scenario.max_new_tokens[1] - 1,
                   scenario.max_new_tokens[0], 2)
    idx = 0
    issued = 0
    warm_uids: List[int] = []
    for bucket in buckets:
        reqs = []
        for _ in range(bucket):
            prompt, _max_new, _prio, _shared = _request_shape(warm_sc, idx)
            idx += 1
            prompt = (prompt * (max_prompt // len(prompt) + 1))[:max_prompt]
            try:
                reqs.append(server.submit(prompt, max_new_tokens=warm_new))
            except BackpressureError:
                break   # tiny pools: whatever got in still warms shapes
        issued += len(reqs)
        warm_uids.extend(r.uid for r in reqs)
        for r in reqs:
            try:
                r.wait(timeout=scenario.result_timeout_s)
            except Exception:
                r.cancel()
    return issued, warm_uids


class _Lane:
    """One closed-loop user: issues its assigned request indices in order,
    retrying 429s with the server's own Retry-After hint (bounded), and
    carrying multi-turn history forward."""

    def __init__(self, server: InferenceServer, scenario: ServeScenario,
                 indices: List[int], results: dict, lock: threading.Lock):
        self.server = server
        self.scenario = scenario
        self.indices = indices
        self.results = results
        self.lock = lock
        self.history: List[int] = []

    def run(self):
        sc = self.scenario
        max_ctx = self.server.engine.state.max_context_length
        for turn in range(max(sc.turns, 1)):
            for index in self.indices:
                prompt, max_new, priority, shared_len = _request_shape(
                    sc, index + turn * sc.num_requests)
                reusable = 0
                if sc.turns > 1:
                    # TRUE conversation continuation: the next turn's
                    # prompt starts with EXACTLY the previous turn's
                    # prompt + reply (the root prefix the radix cache
                    # reuses). Never slice a suffix of the history —
                    # that would break the prefix property and make the
                    # hit counters unaccountable; when the conversation
                    # outgrows the context, start a fresh one instead
                    if self.history and (len(self.history) + len(prompt)
                                         + max_new + 1 <= max_ctx):
                        prompt = self.history + prompt
                        reusable = len(self.history)
                    else:
                        self.history = []
                        reusable = shared_len
                else:
                    reusable = shared_len
                record = self._one(index, turn, prompt, max_new, priority)
                record["reusable_tokens"] = reusable
                if sc.turns > 1 and record.get("tokens") is not None:
                    self.history = (prompt + record["tokens"])
                with self.lock:
                    self.results[(turn, index)] = record

    def _one(self, index: int, turn: int, prompt, max_new, priority) -> dict:
        sc = self.scenario
        retries = 0
        while True:
            try:
                req = self.server.submit(prompt, max_new_tokens=max_new,
                                         timeout_s=sc.timeout_s,
                                         priority=priority)
                break
            except BackpressureError as e:
                retries += 1
                if retries > sc.submit_retry_limit:
                    return {"state": "gave_up", "retries": retries}
                time.sleep(min(e.retry_after_s, 0.02))
            except ServerClosedError:
                return {"state": "refused", "retries": retries}
        slow = (sc.slow_client_every
                and index % sc.slow_client_every == 0)
        try:
            if slow:
                for _tok in req.stream(timeout=sc.result_timeout_s):
                    time.sleep(sc.slow_client_token_s)
            else:
                req.wait(timeout=sc.result_timeout_s)
        except Exception:
            req.cancel()
            req.wait(timeout=10.0)
        return {"state": req.state.value, "uid": req.uid,
                "tokens": list(req.tokens), "retries": retries,
                "finish_reason": req.finish_reason}


def run_scenario(server: InferenceServer, scenario: ServeScenario,
                 provenance: Optional[dict] = None,
                 warmup: bool = False) -> dict:
    """Drive ``server`` (already started) with the scenario; drains it at
    the end and returns the report dict. The process-global tracer is
    enabled for the run if it wasn't (the span-derived latency section
    depends on it).

    The report carries a ``provenance`` section — preset name, seed, the
    full scenario and resolved serving config, and the DSTPU_TRACE dump
    path — so ``dstpu plan --serve`` can locate the trace, enforce
    workload-scoped baselines, and the verify runner
    (``autotuning.serve_verify``) can re-execute the SAME seeded preset
    with a proposed override applied. Caller-supplied ``provenance`` keys
    (e.g. an explicit ``trace_path``, the builder args) merge over the
    auto-filled ones."""
    tracer = get_tracer()
    if not tracer.enabled:
        tracer.configure(enabled=True)
    warm_requests, warm_uids = warm_scenario(server, scenario) \
        if warmup else (0, [])
    # measurement marks: the warm wave pays the XLA compiles and
    # full-bucket traffic ON PURPOSE — mark the compile ledger and
    # snapshot every cumulative counter here so nothing it did leaks into
    # the measured proof set (its uids are likewise dropped from the
    # span-derived latency percentiles below)
    compile_mark = compiles_total()
    if hasattr(server.engine, "sched_mark"):
        # reset the tick-ledger window maxima (max prefill tokens/tick,
        # max decode stall) so the scheduler proof set below covers the
        # measured window only, like every other counter here
        server.engine.sched_mark()
    pre_snap = server.metrics.snapshot() if warmup else {}
    pre_slo = server.metrics.slo_snapshot() if warmup else {}
    pre_prefix = (server.engine.prefix_stats()
                  if warmup and hasattr(server.engine, "prefix_stats")
                  else {})
    results: dict = {}
    lock = threading.Lock()
    t0 = time.monotonic()
    if scenario.mode == "closed":
        lanes = [
            _Lane(server, scenario,
                  list(range(i, scenario.num_requests, scenario.concurrency)),
                  results, lock)
            for i in range(max(scenario.concurrency, 1))]
        threads = [threading.Thread(target=lane.run, daemon=True,
                                    name=f"bench-lane-{i}")
                   for i, lane in enumerate(lanes)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    elif scenario.mode == "open":
        pending = []
        for index in range(scenario.num_requests):
            prompt, max_new, priority, shared_len = _request_shape(
                scenario, index)
            if index >= scenario.burst and scenario.arrival_interval_s > 0:
                time.sleep(scenario.arrival_interval_s)
            try:
                pending.append((index, shared_len, server.submit(
                    prompt, max_new_tokens=max_new,
                    timeout_s=scenario.timeout_s, priority=priority)))
            except BackpressureError:
                results[(0, index)] = {"state": "rejected"}
            except ServerClosedError:
                results[(0, index)] = {"state": "refused"}
        for index, shared_len, req in pending:
            req.wait(timeout=scenario.result_timeout_s)
            results[(0, index)] = {"state": req.state.value, "uid": req.uid,
                                   "tokens": list(req.tokens),
                                   "finish_reason": req.finish_reason,
                                   "reusable_tokens": shared_len}
    else:
        raise ValueError(f"unknown scenario mode {scenario.mode!r}")
    drained = server.drain(timeout=scenario.result_timeout_s)
    wall_s = time.monotonic() - t0

    snap = server.metrics.snapshot()
    ttft, tpot = _span_latencies(tracer.events_snapshot(),
                                 exclude_uids=warm_uids)

    def measured(key):
        """Cumulative counter -> measured-window delta (identical to the
        raw value on unwarmed runs — pre_snap is empty)."""
        return snap[key] - pre_snap.get(key, 0)
    states: Dict[str, int] = {}
    client_tokens = 0
    for rec in results.values():
        states[rec["state"]] = states.get(rec["state"], 0) + 1
        client_tokens += len(rec.get("tokens") or ())
    ledger = (server.engine.kv_ledger()
              if hasattr(server.engine, "kv_ledger") else {})
    # engine-truth prefix/prefill counters (the metrics mirror can lag
    # one tick; after the drain these are final and exact)
    prefix = (server.engine.prefix_stats()
              if hasattr(server.engine, "prefix_stats") else {})
    if prefix and pre_prefix:
        # warmed run: the monotonic prefix counters become measured-window
        # deltas (occupancy gauges stay live values) and the hit ratio is
        # recomputed over the window — warm traffic is deliberately novel
        # and would otherwise dilute it
        for k in ("prefill_tokens_total", "prefill_tokens_saved",
                  "prefill_tokens_computed", "prefix_lookups",
                  "prefix_hits", "prefix_misses", "prefix_hit_tokens",
                  "prefix_lookup_tokens", "prefix_inserted_blocks",
                  "prefix_evicted_blocks"):
            if k in prefix:
                prefix[k] = prefix[k] - pre_prefix.get(k, 0)
        if "prefix_hit_ratio" in prefix:
            prefix["prefix_hit_ratio"] = (
                prefix.get("prefix_hit_tokens", 0)
                / max(prefix.get("prefix_lookup_tokens", 0), 1))
    if prefix:
        # ground-truth denominator: tokens the workload genuinely made
        # shareable (conversation histories + shared-pool prefixes); the
        # cache can never legitimately save more than this
        prefix["expected_reusable_tokens"] = sum(
            rec.get("reusable_tokens", 0) for rec in results.values())
        prefix["conservation_ok"] = (
            prefix.get("prefill_tokens_saved", 0)
            + prefix.get("prefill_tokens_computed", 0)
            == prefix.get("prefill_tokens_total", 0))
        prefix["bytes_per_resident_token"] = \
            snap["bytes_per_resident_token"]
        prefix["host_compression_ratio"] = \
            snap["host_kv_compression_ratio"]
    # scheduler proof set: the engine tick ledger (per-tick prefill-token
    # maxima, cap utilization, decode-gap in ticks). Window maxima cover
    # the measured window (sched_mark above); totals are cumulative, and
    # the conservation check ties them to the engine-truth prefill
    # counter — chunking must neither lose nor duplicate a prompt token.
    sched: dict = {}
    if hasattr(server.engine, "sched_stats"):
        sched_cfg = dict(getattr(server.config, "scheduler", None) or {})
        cap = int(sched_cfg.get("prefill_chunk_tokens", 0) or 0)
        plan_cfg = getattr(getattr(server.engine, "config", None),
                           "scheduler", None)
        # unchunked runs report the decode gap in units of the smallest
        # prefill bucket so a chunked A/B can re-state its gap in the
        # same units (sched_stats(gap_unit_tokens=...))
        unit = cap or (int(plan_cfg.prefill_buckets[0])
                       if plan_cfg is not None and plan_cfg.prefill_buckets
                       else 0)
        sched = server.engine.sched_stats(gap_unit_tokens=unit)
        if hasattr(server.engine, "prefix_stats"):
            computed = int(server.engine.prefix_stats()
                           .get("prefill_tokens_computed", 0))
            sched["prefill_tokens_engine"] = computed
            sched["chunk_conservation_ok"] = \
                sched["chunk_tokens_total"] == computed
    # the SLO proof set + its conservation gate: every measured request
    # that produced a first token lands in the TTFT histogram exactly
    # once (on_finish observes iff first_token_ts is set, and the client
    # record holds tokens iff one fanned out) — a mismatch means a
    # request's latency escaped the SLO accounting
    slo = _slo_section([server.metrics.slo_snapshot()], [pre_slo])
    ttft_n = slo.get("dstpu_req_ttft_seconds", {}).get("count", 0)
    first_token_requests = sum(
        1 for rec in results.values() if rec.get("tokens"))
    slo["conservation"] = {
        "ttft_observations": ttft_n,
        "first_token_requests": first_token_requests,
        "ok": ttft_n == first_token_requests,
    }
    # the atexit dump lands relative to THIS process's cwd — record it
    # absolute, or `dstpu plan --serve` would resolve a relative
    # DSTPU_TRACE against the report's directory instead
    env_trace = os.environ.get("DSTPU_TRACE")
    prov = {
        "preset": scenario.name,
        "seed": scenario.seed,
        "mode": scenario.mode,
        "num_requests": scenario.num_requests,
        "scenario": dataclasses.asdict(scenario),
        "serving_config": dataclasses.asdict(server.config),
        "trace_path": (os.path.abspath(env_trace) if env_trace else None),
    }
    kv_cfg = getattr(getattr(server.engine, "kv", None), "cfg", None)
    if kv_cfg is not None:
        prov["kv_num_blocks"] = kv_cfg.num_blocks
        prov["kv_block_size"] = kv_cfg.block_size
    if provenance:
        prov.update(provenance)
    return {
        "scenario": dataclasses.asdict(scenario),
        "provenance": prov,
        "wall_s": round(wall_s, 3),
        "drained": drained,
        "requests": {"issued": len(results), "states": states,
                     "client_tokens": client_tokens},
        "metrics": snap,
        # the deterministic proof set (see module docstring) — on warmed
        # runs every entry is the measured-window DELTA over the warm
        # wave's snapshot (identical to the raw counter otherwise)
        "counters": {
            "demotions": measured("kv_demotions"),
            "promotions": measured("kv_promotions"),
            "demoted_bytes": measured("kv_demoted_bytes"),
            "promoted_bytes": measured("kv_promoted_bytes"),
            "sheds": measured("requests_shed"),
            "rejected": measured("requests_rejected"),
            "brownout_entries": measured("brownout_entries"),
            "shed_entries": measured("shed_entries"),
            "ladder_transitions": measured("ladder_transitions"),
            "quarantined": measured("requests_quarantined"),
            "step_faults": measured("engine_step_faults"),
            "recomputed_tokens": measured("recomputed_tokens"),
            "kv_drift_events": measured("kv_drift_events"),
            "kv_recalibrations": measured("kv_recalibrations"),
            "sticky_503": measured("degraded_latches"),
            "prefix_evictions": measured("prefix_evictions"),
            "prefill_tokens_total": prefix.get("prefill_tokens_total", 0),
            "prefill_tokens_saved": prefix.get("prefill_tokens_saved", 0),
            "prefill_tokens_computed":
                prefix.get("prefill_tokens_computed", 0),
            # worst tick's prefill tokens in the measured window — the
            # counter the `prefill_chunk_tokens` plan rule predicts on
            "max_prefill_tokens_per_tick":
                sched.get("max_prefill_tokens_per_tick", 0),
            # the compile-ledger proof: XLA compiles that landed INSIDE
            # the measured window (warmed runs must report 0 — a compile
            # here stalled ticks and skewed every latency number above)
            "compiles_during_measurement": compiles_total() - compile_mark,
        },
        # latency_from_trace + counters are measured-window only; the raw
        # "metrics" mirror (and its percentile sketches) stays cumulative
        "warmed": {"enabled": warmup, "requests": warm_requests},
        "slo": slo,
        "scheduler": sched,
        "prefix": prefix,
        "kv_ledger": ledger,
        "ladder": {"level": server.ladder.level.name.lower(),
                   "transitions": dict(server.ladder.transitions),
                   "entries": dict(server.ladder.entries)},
        "latency_from_trace": {"ttft_s": _stats(ttft),
                               "tpot_s": _stats(tpot)},
        "latency_from_metrics": {
            "ttft_p50_s": snap["ttft_p50_s"], "ttft_p99_s": snap["ttft_p99_s"],
            "tpot_p50_s": snap["tpot_p50_s"],
        },
    }


# ---------------------------------------------------------------------------
# fleet mode: the same seeded workloads through the multi-replica router
# ---------------------------------------------------------------------------
def build_tiny_fleet(replicas: int = 2, kv_num_blocks: int = 64,
                     kv_block_size: int = 16,
                     fleet_overrides: Optional[dict] = None,
                     **builder_kwargs):
    """N in-process ``build_tiny_server`` replicas behind HTTP frontends,
    fronted by a ``FleetRouter`` (affinity keyed to the replicas' KV
    block size). Returns the started router; tear it down with
    ``stop_tiny_fleet``. In-process replicas share the jit caches, so
    replica 2..N costs no extra compiles — the fleet drill stays inside
    the tier-1 budget."""
    from deepspeed_tpu.serving.fleet import (FleetConfig, FleetRouter,
                                             ReplicaHandle)
    from deepspeed_tpu.serving.frontend import ServingFrontend
    handles, members = [], []
    for rid in range(replicas):
        server = build_tiny_server(kv_num_blocks=kv_num_blocks,
                                   kv_block_size=kv_block_size,
                                   **builder_kwargs)
        server.replica_id = rid     # in-process: env identity can't differ
        server.start()
        fe = ServingFrontend(server).start()
        handles.append(ReplicaHandle(rid, fe.url))
        members.append((server, fe))
    cfg = FleetConfig(replicas=replicas,
                      affinity_block_tokens=kv_block_size,
                      **(fleet_overrides or {}))
    router = FleetRouter(cfg, handles=handles)
    router._members = members       # teardown + warm need the objects
    return router.start()


def stop_tiny_fleet(router) -> None:
    router.stop(terminate_replicas=False)
    for server, fe in getattr(router, "_members", ()):
        fe.stop()
        if server.running:
            server.stop(drain_timeout=30.0)


class _FleetLane:
    """One closed-loop user against the ROUTER's front door: same seeded
    request shapes as ``_Lane``, but through HTTP streams, retrying fleet
    429s with the router's Retry-After hint (bounded)."""

    def __init__(self, router_url: str, scenario: ServeScenario,
                 indices: List[int], results: dict, lock: threading.Lock):
        self.url = router_url
        self.scenario = scenario
        self.indices = indices
        self.results = results
        self.lock = lock

    def run(self):
        from deepspeed_tpu.serving import http_util
        sc = self.scenario
        for index in self.indices:
            prompt, max_new, priority, shared_len = _request_shape(sc, index)
            record = {"state": "gave_up", "retries": 0}
            for attempt in range(sc.submit_retry_limit + 1):
                tokens: List[int] = []
                final: dict = {}
                try:
                    reply = http_util.open_stream(
                        self.url + "/generate",
                        {"prompt_tokens": prompt,
                         "max_new_tokens": max_new, "priority": priority,
                         "stream": True},
                        timeout_s=sc.result_timeout_s)
                    if reply.status == 429:
                        record = {"state": "rejected", "retries": attempt}
                        time.sleep(min(reply.retry_after_s() or 0.02, 0.02))
                        continue
                    if reply.status != 200:
                        record = {"state": "refused", "retries": attempt,
                                  "error": reply.error}
                        break
                    for rec in reply.records():
                        if "token" in rec:
                            tokens.append(int(rec["token"]))
                        elif rec.get("done"):
                            final = rec
                except Exception as e:
                    record = {"state": "failed", "retries": attempt,
                              "error": repr(e)}
                    break
                record = {"state": final.get("state", "failed"),
                          "uid": final.get("uid"), "tokens": tokens,
                          "finish_reason": final.get("finish_reason"),
                          "rerouted": final.get("rerouted", 0),
                          "recomputed_tokens":
                              final.get("recomputed_tokens", 0),
                          "retries": attempt}
                break
            record["reusable_tokens"] = shared_len
            with self.lock:
                self.results[(0, index)] = record


def run_fleet_scenario(router, scenario: ServeScenario,
                       provenance: Optional[dict] = None,
                       warmup: bool = False) -> dict:
    """Closed-loop drive of a fleet through the ROUTER. The proof set is
    the router's exact counters plus the replica-summed prefix section
    (same conservation identity as the single-replica report: ``saved +
    computed == total`` holds fleet-wide because every replica holds it),
    and the routing conservation identity ``completed + client_sheds +
    requests_lost + client_errors == submitted`` — every HTTP request the
    router admitted is accounted to exactly one terminal counter."""
    if scenario.mode != "closed" or scenario.turns > 1:
        raise ValueError("fleet scenarios are closed-loop single-turn")
    members = getattr(router, "_members", ())
    if warmup:
        for server, _fe in members:
            warm_scenario(server, scenario)
    c0 = router.counters_snapshot()
    # always a delta (like the router counters above): a previous
    # scenario on the same fleet must not leak into this proof set
    pre_slo: List[Dict[str, dict]] = [
        server.metrics.slo_snapshot() for server, _fe in members]
    pre_prefix: List[dict] = [
        server.engine.prefix_stats() if hasattr(server.engine,
                                                "prefix_stats") else {}
        for server, _fe in members]
    results: dict = {}
    lock = threading.Lock()
    t0 = time.monotonic()
    lanes = [
        _FleetLane(router.url, scenario,
                   list(range(i, scenario.num_requests,
                              scenario.concurrency)),
                   results, lock)
        for i in range(max(scenario.concurrency, 1))]
    threads = [threading.Thread(target=lane.run, daemon=True,
                                name=f"fleet-lane-{i}")
               for i, lane in enumerate(lanes)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.monotonic() - t0
    # settle WITHOUT drain(): drain flips the replica to draining
    # permanently, which would pull every member out of rotation and
    # leave the fleet unroutable after one scenario (lanes already hold
    # final records, so quiescence is just the tail of bookkeeping)
    settle_deadline = time.monotonic() + scenario.result_timeout_s
    for server, _fe in members:
        while time.monotonic() < settle_deadline:
            h = server.health()
            if h.get("queued", 0) == 0 and h.get("inflight", 0) == 0:
                break
            time.sleep(0.01)

    counters = {k: v - c0.get(k, 0)
                for k, v in router.counters_snapshot().items()}
    states: Dict[str, int] = {}
    client_tokens = 0
    for rec in results.values():
        states[rec["state"]] = states.get(rec["state"], 0) + 1
        client_tokens += len(rec.get("tokens") or ())
    prefix: dict = {}
    for i, (server, _fe) in enumerate(members):
        if not hasattr(server.engine, "prefix_stats"):
            continue
        stats = server.engine.prefix_stats()
        for k in ("prefill_tokens_total", "prefill_tokens_saved",
                  "prefill_tokens_computed", "prefix_lookups",
                  "prefix_hits", "prefix_misses", "prefix_hit_tokens",
                  "prefix_lookup_tokens"):
            if k in stats:
                prefix[k] = (prefix.get(k, 0) + stats[k]
                             - (pre_prefix[i].get(k, 0) if warmup else 0))
    if prefix:
        prefix["prefix_hit_ratio"] = (
            prefix.get("prefix_hit_tokens", 0)
            / max(prefix.get("prefix_lookup_tokens", 0), 1))
        prefix["expected_reusable_tokens"] = sum(
            rec.get("reusable_tokens", 0) for rec in results.values())
        prefix["conservation_ok"] = (
            prefix.get("prefill_tokens_saved", 0)
            + prefix.get("prefill_tokens_computed", 0)
            == prefix.get("prefill_tokens_total", 0))
    # fleet SLO proof set: per-replica histograms folded counterwise
    # (LogHistogram.merge — same fixed bounds everywhere). Conservation
    # is a band, not a point: every router-completed request observed
    # TTFT at exactly one replica, and each reroute may have added one
    # extra observation at the abandoned replica before the failover
    slo = _slo_section([server.metrics.slo_snapshot()
                        for server, _fe in members], pre_slo)
    ttft_n = slo.get("dstpu_req_ttft_seconds", {}).get("count", 0)
    completed = counters.get("completed", 0)
    slo["conservation"] = {
        "ttft_observations": ttft_n,
        "completed": completed,
        "reroutes": counters.get("reroutes", 0),
        "ok": (completed <= ttft_n
               <= completed + counters.get("reroutes", 0)),
    }
    health = router.health()
    prov = {
        "preset": scenario.name,
        "seed": scenario.seed,
        "mode": "fleet_closed",
        "num_requests": scenario.num_requests,
        "scenario": dataclasses.asdict(scenario),
        # the fleet topology: who routed, with what affinity/spill policy
        "fleet": {
            "replicas": [{"id": s["id"], "url": s["url"]}
                         for s in health["replicas"]],
            "affinity_enabled": router.config.affinity_enabled,
            "affinity_block_tokens": router.config.affinity_block_tokens,
            "spill_enabled": router.config.spill_enabled,
            "retry_budget": router.config.retry_budget,
        },
    }
    if provenance:
        prov.update(provenance)
    return {
        "scenario": dataclasses.asdict(scenario),
        "provenance": prov,
        "wall_s": round(wall_s, 3),
        "requests": {"issued": len(results), "states": states,
                     "client_tokens": client_tokens},
        # the router's exact proof set + the conservation identity over it
        "counters": counters,
        "routing_conservation_ok": (
            counters.get("completed", 0) + counters.get("client_sheds", 0)
            + counters.get("requests_lost", 0)
            + counters.get("client_errors", 0)
            == counters.get("submitted", 0)),
        "slo": slo,
        "prefix": prefix,
        "replicas": health["replicas"],
    }


# ---------------------------------------------------------------------------
# CLI (bin/dstpu_bench_serve) — hermetic tiny-llama CPU run
# ---------------------------------------------------------------------------
def build_tiny_server(kv_num_blocks: int = 64, kv_block_size: int = 16,
                      kv_offload: bool = True,
                      prefix_cache: bool = True,
                      host_kv_quantize: str = "int8",
                      serving_overrides: Optional[dict] = None
                      ) -> InferenceServer:
    """The hermetic benchmark target: tiny random-init fp32 llama +
    small KV pool so tier/ladder behavior shows at micro request counts."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      V2EngineConfig)
    from deepspeed_tpu.inference.v2.scheduler import SchedulerConfig
    from deepspeed_tpu.models.llama import (TINY_LLAMA, LlamaConfig,
                                            LlamaForCausalLM)
    from deepspeed_tpu.serving.server import ServingConfig

    cfg = LlamaConfig(**{**TINY_LLAMA.__dict__, "dtype": jnp.float32,
                         "max_seq_len": 512})
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    v2cfg = V2EngineConfig(
        kv_block_size=kv_block_size, kv_num_blocks=kv_num_blocks,
        scheduler=SchedulerConfig(max_tokens_per_step=64,
                                  prefill_buckets=(16, 32, 64)))
    engine = InferenceEngineV2(params, cfg, v2cfg)
    overrides = {"max_queue_depth": 32, "kv_offload_enabled": kv_offload,
                 "kv_demote_watermark": 0.5,
                 "kv_demote_watermark_brownout": 0.3,
                 "prefix_cache_enabled": prefix_cache,
                 "host_kv_quantize": (host_kv_quantize if kv_offload
                                      else "none"),
                 "idle_poll_s": 0.001}
    overrides.update(serving_overrides or {})
    sched_group = dict((serving_overrides or {}).get("scheduler") or {})
    if sched_group.get("role_split"):
        # prefill-role/decode-role pair sharing the tiny params; each role
        # gets its own KV pool at the configured geometry, and the server
        # drives the pair through the single-engine surface
        from deepspeed_tpu.serving.disagg import DisaggregatedEngine
        engine = DisaggregatedEngine(
            engine, InferenceEngineV2(params, cfg, v2cfg),
            handoff_quantize=sched_group.get("handoff_quantize", "none"))
    return InferenceServer(engine, ServingConfig(**overrides))


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(prog="dstpu_bench_serve",
                                description=__doc__)
    p.add_argument("--scenario", default="micro",
                   choices=sorted(SCENARIOS))
    p.add_argument("--requests", type=int, default=None,
                   help="override the scenario's num_requests")
    p.add_argument("--concurrency", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--kv-num-blocks", type=int, default=64)
    p.add_argument("--kv-block-size", type=int, default=16)
    p.add_argument("--replicas", type=int, default=1,
                   help="run the scenario through a FleetRouter over this "
                        "many in-process replicas (>1 switches to fleet "
                        "mode: router counters + replica-summed prefix "
                        "proof set; topology lands in provenance)")
    p.add_argument("--no-kv-offload", action="store_true",
                   help="run with the offload tier disabled (pre-tier "
                        "admission semantics)")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="run with the radix prefix cache disabled "
                        "(every prompt prefills from scratch)")
    p.add_argument("--host-kv-quantize", default="int8",
                   choices=("none", "int8", "fp8"),
                   help="host-tier page codec for demoted KV")
    p.add_argument("--shared-prefix-frac", type=float, default=None,
                   help="override the scenario's shared-prefix fraction "
                        "(0.0 disables; seeded, deterministic per index)")
    p.add_argument("--warm", action="store_true",
                   help="warm the XLA compile caches with the scenario's "
                        "shape distribution before measuring, then ASSERT "
                        "compiles_during_measurement == 0 (the proof-set "
                        "form of 'warm the exact shapes first')")
    p.add_argument("--json", default=None,
                   help="write the full report JSON here (stdout always "
                        "gets it too)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="dump the dstrace ring here after the run and "
                        "record it in the report's provenance (feeds "
                        "`dstpu plan --serve`)")
    p.add_argument("--serving-overrides", default=None, metavar="JSON",
                   help="serving-config override dict applied to the "
                        "tiny server (e.g. '{\"kv_demote_watermark\": "
                        "0.5}') — recorded in provenance so plan "
                        "proposals compose over it")
    p.add_argument("--verify-plan", default=None, metavar="PLAN",
                   help="instead of a fresh run: load a `dstpu plan "
                        "--serve` artifact and re-execute its seeded "
                        "preset once per proposal with the override "
                        "applied, judging each counter prediction "
                        "exactly (verdicts -> autotuning_results.json)")
    p.add_argument("--results-dir", default=None,
                   help="with --verify-plan: where "
                        "autotuning_results.json persists the verdicts")
    args = p.parse_args(argv)

    if args.verify_plan:
        from deepspeed_tpu.autotuning.serve_verify import verify_serve_plan
        verifications = verify_serve_plan(
            args.verify_plan, results_dir=args.results_dir,
            requests=args.requests)
        print(json.dumps(verifications, indent=2, default=str))
        return 0

    scenario = SCENARIOS[args.scenario]
    patch = {}
    if args.requests is not None:
        patch["num_requests"] = args.requests
    if args.concurrency is not None:
        patch["concurrency"] = args.concurrency
    if args.seed is not None:
        patch["seed"] = args.seed
    if args.shared_prefix_frac is not None:
        patch["shared_prefix_frac"] = args.shared_prefix_frac
    if patch:
        scenario = dataclasses.replace(scenario, **patch)

    serving_overrides = (json.loads(args.serving_overrides)
                         if args.serving_overrides else {})
    builder = {"kv_num_blocks": args.kv_num_blocks,
               "kv_block_size": args.kv_block_size,
               "kv_offload": not args.no_kv_offload,
               "prefix_cache": not args.no_prefix_cache,
               "host_kv_quantize": args.host_kv_quantize,
               "serving_overrides": serving_overrides}
    provenance = {"builder": builder}
    if args.trace:
        provenance["trace_path"] = os.path.abspath(args.trace)
    if args.replicas > 1:
        provenance["builder"] = dict(builder, replicas=args.replicas)
        router = build_tiny_fleet(replicas=args.replicas, **builder)
        try:
            report = run_fleet_scenario(router, scenario,
                                        provenance=provenance,
                                        warmup=args.warm)
        finally:
            stop_tiny_fleet(router)
    else:
        server = build_tiny_server(**builder).start()
        try:
            report = run_scenario(server, scenario, provenance=provenance,
                                  warmup=args.warm)
        finally:
            server.stop(drain_timeout=30.0)
    if args.trace:
        get_tracer().export_chrome(args.trace)
    text = json.dumps(report, indent=2, default=str)
    print(text)
    if args.json:
        with open(args.json, "w") as f:
            f.write(text + "\n")
    slo_cons = (report.get("slo") or {}).get("conservation") or {}
    if slo_cons and not slo_cons.get("ok"):
        # same explicit-check discipline as the --warm gate below: the
        # SLO histograms must account for every completed request
        print("dstpu_bench_serve: SLO conservation identity failed — "
              f"{slo_cons} (a request's latency escaped the dstpu_req_* "
              "histograms, or was double-counted)", file=sys.stderr)
        return 1
    if args.warm:
        compiles = report["counters"].get("compiles_during_measurement", 0)
        if compiles != 0:
            # explicit check, not assert: python -O must not strip the
            # proof, and the CLI keeps its exit-code discipline
            print(f"dstpu_bench_serve: {compiles} XLA compile(s) inside "
                  "the measured window after warmup — a shape escaped the "
                  "warm wave (see xla/compile instants in the trace)",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
