"""dstpu fleet — multi-replica router/front tier.

Reference analog: the MII/FastGen split — a thin front tier routing over
N independent engine processes — grown the properties one replica cannot
provide alone:

* **prefix-affinity routing** — the prompt's full-block prefix is hashed
  (``affinity_key``, same block-granular cap as ``PrefixCache.lookup``)
  and the request prefers the replica whose radix cache last served that
  prefix, so the fleet-wide hit ratio survives scale-out instead of
  degrading 1/N;
* **ladder-aware spill** — a replica publishing brownout/shed through
  ``/healthz`` sheds to healthy peers BEFORE any client sees a 429;
  sticky-503 (degraded) and lost replicas leave rotation immediately
  (healthz polling, the membership-heartbeat idiom);
* **zero-loss failover** — the router always streams from replicas
  internally, so it knows EXACTLY which tokens each client already has;
  on replica death it re-admits ``prompt + sent_tokens`` to a survivor
  (the prefix cache turns the re-prefill into a suffix), with bounded
  retry/backoff honoring Retry-After, and a per-request ``rerouted`` /
  ``recomputed_tokens`` ledger proving nothing was dropped;
* **elastic replica lifecycle** — the elasticity-agent idiom (restart
  budget + backoff + DSTPU_RESUME + status artifact) applied to serving:
  sustained queue pressure scales out, sustained idle drains + retires
  the newest replica, and a retiring replica ships its warm prefix cache
  to its successor as a quantized HostKVStore handoff file
  (``/admin/drain`` -> export -> ``/admin/adopt``).

Every routing decision is exact-counter accounted: ``first_choice_sheds``
(requests whose FIRST-choice replica was shedding — the would-be client
429s of a spill-blind router) vs ``client_sheds`` (requests actually
refused) is the within-run counterfactual the chaos drill asserts
``client_sheds < first_choice_sheds`` on, no wall-clock A/B needed.

The pure decision helpers (``affinity_key``, ``pick_replica``,
``plan_scale``) are DS002-registered hot paths: routing bookkeeping is
stdlib int/dict work and must never grow a host sync or a numpy
materialization. This module never imports jax — a router host needs no
accelerator runtime.
"""

import argparse
import dataclasses
import itertools
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.config import constants as C
from deepspeed_tpu.resilience.chaos import REPLICA_ID_ENV
from deepspeed_tpu.serving import http_util
from deepspeed_tpu.telemetry.tracer import TRACE_ENV, get_tracer
from deepspeed_tpu.utils.logging import logger

#: status-artifact env var (elasticity.agent STATUS_ENV idiom): when set,
#: the router keeps a JSON fleet summary at this path for env_report
FLEET_STATUS_ENV = "DSTPU_FLEET_STATUS"

#: flight-recorder directory (mirrors ``serving.server.FLIGHT_DIR_ENV``;
#: the string is duplicated here because the router must never import
#: the engine-owning module — a router host needs no accelerator runtime)
FLIGHT_DIR_ENV = "DSTPU_FLIGHT_DIR"


@dataclasses.dataclass
class FleetConfig:
    replicas: int = 2                    # initial fleet size
    # --- prefix-affinity routing ---
    affinity_enabled: bool = True
    affinity_block_tokens: int = 64      # MUST match the replicas'
    # kv_block_size: the affinity key hashes whole cache blocks
    affinity_max_keys: int = 4096        # LRU cap on the affinity memo
    # --- ladder-aware spill ---
    spill_enabled: bool = True
    # --- healthz polling (membership-heartbeat idiom) ---
    poll_interval_s: float = 0.25
    poll_timeout_s: float = 2.0
    lost_after_s: float = 2.0            # unreachable this long -> lost
    # --- zero-loss failover ---
    retry_budget: int = 3                # reroutes per request
    retry_backoff_s: float = 0.05
    retry_backoff_max_s: float = 1.0
    request_timeout_s: float = 120.0     # overall per client request
    stream_read_timeout_s: float = 30.0  # per-token socket deadline
    default_max_new_tokens: int = 64
    # --- replica lifecycle (elasticity-agent idiom) ---
    relaunch_budget: int = 1             # relaunches per lost replica
    scale_out_enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 4
    scale_out_queue_depth: int = 4       # queued >= this counts as pressure
    scale_out_pressure_polls: int = 8    # sustained polls before scale-out
    retire_idle_polls: int = 40          # sustained idle polls before retire
    drain_deadline_s: float = 60.0       # retirement drain+export deadline
    handoff_dir: str = ""                # "" -> a private temp dir
    handoff_quantize: str = "int8"       # prefix-handoff page codec
    # --- observability ---
    status_path: str = ""                # "" -> $DSTPU_FLEET_STATUS if set
    flight_dir: str = ""                 # "" -> $DSTPU_FLIGHT_DIR if set
    seed: int = 0                        # retry-jitter stream

    @classmethod
    def from_ds_config(cls, ds_config: dict) -> "FleetConfig":
        """Build from a DeepSpeed-style config dict's ``"fleet"`` group
        (key constant ``config.constants.FLEET``; unknown keys are an
        error — config drift must not fail silently)."""
        group = dict(ds_config.get(C.FLEET, {}) or {})
        names = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(group) - names)
        if unknown:
            raise ValueError(
                f"unknown '{C.FLEET}' config keys: {unknown}; "
                f"known: {sorted(names)}")
        return cls(**group)


# ----------------------------------------------------------------------
# pure routing decisions (DS002 hot paths: stdlib bookkeeping only)
# ----------------------------------------------------------------------
def affinity_key(prompt_tokens: Sequence[int],
                 block_tokens: int) -> Optional[int]:
    """Hash of the prompt's HEAD block — the first ``block_tokens``
    tokens, the root of any radix-cache chain the prompt can share.
    Keying on the head (not every full block) is deliberate: a workload
    of shared-system-prompt requests diverges after the shared head, and
    hashing the divergent tail would scatter exactly the requests that
    could reuse each other's warm pages. None when the prompt has no
    full cacheable block (``(len - 1) // block == 0``, mirroring
    ``PrefixCache.lookup``: the last prompt token is always computed, so
    it can never be part of a cached block). Tuple-of-int hashing is
    deterministic within a process, which is all routing stability
    needs."""
    if block_tokens < 1:
        return None
    full = max(len(prompt_tokens) - 1, 0) // block_tokens
    if full <= 0:
        return None
    head = tuple(int(t) for t in prompt_tokens[:block_tokens])
    return hash(head) & 0xFFFFFFFFFFFF


def pick_replica(snaps: List[dict], affinity_rid: Optional[int],
                 spill: bool,
                 exclude: frozenset) -> Tuple[Optional[int], str]:
    """One pure routing decision over healthz snapshots.

    The FIRST CHOICE is the affinity target when it is in rotation, else
    the least-loaded replica (queued + inflight + pending, id
    tie-break; ``pending`` is the router's own optimistic in-flight
    count, so requests routed between two health polls spread across
    replicas instead of piling onto the stale-idlest one). Returns
    ``(replica_id, verdict)``:

      affinity / least_loaded  first choice, accepting
      spill                    first choice shedding/draining -> healthy
                               peer (only with ``spill``)
      pinned_shedding          spill disabled: route to the shedding
                               first choice anyway and relay its 429 —
                               the ladder-blind baseline the drill's
                               counterfactual counter measures
      shed_all                 nobody in rotation accepts (rid None)
      no_replicas              rotation empty after ``exclude`` (rid None)
    """
    rotation = [s for s in snaps
                if s.get("in_rotation") and s["id"] not in exclude]
    if not rotation:
        return None, "no_replicas"

    def load(s: dict) -> Tuple[int, int]:
        return (int(s.get("queued", 0)) + int(s.get("inflight", 0))
                + int(s.get("pending", 0)), s["id"])

    def accepting(s: dict) -> bool:
        return not s.get("draining") and s.get("level") != "shed"

    first = None
    verdict = "least_loaded"
    if affinity_rid is not None:
        for s in rotation:
            if s["id"] == affinity_rid:
                first = s
                verdict = "affinity"
                break
    if first is None:
        first = min(rotation, key=load)
    if accepting(first):
        return first["id"], verdict
    if not spill:
        return first["id"], "pinned_shedding"
    takers = [s for s in rotation if accepting(s) and s["id"] != first["id"]]
    if not takers:
        return None, "shed_all"
    return min(takers, key=load)["id"], "spill"


def plan_scale(snaps: List[dict], cfg: FleetConfig, pressure_polls: int,
               idle_polls: int) -> Tuple[Optional[str], int, int]:
    """Pure scale decision from one poll's snapshots + streak counters:
    ``("out" | "retire" | None, pressure_polls', idle_polls')``. Pressure
    = EVERY in-rotation replica is off-healthy or has a deep queue;
    idle = every in-rotation replica has nothing queued or in flight.
    Streaks (not instants) drive actions so one bursty poll can't thrash
    the fleet; both reset to 0 when an action fires."""
    rotation = [s for s in snaps if s.get("in_rotation")]
    n_live = len([s for s in snaps
                  if not s.get("retired") and not s.get("lost")])
    pressured = bool(rotation) and all(
        s.get("level") != "healthy"
        or int(s.get("queued", 0)) >= cfg.scale_out_queue_depth
        for s in rotation)
    idle = bool(rotation) and all(
        int(s.get("queued", 0)) == 0 and int(s.get("inflight", 0)) == 0
        for s in rotation)
    pressure_polls = pressure_polls + 1 if pressured else 0
    idle_polls = idle_polls + 1 if idle else 0
    if (cfg.scale_out_enabled
            and pressure_polls >= cfg.scale_out_pressure_polls
            and n_live < cfg.max_replicas):
        return "out", 0, idle_polls
    if (cfg.scale_out_enabled and idle_polls >= cfg.retire_idle_polls
            and n_live > cfg.min_replicas):
        return "retire", pressure_polls, 0
    return None, pressure_polls, idle_polls


# ----------------------------------------------------------------------
# replica handles
# ----------------------------------------------------------------------
class ReplicaHandle:
    """Router-side state for one replica endpoint. ``proc`` is whatever
    the launcher returned (anything with ``poll()``/``terminate()``/
    ``kill()``; None for externally-managed or in-process replicas)."""

    def __init__(self, rid: int, url: str, proc=None):
        self.id = rid
        self.url = url
        self.proc = proc
        self.alive = False              # >= 1 successful healthz poll
        self.status = "unknown"
        self.level = "unknown"
        self.draining = False
        self.queued = 0
        self.inflight = 0
        self.prefix_cache_blocks = 0
        # router-side optimistic in-flight count: requests this router
        # routed here whose proxy attempt hasn't returned yet. healthz
        # queued/inflight lag by up to one poll interval; without this
        # every request inside that window lands on the same
        # stale-idlest replica
        self.pending = 0
        self.lost = False
        self.retired = False
        self.consecutive_failures = 0
        self.relaunches = 0
        self.last_ok = 0.0

    @property
    def in_rotation(self) -> bool:
        """Eligible for NEW requests. Draining replicas finish their
        in-flight streams but take nothing new; degraded (sticky 503)
        and stopped replicas are out the moment a poll sees them."""
        return (self.alive and not self.lost and not self.retired
                and not self.draining
                and self.status not in ("degraded", "stopped"))

    def snapshot(self) -> dict:
        return {"id": self.id, "url": self.url, "alive": self.alive,
                "status": self.status, "level": self.level,
                "draining": self.draining, "queued": self.queued,
                "inflight": self.inflight, "pending": self.pending,
                "prefix_cache_blocks": self.prefix_cache_blocks,
                "lost": self.lost, "retired": self.retired,
                "relaunches": self.relaunches,
                "in_rotation": self.in_rotation}


#: counter keys the router maintains; also the /metrics + status-artifact
#: proof surface the chaos drill asserts against
COUNTER_KEYS = (
    "submitted", "completed", "client_errors", "refused", "routed",
    "affinity_hits", "spills", "first_choice_sheds", "client_sheds",
    "reroutes", "recomputed_tokens", "requests_lost", "replicas_lost",
    "relaunches", "scale_outs", "retirements", "handoffs",
)


class FleetRouter:
    """The front tier: a stdlib ThreadingHTTPServer proxying
    ``POST /generate`` across replicas plus a healthz-polling membership
    thread making rotation/scale decisions. Construct with pre-built
    handles (in-process fleets) and/or a ``launcher(rid, resume) ->
    ReplicaHandle`` for process-managed replicas (relaunch + scale-out
    need it)."""

    def __init__(self, config: Optional[FleetConfig] = None,
                 handles: Sequence[ReplicaHandle] = (),
                 launcher: Optional[Callable[[int, bool],
                                             ReplicaHandle]] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.config = config or FleetConfig()
        self._launcher = launcher
        self._lock = threading.Lock()
        self._handles: Dict[int, ReplicaHandle] = {h.id: h for h in handles}
        self._affinity: "OrderedDict[int, int]" = OrderedDict()
        self.counters: Dict[str, int] = {k: 0 for k in COUNTER_KEYS}
        # fleet uid -> per-request ledger entry (bounded; the proof that
        # nothing was dropped rides these + the counters)
        self.ledger: "OrderedDict[int, dict]" = OrderedDict()
        self._ledger_cap = 4096
        self._fleet_uid = itertools.count(1)
        self._stop_evt = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self._pressure_polls = 0
        self._idle_polls = 0
        self._retiring = False
        self._handoff_dir = self.config.handoff_dir or None
        # flight-recorder dumps already folded into the stitched timeline
        # (each discovery announces itself exactly once)
        self._flight_seen: set = set()
        self._retry_policy = http_util.RetryPolicy(
            max_attempts=max(self.config.retry_budget, 1),
            backoff_s=self.config.retry_backoff_s,
            backoff_max_s=self.config.retry_backoff_max_s,
            seed=self.config.seed)
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            timeout = 65.0

            def log_message(self, fmt, *args):
                logger.debug("fleet: " + fmt % args)

            def handle_one_request(self):
                # a client hanging up mid-response (timeout, ctrl-C) is
                # its prerogative, not a router stack trace
                try:
                    super().handle_one_request()
                except (BrokenPipeError, ConnectionResetError):
                    self.close_connection = True

            def _json(self, code: int, payload: dict, headers=()):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    h = router.health()
                    self._json(200 if h["ok"] else 503, h)
                elif self.path == "/metrics":
                    body = router.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                raw = self.rfile.read(int(self.headers.get("Content-Length",
                                                           0) or 0))
                if self.path != "/generate":
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    body = json.loads(raw or b"{}")
                    if not isinstance(body, dict):
                        raise TypeError("payload must be a JSON object")
                except (ValueError, TypeError) as e:
                    self._json(400, {"error": f"bad request: {e!r}"})
                    return
                # the propagation channel: a client-sent X-Dstpu-Trace
                # header becomes the request's fleet-wide trace id (body
                # field wins if both — it's the more deliberate one)
                hdr_trace = self.headers.get("X-Dstpu-Trace")
                if hdr_trace and not body.get("trace_id"):
                    body["trace_id"] = hdr_trace
                if body.get("stream"):
                    sink = _ChunkSink(self)
                    status, payload, headers = router.route_generate(
                        body, sink.start, sink.emit)
                    if sink.started:
                        sink.finish(payload)
                    else:
                        self._json(status, payload, headers=headers)
                else:
                    tokens: List[int] = []
                    status, payload, headers = router.route_generate(
                        body, lambda: None, tokens.append)
                    if status == 200:
                        payload = dict(payload, tokens=tokens)
                    self._json(status, payload, headers=headers)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        self._http_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "FleetRouter":
        if self._launcher is not None and not self._handles:
            self._launch_initial()
        self._poll_once()           # routing needs snapshots before traffic
        self._http_thread = threading.Thread(target=self.httpd.serve_forever,
                                             name="dstpu-fleet-http",
                                             daemon=True)
        self._http_thread.start()
        self._poll_thread = threading.Thread(target=self._poll_loop,
                                             name="dstpu-fleet-poll",
                                             daemon=True)
        self._poll_thread.start()
        return self

    def _launch_initial(self) -> None:
        """Launch the initial fleet in parallel (worker startup dominates
        fleet bring-up; serializing N of them would N-fold it)."""
        errs: List[BaseException] = []

        def one(rid: int) -> None:
            try:
                h = self._launcher(rid, False)
            except BaseException as e:   # noqa: BLE001 — surfaced below
                errs.append(e)
                return
            with self._lock:
                self._handles[h.id] = h

        threads = [threading.Thread(target=one, args=(rid,), daemon=True)
                   for rid in range(self.config.replicas)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise RuntimeError(f"fleet launch failed: {errs[0]!r}") from \
                errs[0]

    def stop(self, terminate_replicas: bool = True) -> None:
        self._stop_evt.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5.0)
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
        if terminate_replicas:
            for h in list(self._handles.values()):
                self._terminate(h)
        self._write_status()

    @staticmethod
    def _terminate(h: ReplicaHandle, grace_s: float = 5.0) -> None:
        proc = h.proc
        if proc is None or proc.poll() is not None:
            return
        try:
            proc.terminate()
            deadline = time.monotonic() + grace_s
            while proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if proc.poll() is None:
                proc.kill()
        except Exception:
            logger.exception(f"fleet: terminating replica {h.id} failed")

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def counters_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counters)

    def ledger_snapshot(self) -> Dict[int, dict]:
        with self._lock:
            return {uid: dict(e) for uid, e in self.ledger.items()}

    def health(self) -> dict:
        with self._lock:
            snaps = [h.snapshot() for h in self._handles.values()]
            counters = dict(self.counters)
            keys = len(self._affinity)
        return {"ok": any(s["in_rotation"] for s in snaps),
                "replicas": snaps, "counters": counters,
                "affinity_keys": keys}

    def prometheus_text(self) -> str:
        """Router counters + the fleet/ tracer tracks, one TYPE block per
        family (the metrics.py discipline)."""
        lines: List[str] = []
        now = time.monotonic()
        with self._lock:
            counters = dict(self.counters)
            snaps = [h.snapshot() for h in self._handles.values()]
            # healthz staleness: seconds since the OLDEST fresh poll over
            # live replicas — the router's worst-case blind window. A
            # climbing gauge means the poll loop is wedged or a replica
            # stopped answering before being marked lost.
            ages = [now - h.last_ok for h in self._handles.values()
                    if h.alive and not h.lost and not h.retired]
        # ONE emission site for every dstpu_fleet_* family: the row list
        # can't claim a family twice (the gauge used to be a second
        # hand-emitted TYPE block inside the counter loop's namespace —
        # one COUNTER_KEYS collision away from duplicate metadata, which
        # the Prometheus text parser rejects wholesale; DS008 pins this)
        rows = [(k, "counter", counters[k]) for k in COUNTER_KEYS]
        rows.append(("replicas_in_rotation", "gauge",
                     sum(1 for s in snaps if s["in_rotation"])))
        rows.append(("healthz_staleness", "gauge",
                     round(max(ages), 6) if ages else 0.0))
        for key, kind, val in rows:
            lines.append(f"# TYPE dstpu_fleet_{key} {kind}")
            lines.append(f"dstpu_fleet_{key} {val}")
        lines.extend(get_tracer().prometheus_lines(prefix=("fleet/",
                                                           "req/")))
        return "\n".join(lines) + "\n"

    def discover_flight_dumps(self) -> List[str]:
        """Scan the flight-recorder directory for dumps left behind by
        dying/shedding replicas (``serving.server.flight_dump`` writes
        ``flight_replica{rid}_{pid}.json`` atomically, so a file that
        exists is complete). Each newly seen dump is announced once with
        a ``fleet/flight_recovered`` instant — the router-side marker the
        offline stitcher uses to fold the dump's ring into the per-request
        timeline. Returns every dump currently on disk (sorted)."""
        dirpath = self.config.flight_dir or os.environ.get(FLIGHT_DIR_ENV)
        if not dirpath or not os.path.isdir(dirpath):
            return []
        try:
            names = sorted(n for n in os.listdir(dirpath)
                           if n.startswith("flight_replica")
                           and n.endswith(".json"))
        except OSError:
            return []
        paths = [os.path.join(dirpath, n) for n in names]
        for p in paths:
            if p not in self._flight_seen:
                self._flight_seen.add(p)
                get_tracer().instant("fleet/flight_recovered", cat="serve",
                                     path=p)
                logger.warning(f"fleet: recovered flight dump {p}")
        return paths

    def _write_status(self) -> None:
        flight_dumps = self.discover_flight_dumps()
        path = self.config.status_path or os.environ.get(FLEET_STATUS_ENV)
        if not path:
            return
        with self._lock:
            doc = {"replicas": [h.snapshot()
                                for h in self._handles.values()],
                   "counters": dict(self.counters),
                   "flight_dumps": flight_dumps,
                   "updated": time.time()}
        tmp = f"{path}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2)
            os.replace(tmp, path)
        except OSError:
            logger.exception(f"fleet: writing status artifact {path} failed")

    # ------------------------------------------------------------------
    # healthz polling (membership) + lifecycle decisions
    # ------------------------------------------------------------------
    def _poll_loop(self) -> None:
        while not self._stop_evt.wait(self.config.poll_interval_s):
            try:
                with get_tracer().span("fleet/poll_tick", cat="serve"):
                    self._poll_once()
            except Exception:
                logger.exception("fleet: poll tick failed")

    def _poll_once(self) -> None:
        for h in list(self._handles.values()):
            if h.retired or h.lost:
                continue
            self._poll_replica(h)
        with self._lock:
            snaps = [h.snapshot() for h in self._handles.values()]
        get_tracer().counter(
            "fleet/rotation", cat="serve",
            in_rotation=sum(1 for s in snaps if s["in_rotation"]),
            draining=sum(1 for s in snaps if s["draining"]),
            lost=sum(1 for s in snaps if s["lost"]))
        get_tracer().counter(
            "fleet/load", cat="serve",
            queued=sum(s["queued"] for s in snaps if s["in_rotation"]),
            inflight=sum(s["inflight"] for s in snaps if s["in_rotation"]))
        action, self._pressure_polls, self._idle_polls = plan_scale(
            snaps, self.config, self._pressure_polls, self._idle_polls)
        if action == "out" and self._launcher is not None:
            self._scale_out()
        elif action == "retire" and not self._retiring:
            self._retire_one()
        self._write_status()

    def _poll_replica(self, h: ReplicaHandle) -> None:
        try:
            reply = http_util.request_json(
                "GET", h.url + "/healthz",
                timeout_s=self.config.poll_timeout_s)
        except Exception:
            h.consecutive_failures += 1
            proc_dead = h.proc is not None and h.proc.poll() is not None
            window = (h.consecutive_failures
                      * max(self.config.poll_interval_s, 0.01))
            if proc_dead or window >= self.config.lost_after_s:
                self._mark_lost(h, "process exited" if proc_dead
                                else "healthz unreachable")
            return
        payload = reply.json()
        h.consecutive_failures = 0
        h.alive = True
        h.last_ok = time.monotonic()
        was_in = h.in_rotation
        h.status = str(payload.get("status", "unknown"))
        h.level = str(payload.get("level", "unknown"))
        h.draining = bool(payload.get("draining"))
        h.queued = int(payload.get("queued", 0) or 0)
        h.inflight = int(payload.get("inflight", 0) or 0)
        h.prefix_cache_blocks = int(payload.get("prefix_cache_blocks", 0)
                                    or 0)
        if was_in and not h.in_rotation:
            # sticky-503/degraded/draining: out of rotation the moment the
            # poll sees it — no request waits for a timeout to learn this
            get_tracer().instant("fleet/out_of_rotation", cat="serve",
                                 replica=h.id, status=h.status,
                                 level=h.level)
            logger.warning(f"fleet: replica {h.id} out of rotation "
                           f"(status={h.status} level={h.level})")

    def _mark_lost(self, h: ReplicaHandle, reason: str) -> None:
        if h.lost or h.retired:
            return
        h.lost = True
        h.alive = False
        with self._lock:
            self.counters["replicas_lost"] += 1
            # affinity entries pointing at a corpse would keep steering
            # requests into the failover path; drop them now
            dead_keys = [k for k, rid in self._affinity.items()
                         if rid == h.id]
            for k in dead_keys:
                del self._affinity[k]
        get_tracer().instant("fleet/replica_lost", cat="serve",
                             replica=h.id, reason=reason)
        logger.warning(f"fleet: replica {h.id} LOST ({reason})")
        if (self._launcher is not None and h.proc is not None
                and h.relaunches < self.config.relaunch_budget):
            threading.Thread(target=self._relaunch, args=(h,),
                             name=f"dstpu-fleet-relaunch-{h.id}",
                             daemon=True).start()

    def _relaunch(self, dead: ReplicaHandle) -> None:
        """Elastic-agent idiom: relaunch a lost replica under its id with
        DSTPU_RESUME set (the chaos die-once contract spares it), within
        the relaunch budget."""
        try:
            fresh = self._launcher(dead.id, True)
        except Exception:
            logger.exception(f"fleet: relaunch of replica {dead.id} failed")
            return
        fresh.relaunches = dead.relaunches + 1
        with self._lock:
            self.counters["relaunches"] += 1
            self._handles[dead.id] = fresh
        get_tracer().instant("fleet/replica_relaunched", cat="serve",
                             replica=dead.id,
                             relaunches=fresh.relaunches)
        logger.warning(f"fleet: replica {dead.id} relaunched "
                       f"({fresh.relaunches}/{self.config.relaunch_budget})")

    def _scale_out(self) -> None:
        with self._lock:
            rid = max(self._handles, default=-1) + 1
            self.counters["scale_outs"] += 1
        get_tracer().instant("fleet/scale_out", cat="serve", replica=rid)
        logger.warning(f"fleet: scaling out -> replica {rid}")

        def launch() -> None:
            try:
                fresh = self._launcher(rid, False)
            except Exception:
                logger.exception(f"fleet: scale-out launch of replica "
                                 f"{rid} failed")
                return
            with self._lock:
                self._handles[rid] = fresh

        threading.Thread(target=launch, name=f"dstpu-fleet-scale-{rid}",
                         daemon=True).start()

    def _retire_one(self) -> None:
        """Drain + retire the newest in-rotation replica (LIFO, the
        scale-out inverse), shipping its warm prefix cache to the least-
        loaded survivor via the handoff file."""
        with self._lock:
            rotation = [h for h in self._handles.values() if h.in_rotation]
            if len(rotation) <= self.config.min_replicas:
                return
            victim = max(rotation, key=lambda h: h.id)
            survivors = [h for h in rotation if h.id != victim.id]
            successor = min(survivors,
                            key=lambda h: (h.queued + h.inflight, h.id)) \
                if survivors else None
            victim.draining = True       # out of rotation immediately
            self.counters["retirements"] += 1
            self._retiring = True
        if self._handoff_dir is None:
            self._handoff_dir = tempfile.mkdtemp(prefix="dstpu-fleet-")
        path = os.path.join(self._handoff_dir,
                            f"handoff_replica_{victim.id}.npz")
        get_tracer().instant("fleet/retire", cat="serve", replica=victim.id,
                             successor=(successor.id if successor else -1))
        logger.warning(f"fleet: retiring replica {victim.id} "
                       f"(successor {successor.id if successor else None})")
        try:
            http_util.request_json(
                "POST", victim.url + "/admin/drain",
                payload={"handoff_path": path,
                         "quantize": self.config.handoff_quantize},
                timeout_s=self.config.poll_timeout_s)
        except Exception:
            logger.exception(f"fleet: drain request to replica "
                             f"{victim.id} failed")
        threading.Thread(target=self._finish_retirement,
                         args=(victim, successor, path),
                         name=f"dstpu-fleet-retire-{victim.id}",
                         daemon=True).start()

    def _finish_retirement(self, victim: ReplicaHandle,
                           successor: Optional[ReplicaHandle],
                           path: str) -> None:
        try:
            deadline = time.monotonic() + self.config.drain_deadline_s
            # the handoff file appears (atomic rename) when the victim's
            # drain -> stop -> export completed; a dead/cache-less victim
            # never writes one, so the deadline moves things along
            while time.monotonic() < deadline and not os.path.exists(path):
                proc_exited = (victim.proc is not None
                               and victim.proc.poll() is not None)
                if proc_exited:
                    break
                time.sleep(0.1)
            if os.path.exists(path) and successor is not None \
                    and not successor.lost:
                try:
                    http_util.request_json(
                        "POST", successor.url + "/admin/adopt",
                        payload={"handoff_path": path},
                        timeout_s=self.config.poll_timeout_s)
                    with self._lock:
                        self.counters["handoffs"] += 1
                    get_tracer().instant("fleet/handoff", cat="serve",
                                         replica=victim.id,
                                         successor=successor.id)
                except Exception:
                    logger.exception("fleet: handoff adopt failed")
            with self._lock:
                victim.retired = True
                dead_keys = [k for k, rid in self._affinity.items()
                             if rid == victim.id]
                for k in dead_keys:
                    del self._affinity[k]
            self._terminate(victim)
        finally:
            self._retiring = False

    # ------------------------------------------------------------------
    # the request path
    # ------------------------------------------------------------------
    def route_generate(self, body: dict, started: Callable[[], None],
                       emit: Callable[[int], None]
                       ) -> Tuple[int, dict, list]:
        """Route + proxy one client request with zero-loss failover.
        ``started()`` fires once, just before the first token can flow
        (streaming handlers send their 200 header there); ``emit(tok)``
        forwards each generated token. Returns ``(status, payload,
        headers)`` — the final record for streaming clients, the whole
        response for non-streaming ones."""
        cfg = self.config
        prompt = body.get("prompt_tokens")
        if not isinstance(prompt, list) or not all(
                isinstance(t, int) and not isinstance(t, bool)
                for t in prompt):
            with self._lock:
                self.counters["submitted"] += 1
                self.counters["client_errors"] += 1
            return 400, {"error": "prompt_tokens must be a list of ints"}, []
        try:
            max_new = int(body.get("max_new_tokens")
                          or cfg.default_max_new_tokens)
        except (TypeError, ValueError):
            with self._lock:
                self.counters["submitted"] += 1
                self.counters["client_errors"] += 1
            return 400, {"error": "bad max_new_tokens"}, []
        uid = next(self._fleet_uid)
        # fleet-wide trace id: accepted from the client (X-Dstpu-Trace /
        # body), minted here otherwise. Propagated to every replica the
        # request touches; the router's req/wall span below is the
        # envelope the offline stitcher ties the replica phases against.
        trace_id = str(body.get("trace_id") or f"r{os.getpid()}-{uid}")
        wall_t0 = time.monotonic()
        key = (affinity_key(prompt, cfg.affinity_block_tokens)
               if cfg.affinity_enabled else None)
        entry = {"rerouted": 0, "recomputed_tokens": 0, "tokens": 0,
                 "replicas": [], "state": "routing", "trace_id": trace_id}
        with self._lock:
            self.counters["submitted"] += 1
            self.ledger[uid] = entry
            while len(self.ledger) > self._ledger_cap:
                self.ledger.popitem(last=False)
        sent: List[int] = []
        tried: set = set()
        first_attempt = True
        first_shed_counted = False
        reroutes_left = cfg.retry_budget
        deadline = time.monotonic() + (float(body.get("timeout_s"))
                                       if body.get("timeout_s")
                                       else cfg.request_timeout_s)

        def note_first_shed() -> None:
            nonlocal first_shed_counted
            if first_attempt and not first_shed_counted:
                first_shed_counted = True
                with self._lock:
                    self.counters["first_choice_sheds"] += 1

        def finish_wall(outcome: str) -> None:
            # the router-observed wall time for this request, start to
            # terminal — the tie-out denominator: replica span sums plus
            # router-attributed gaps (reroute backoffs) must account for
            # this envelope within reqtrace's tolerance
            get_tracer().complete("req/wall", time.monotonic() - wall_t0,
                                  cat="serve", trace_id=trace_id, uid=uid,
                                  outcome=outcome, tokens=len(sent),
                                  replicas=list(entry["replicas"]))

        while True:
            with self._lock:
                snaps = [h.snapshot() for h in self._handles.values()]
                arid = (self._affinity.get(key)
                        if key is not None else None)
            rid, verdict = pick_replica(snaps, arid, cfg.spill_enabled,
                                        frozenset(tried))
            if verdict in ("pinned_shedding", "spill", "shed_all"):
                note_first_shed()
            if verdict == "spill":
                with self._lock:
                    self.counters["spills"] += 1
                get_tracer().instant("fleet/spill", cat="serve", uid=uid,
                                     to=rid)
            if rid is None:
                if tried and time.monotonic() < deadline:
                    # everyone was tried this round: forget the round and
                    # re-pick after a backoff (replicas recover, relaunch)
                    tried.clear()
                    time.sleep(http_util.backoff_delay(
                        self._retry_policy, 1, salt=uid))
                    first_attempt = False
                    continue
                if verdict == "shed_all" and not sent:
                    with self._lock:
                        self.counters["client_sheds"] += 1
                    entry["state"] = "shed"
                    finish_wall("shed")
                    return (429, {"uid": uid, "error": "fleet shedding",
                                  "retry_after_s": 1.0},
                            [("Retry-After", "1")])
                finish_wall("lost")
                return self._lose(uid, entry, sent,
                                  "no replicas in rotation")
            handle = self._handles.get(rid)
            if handle is None:
                tried.add(rid)
                continue
            with self._lock:
                self.counters["routed"] += 1
                if verdict == "affinity":
                    self.counters["affinity_hits"] += 1
                entry["replicas"].append(rid)
            remaining = max_new - len(sent)
            if remaining <= 0:
                # the dying replica streamed the full budget but its final
                # record never arrived: the generation is complete
                entry["state"] = "finished"
                entry["tokens"] = len(sent)
                with self._lock:
                    self.counters["completed"] += 1
                finish_wall("finished")
                return 200, self._final(uid, entry, sent, rid,
                                        {"finish_reason": "length",
                                         "state": "finished"}), []
            with self._lock:
                handle.pending += 1
            try:
                kind, info = self._proxy_once(handle, prompt + sent,
                                              remaining, body, uid, sent,
                                              started, emit, deadline,
                                              trace_id)
            finally:
                with self._lock:
                    handle.pending = max(0, handle.pending - 1)
            if kind == "done":
                if key is not None:
                    with self._lock:
                        self._affinity[key] = rid
                        self._affinity.move_to_end(key)
                        while len(self._affinity) > cfg.affinity_max_keys:
                            self._affinity.popitem(last=False)
                entry["state"] = str(info.get("state", "finished"))
                entry["tokens"] = len(sent)
                with self._lock:
                    self.counters["completed"] += 1
                finish_wall("finished")
                return 200, self._final(uid, entry, sent, rid, info), []
            if kind == "client_error":
                with self._lock:
                    self.counters["client_errors"] += 1
                entry["state"] = "client_error"
                finish_wall("client_error")
                return 400, dict(info, uid=uid), []
            if kind == "shed":
                # the replica's door 429'd a request the poll snapshot
                # thought it would take — same shed, later signal
                note_first_shed()
                tried.add(rid)
                if not cfg.spill_enabled:
                    with self._lock:
                        self.counters["client_sheds"] += 1
                    entry["state"] = "shed"
                    finish_wall("shed")
                    ra = info if isinstance(info, (int, float)) else 1.0
                    return (429, {"uid": uid, "error": "replica shedding",
                                  "retry_after_s": ra},
                            [("Retry-After", f"{ra:.0f}")])
                first_attempt = False
                continue
            if kind == "refused":
                # 503 at the door (draining/degraded): not a shed, try a
                # peer; counted so conservation still closes
                tried.add(rid)
                with self._lock:
                    self.counters["refused"] += 1
                first_attempt = False
                continue
            # kind == "died": transport death / mid-stream abort — the
            # zero-loss failover path
            if reroutes_left <= 0 or time.monotonic() >= deadline:
                finish_wall("lost")
                return self._lose(uid, entry, sent,
                                  f"retry budget exhausted after {info!r}")
            attempt = cfg.retry_budget - reroutes_left + 1
            reroutes_left -= 1
            recompute = len(prompt) + len(sent)
            with self._lock:
                self.counters["reroutes"] += 1
                self.counters["recomputed_tokens"] += recompute
                entry["rerouted"] += 1
                entry["recomputed_tokens"] += recompute
            get_tracer().instant("fleet/reroute", cat="serve", uid=uid,
                                 from_replica=rid, sent=len(sent),
                                 recompute=recompute)
            logger.warning(f"fleet: rerouting request {uid} off replica "
                           f"{rid} with {len(sent)} tokens already "
                           f"streamed ({info!r})")
            tried.add(rid)
            delay = http_util.backoff_delay(self._retry_policy, attempt,
                                            salt=uid)
            time.sleep(delay)
            # the reroute backoff is router-attributed time: it links the
            # dying replica's spans to the survivor's in the stitched
            # timeline AND accounts for the gap between them (tie-out)
            get_tracer().complete("req/reroute", delay, cat="serve",
                                  trace_id=trace_id, uid=uid,
                                  from_replica=rid, sent=len(sent),
                                  recompute=recompute)
            first_attempt = False

    def _lose(self, uid: int, entry: dict, sent: List[int],
              reason: str) -> Tuple[int, dict, list]:
        with self._lock:
            self.counters["requests_lost"] += 1
        entry["state"] = "lost"
        entry["tokens"] = len(sent)
        get_tracer().instant("fleet/request_lost", cat="serve", uid=uid,
                             reason=reason)
        logger.error(f"fleet: request {uid} LOST ({reason})")
        return 503, {"uid": uid, "error": f"request lost: {reason}",
                     "tokens_streamed": len(sent)}, []

    def _final(self, uid: int, entry: dict, sent: List[int], rid: int,
               info: dict) -> dict:
        return {"uid": uid, "state": entry["state"],
                "finish_reason": info.get("finish_reason"),
                "trace_id": entry.get("trace_id"),
                "replica_id": rid, "replicas": list(entry["replicas"]),
                "rerouted": entry["rerouted"],
                "recomputed_tokens": entry["recomputed_tokens"],
                "tokens_streamed": len(sent)}

    def _proxy_once(self, handle: ReplicaHandle, prompt: List[int],
                    max_new: int, body: dict, uid: int, sent: List[int],
                    started: Callable[[], None],
                    emit: Callable[[int], None],
                    deadline: float, trace_id: str) -> Tuple[str, object]:
        """One streamed attempt against one replica. The router ALWAYS
        streams internally — even for non-streaming clients — because the
        exact sent-token count is what makes failover lossless. Tokens
        are appended to ``sent`` and forwarded through ``emit`` the
        moment they arrive, so whatever the failure mode, the ledger
        knows precisely what the client already holds.

        Returns ``(kind, info)``: ``done`` (final record), ``shed``
        (door 429, info=retry_after_s), ``refused`` (door 503),
        ``client_error`` (door 400), ``died`` (transport death / broken
        stream / server error — the failover trigger)."""
        payload = {"prompt_tokens": prompt, "max_new_tokens": max_new,
                   "stream": True, "priority": body.get("priority", 0),
                   # the dedupe uid: the submit may be retried because THIS
                   # id makes the retry safe to attribute
                   "client_uid": uid,
                   # trace propagation rides the body too, for transports
                   # that strip custom headers
                   "trace_id": trace_id}
        if body.get("timeout_s") is not None:
            payload["timeout_s"] = body["timeout_s"]
        io_timeout = min(self.config.stream_read_timeout_s,
                         max(deadline - time.monotonic(), 0.05))
        try:
            reply = http_util.open_stream(handle.url + "/generate", payload,
                                          timeout_s=io_timeout,
                                          headers={"X-Dstpu-Trace": trace_id})
        except Exception as e:
            return "died", repr(e)
        if reply.status == 429:
            return "shed", (reply.retry_after_s() or 1.0)
        if reply.status == 503:
            return "refused", (reply.error or {})
        if reply.status == 400:
            return "client_error", (reply.error or {})
        if reply.status != 200:
            return "died", f"status {reply.status}"
        started()
        try:
            for rec in reply.records():
                if "token" in rec:
                    tok = int(rec["token"])
                    sent.append(tok)
                    emit(tok)
                elif rec.get("done"):
                    state = str(rec.get("state", "finished"))
                    if rec.get("error") or state not in ("finished",):
                        # the replica aborted/failed the stream underneath
                        # us — same contract as a death: re-admit elsewhere
                        return "died", rec.get("error", state)
                    return "done", rec
        except Exception as e:
            return "died", repr(e)
        finally:
            reply.close()
        return "died", "stream ended without a final record"


class _ChunkSink:
    """Lazy chunked-response writer for the router's streaming path: the
    200 header goes out only once a replica actually accepted the request
    (``start``), so door-rejections can still be plain status replies."""

    def __init__(self, handler):
        self._h = handler
        self.started = False

    def start(self) -> None:
        if self.started:
            return
        self.started = True
        h = self._h
        h.send_response(200)
        h.send_header("Content-Type", "application/jsonlines")
        h.send_header("Transfer-Encoding", "chunked")
        h.end_headers()

    def _chunk(self, obj: dict) -> None:
        data = (json.dumps(obj) + "\n").encode()
        self._h.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self._h.wfile.flush()

    def emit(self, tok: int) -> None:
        self.start()
        try:
            self._chunk({"token": tok})
        except OSError:
            # client went away; keep consuming the replica stream so the
            # ledger still closes, just stop forwarding
            pass

    def finish(self, payload: dict) -> None:
        try:
            self._chunk(dict(payload, done=True))
            self._h.wfile.write(b"0\r\n\r\n")
        except OSError:
            pass
        self._h.close_connection = True


# ----------------------------------------------------------------------
# subprocess replicas (bin/dstpu_fleet + the chaos kill drill)
# ----------------------------------------------------------------------
def subprocess_launcher(workdir: str, worker_args: Sequence[str] = (),
                        start_timeout_s: float = 180.0
                        ) -> Callable[[int, bool], ReplicaHandle]:
    """A launcher over ``fleet_worker`` subprocesses. Each worker gets
    ``DSTPU_REPLICA_ID`` (the chaos replica-kill selector + healthz
    identity); relaunches set ``DSTPU_RESUME`` so die-once chaos spares
    them (elastic-agent contract). The worker publishes its URL through a
    ready file; stdout/stderr land in per-replica logs under
    ``workdir``."""

    def launch(rid: int, resume: bool) -> ReplicaHandle:
        ready = os.path.join(workdir, f"replica_{rid}.ready.json")
        if os.path.exists(ready):
            os.remove(ready)
        cmd = [sys.executable, "-m", "deepspeed_tpu.serving.fleet_worker",
               "--replica-id", str(rid), "--ready-file", ready,
               *worker_args]
        env = dict(os.environ)
        env[REPLICA_ID_ENV] = str(rid)
        # flight recorder: workers dump their ring + in-flight ledgers
        # here on death/shed (an explicit $DSTPU_FLIGHT_DIR wins so
        # drills can point the whole fleet at one directory)
        env.setdefault(FLIGHT_DIR_ENV, workdir)
        # $DSTPU_TRACE on the router would be inherited verbatim: every
        # worker's atexit ring dump would clobber the same file (and the
        # router's own dump). Derive a per-replica path instead — the
        # survivor rings it produces are exactly what `dstpu reqtrace`
        # stitches next to the router ring and the flight dumps.
        trace_path = env.get(TRACE_ENV)
        if trace_path:
            base, ext = os.path.splitext(trace_path)
            env[TRACE_ENV] = f"{base}_replica{rid}{ext or '.json'}"
        if resume:
            env["DSTPU_RESUME"] = "fleet-relaunch"
        else:
            env.pop("DSTPU_RESUME", None)
        log_path = os.path.join(workdir, f"replica_{rid}.log")
        log_f = open(log_path, "ab")
        try:
            proc = subprocess.Popen(cmd, env=env, stdout=log_f,
                                    stderr=subprocess.STDOUT)
        finally:
            log_f.close()
        deadline = time.monotonic() + start_timeout_s
        while time.monotonic() < deadline:
            if os.path.exists(ready):
                try:
                    with open(ready) as f:
                        info = json.load(f)
                    return ReplicaHandle(rid, info["url"], proc=proc)
                except (OSError, ValueError, KeyError):
                    pass    # mid-write; retry
            if proc.poll() is not None:
                raise RuntimeError(
                    f"replica {rid} exited with {proc.returncode} before "
                    f"ready (log: {log_path})")
            time.sleep(0.1)
        proc.kill()
        raise RuntimeError(f"replica {rid} not ready within "
                           f"{start_timeout_s:.0f}s (log: {log_path})")

    return launch


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``dstpu_fleet``: run the router over N tiny hermetic replicas
    (subprocess fleet_worker each) or over externally-managed replica
    URLs (``--replica-url``, repeatable — e.g. N ``dstpu_serve``
    processes serving a real checkpoint)."""
    p = argparse.ArgumentParser(prog="dstpu_fleet", description=main.__doc__)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--replica-url", action="append", default=[],
                   help="route over these URLs instead of launching "
                        "workers (repeatable)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--no-affinity", action="store_true")
    p.add_argument("--no-spill", action="store_true")
    p.add_argument("--scale-out", action="store_true",
                   help="enable elastic scale-out/retire")
    p.add_argument("--max-replicas", type=int, default=4)
    p.add_argument("--kv-num-blocks", type=int, default=64)
    p.add_argument("--kv-block-size", type=int, default=16)
    p.add_argument("--workdir", default="",
                   help="ready files + replica logs (default: temp dir)")
    p.add_argument("--status-path", default="",
                   help="fleet status artifact (default: "
                        "$DSTPU_FLEET_STATUS if set)")
    args = p.parse_args(argv)
    workdir = args.workdir or tempfile.mkdtemp(prefix="dstpu-fleet-")
    cfg = FleetConfig(replicas=args.replicas,
                      affinity_enabled=not args.no_affinity,
                      affinity_block_tokens=args.kv_block_size,
                      spill_enabled=not args.no_spill,
                      scale_out_enabled=args.scale_out,
                      max_replicas=args.max_replicas,
                      handoff_dir=workdir,
                      # workers flight-dump into the workdir by default
                      # (subprocess_launcher's $DSTPU_FLIGHT_DIR
                      # setdefault) — look for recoveries there unless
                      # the operator pointed the fleet elsewhere
                      flight_dir=os.environ.get(FLIGHT_DIR_ENV, workdir),
                      status_path=args.status_path)
    if args.replica_url:
        handles = [ReplicaHandle(i, u)
                   for i, u in enumerate(args.replica_url)]
        router = FleetRouter(cfg, handles=handles, host=args.host,
                             port=args.port)
    else:
        launcher = subprocess_launcher(
            workdir, worker_args=["--kv-num-blocks",
                                  str(args.kv_num_blocks),
                                  "--kv-block-size",
                                  str(args.kv_block_size)])
        router = FleetRouter(cfg, launcher=launcher, host=args.host,
                             port=args.port)
    router.start()
    print(f"dstpu_fleet: routing on {router.url} "
          f"({len(router.health()['replicas'])} replicas; workdir "
          f"{workdir})")
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
