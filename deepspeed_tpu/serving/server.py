"""Continuous-batching serve loop over ``InferenceEngineV2``.

Reference analog: DeepSpeed-MII's async pipeline — the missing layer the
SURVEY marks "serving layer (MII, external)" above the v2 ragged engine.
Architecture:

  submit() threads --> bounded admission queue --> serve loop (ONE thread)
                                                     |-- engine.admit / step
                                                     |-- token fan-out to
                                                     |   per-request streams
                                                     `-- deadline / cancel /
                                                         reap / metrics

The engine is single-threaded by construction (jit dispatch + host-side KV
bookkeeping), so ONLY the serve loop touches it; callers interact through
thread-safe ``Request`` objects. Admission control is two-tier: a bounded
queue (depth) plus a projected KV-occupancy watermark — both reject at
``submit()`` with a retry-after hint rather than buffering unboundedly.
"""

import itertools
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.request import Request, RequestState
from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.logging import logger


class BackpressureError(RuntimeError):
    """Admission rejected: queue full or projected KV occupancy over the
    watermark. ``retry_after_s`` is the client backoff hint (HTTP 429 +
    Retry-After in the front-end)."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class ServerClosedError(RuntimeError):
    """Submission refused: the server is draining or stopped."""


class _EngineStepError(RuntimeError):
    """Internal: ``engine.step`` raised — engine state is suspect, so the
    serve loop fails every engine-resident request (other tick errors are
    logged and survived)."""


@dataclass
class ServingConfig:
    max_queue_depth: int = 64            # bounded admission queue
    kv_high_watermark: float = 0.95      # projected KV-occupancy reject line
    default_max_new_tokens: int = 64
    default_timeout_s: Optional[float] = None   # per-request deadline
    retry_after_s: float = 1.0           # backoff hint on rejection
    idle_poll_s: float = 0.002           # loop sleep when no work
    monitor_export_every: int = 0        # engine steps between monitor
    # exports; 0 disables the fan-out even when a monitor is attached


class InferenceServer:
    """Drives one ``InferenceEngineV2`` from a background thread with
    continuous batching, streaming fan-out, admission control, and
    graceful drain (the shutdown AND elastic-resize hook: drain, resize or
    recreate the engine, start a fresh server)."""

    def __init__(self, engine, config: Optional[ServingConfig] = None,
                 monitor=None, membership=None):
        self.engine = engine
        self.config = config or ServingConfig()
        # optional resilience.membership.MembershipView: a wedged/lost peer
        # flips this replica to degraded (503) BEFORE the serve tick walks
        # into a collective that would hang it forever
        self.membership = membership
        if not 0.0 < self.config.kv_high_watermark <= 1.0:
            # the watermark IS the no-mid-decode-exhaustion invariant: the
            # sum of accepted requests' worst-case blocks never exceeds
            # watermark * usable blocks, so lazy per-step reservation can't
            # run dry; above 1.0 that guarantee is gone
            raise ValueError(
                f"kv_high_watermark must be in (0, 1], got "
                f"{self.config.kv_high_watermark}")
        self.metrics = ServingMetrics()
        self.monitor = monitor
        self._uid = itertools.count(1)
        self._lock = threading.Lock()          # queue + tables, never engine
        self._queue: List[Request] = []        # accepted, not yet in engine
        self._inflight: Dict[int, Request] = {}  # uid -> engine-resident
        self._draining = False
        self._stopped = False
        self._degraded: Optional[str] = None   # sticky engine-failure reason
        self._kv_drifted = False   # edge detector for the kv_drift instant
        self._wake = threading.Event()         # submit() nudges the loop
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "InferenceServer":
        if self._thread is not None:
            raise RuntimeError("server already started")
        self._thread = threading.Thread(target=self._serve_loop,
                                        name="dstpu-serve", daemon=True)
        self._thread.start()
        return self

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop accepting new requests; keep stepping until every accepted
        request reaches a terminal state. Returns True when fully drained
        (False on timeout, with requests still in flight)."""
        with self._lock:
            self._draining = True
        self._wake.set()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                live = len(self._queue) + len(self._inflight)
            if live == 0:
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(self.config.idle_poll_s)

    def stop(self, drain_timeout: Optional[float] = 30.0):
        """Graceful shutdown: drain, then stop the loop. Requests still
        live after the drain timeout are force-cancelled."""
        if self._thread is None or not self._thread.is_alive():
            # no serve loop to honor cancellations: settle accepted
            # requests directly instead of polling a drain that can't
            # progress (callers blocked in result() would hang forever)
            with self._lock:
                self._draining = True
            self._fail_all("server stopped before the serve loop ran")
            with self._lock:
                self._stopped = True
            return
        drained = self.drain(timeout=drain_timeout)
        if not drained:
            with self._lock:
                leftovers = list(self._queue) + list(self._inflight.values())
            for req in leftovers:
                req.cancel()
            self.drain(timeout=5.0)
        with self._lock:
            self._stopped = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    @property
    def running(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and not self._stopped)

    @property
    def draining(self) -> bool:
        return self._draining

    def health(self) -> dict:
        with self._lock:
            queued, inflight = len(self._queue), len(self._inflight)
            degraded = self._degraded
        state = ("stopped" if self._stopped else
                 # an engine-step failure means the KV/sequence state is
                 # suspect: report unhealthy (503 at /healthz) so load
                 # balancers stop routing here — sticky until the engine is
                 # replaced (drain + recreate), not self-clearing
                 "degraded" if degraded else
                 "draining" if self._draining else
                 "serving" if self.running else "not_started")
        out = {"status": state, "ok": state == "serving",
               "queued": queued, "inflight": inflight,
               "kv_occupancy": self.engine.kv_occupancy()}
        if degraded:
            out["degraded_reason"] = degraded
        if self.membership is not None:
            out["membership"] = self.membership.summary()
        return out

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------
    def _blocks_for(self, req: Request) -> int:
        return self.engine.kv.blocks_needed(
            len(req.prompt_tokens) + req.max_new_tokens)

    def submit(self, prompt_tokens: Sequence[int],
               max_new_tokens: Optional[int] = None,
               timeout_s: Optional[float] = None) -> Request:
        """Accept a request (thread-safe) or reject synchronously.
        Raises ``ServerClosedError`` when draining/stopped and
        ``BackpressureError`` when the queue or the projected KV occupancy
        is over its limit."""
        cfg = self.config
        if max_new_tokens is None:
            max_new_tokens = cfg.default_max_new_tokens
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        req = Request(uid=next(self._uid), prompt_tokens=prompt_tokens,
                      max_new_tokens=max_new_tokens,
                      timeout_s=(timeout_s if timeout_s is not None
                                 else cfg.default_timeout_s))
        if not req.prompt_tokens:
            raise ValueError("empty prompt")
        max_ctx = self.engine.state.max_context_length
        if len(req.prompt_tokens) + req.max_new_tokens > max_ctx:
            # past max_seq_len the decode would silently clamp positions
            # (garbage RoPE rotations), so reject at the door
            raise ValueError(
                f"prompt ({len(req.prompt_tokens)}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max context {max_ctx}")
        with self._lock:
            if self._draining or self._stopped:
                raise ServerClosedError("server is draining; not accepting "
                                        "new requests")
            if self._degraded:
                # new work on a suspect engine would fail anyway — refuse at
                # the door (503) until the replica is drained and replaced
                raise ServerClosedError(
                    f"server degraded ({self._degraded}); not accepting "
                    "new requests")
            if len(self._queue) >= cfg.max_queue_depth:
                self.metrics.on_reject()
                get_tracer().instant("serve/backpressure", cat="serve",
                                     kind="queue_full")
                raise BackpressureError(
                    f"admission queue full ({cfg.max_queue_depth}); retry "
                    f"after {cfg.retry_after_s:.1f}s", cfg.retry_after_s)
            # projected occupancy at completion: worst-case blocks of every
            # accepted request (queued AND in flight — an admitted request
            # keeps reserving blocks as it decodes) + this one
            total_blocks = max(self.engine.kv_usable_blocks(), 1)
            projected = (sum(self._blocks_for(r) for r in self._queue)
                         + sum(self._blocks_for(r)
                               for r in self._inflight.values())
                         + self._blocks_for(req))
            if projected / total_blocks > cfg.kv_high_watermark:
                self.metrics.on_reject()
                get_tracer().instant("serve/backpressure", cat="serve",
                                     kind="kv_watermark")
                raise BackpressureError(
                    f"projected KV occupancy {projected}/{total_blocks} over "
                    f"watermark {cfg.kv_high_watermark:.2f}; retry after "
                    f"{cfg.retry_after_s:.1f}s", cfg.retry_after_s)
            self._queue.append(req)
        self.metrics.on_submit()
        self._wake.set()
        return req

    def cancel(self, uid: int) -> bool:
        """Request cancellation by uid; True if the request was found live."""
        with self._lock:
            for r in self._queue:
                if r.uid == uid:
                    r.cancel()
                    return True
            req = self._inflight.get(uid)
        if req is not None:
            req.cancel()
            return True
        return False

    # ------------------------------------------------------------------
    # the serve loop (single thread; sole owner of the engine)
    # ------------------------------------------------------------------
    def _serve_loop(self):
        while True:
            if self._stopped:
                return
            try:
                worked = self._serve_once()
            except _EngineStepError as e:
                # the KV cache / sequence state may be inconsistent after a
                # failed step: every engine-resident request is compromised
                # and the replica must stop advertising itself healthy
                logger.exception("serve loop: engine step failed; failing "
                                 "in-flight requests")
                get_tracer().instant("serve/degraded", cat="serve",
                                     reason="engine_step_failed")
                with self._lock:
                    self._degraded = f"engine step failed: {e}"
                self._fail_all("engine step raised")
                worked = False
            except Exception:
                # non-engine bookkeeping glitch: requests are still healthy,
                # log and keep serving
                logger.exception("serve loop: non-fatal tick error")
                worked = False
            if not worked:
                # nothing to do: block until a submit() nudge (bounded so
                # deadline expiry of QUEUED requests is still noticed)
                self._wake.wait(timeout=self.config.idle_poll_s * 10)
                self._wake.clear()

    def _serve_once(self) -> bool:
        if self.membership is not None and self._degraded is None:
            if not self._check_membership():
                return False
        self._expire_and_cancel()
        self._admit_from_queue()
        worked = False
        if self.engine.has_work():
            try:
                with get_tracer().span("serve/engine_step", cat="serve"):
                    out = self.engine.step()
            except Exception as e:
                raise _EngineStepError(str(e)) from e
            self.metrics.on_step()
            worked = True
            self._fan_out(out)
        self._reap()
        with self._lock:
            queued, inflight = len(self._queue), len(self._inflight)
            # the admission model's worst-case projection, re-derived at
            # tick time over everything still live (same sum submit()
            # admits against)
            projected_blocks = (sum(self._blocks_for(r) for r in self._queue)
                                + sum(self._blocks_for(r)
                                      for r in self._inflight.values()))
        self._reconcile_kv(projected_blocks)
        self.metrics.set_gauges(queue_depth=queued, inflight=inflight,
                                kv_occupancy=self.engine.kv_occupancy())
        every = self.config.monitor_export_every
        if every and self.metrics.engine_steps % every == 0:
            try:
                self.metrics.export(self.monitor, self.metrics.engine_steps)
            except Exception:
                logger.exception("serve loop: monitor export failed")
        return worked

    def _reconcile_kv(self, projected_blocks: int) -> None:
        """Reconcile the projected KV watermark (admission control's model
        of memory) against what the engine actually reserved — so the
        model itself is observable: ``kv_projected_bytes`` vs
        ``kv_observed_bytes`` gauges on ``/metrics``, a ``serve/kv_bytes``
        counter track on the dstrace timeline, and an edge-triggered
        ``serve/kv_drift`` instant when they diverge >10% (the projection
        over-reserving is expected mid-decode; *sustained* divergence
        means admission is turning work away on memory it actually has).
        Pure host-int arithmetic — the serve tick stays sync-free."""
        block_bytes = getattr(self.engine, "kv_block_bytes", None)
        if block_bytes is None:
            return
        bb = block_bytes()
        projected = projected_blocks * bb
        observed = self.engine.kv_reserved_blocks() * bb
        self.metrics.set_kv_bytes(projected, observed)
        tracer = get_tracer()
        if tracer.enabled:
            tracer.counter("serve/kv_bytes", cat="mem",
                           projected=projected, observed=observed)
        drifted = (max(projected, observed) > 0
                   and abs(projected - observed)
                   / max(projected, observed) > 0.10)
        if drifted and not self._kv_drifted:
            self.metrics.on_kv_drift()
            tracer.instant(
                "serve/kv_drift", cat="serve",
                projected_bytes=projected, observed_bytes=observed,
                drift_frac=round(abs(projected - observed)
                                 / max(projected, observed), 4))
        self._kv_drifted = drifted

    def _check_membership(self) -> bool:
        """Poll the membership view — the view throttles its own directory
        scans (``poll_lost``: half the lost_after window, same cadence the
        training runner uses), so this is cheap to call every serve tick.
        A lost peer means the next engine collective would wedge the tick
        forever: flip to sticky degraded (503) and fail in-flight requests
        NOW, while this thread can still run."""
        try:
            lost = self.membership.poll_lost()
        except Exception:
            logger.exception("serve loop: membership check failed")
            return True
        if not lost:                   # healthy, or throttled (None)
            return True
        reason = f"comm peer(s) lost: {lost}"
        logger.error(f"serve loop: {reason}; degrading replica instead of "
                     "stepping into a wedged collective")
        get_tracer().instant("serve/degraded", cat="serve",
                             reason="peer_lost", ranks=str(lost))
        with self._lock:
            self._degraded = reason
        self._fail_all(reason)
        return False

    def _admit_from_queue(self):
        """FIFO admission while the engine currently has room for the
        request's FULL worst case (prompt + max_new_tokens). Note
        ``can_schedule`` checks free blocks WITHOUT reserving — the actual
        no-mid-decode-exhaustion guarantee is submit()'s worst-case
        projection against the <=1.0 KV watermark."""
        while True:
            with self._lock:
                if not self._queue:
                    return
                req = self._queue[0]
            need = len(req.prompt_tokens) + req.max_new_tokens
            if not self.engine.can_schedule([req.uid], [need]):
                return
            with self._lock:
                self._queue.pop(0)
                self._inflight[req.uid] = req
            try:
                self.engine.admit(req.uid, req.prompt_tokens)
            except Exception as e:
                # fail THIS request, not the batch (e.g. prompt longer than
                # the engine's max context)
                with self._lock:
                    self._inflight.pop(req.uid, None)
                req.finalize(RequestState.FAILED, "error", error=repr(e))
                self.metrics.on_finish(req)
                continue
            req.admit_ts = time.monotonic()
            req.state = RequestState.PREFILL

    def _fan_out(self, step_out: Dict[int, int]):
        now = time.monotonic()
        n = 0
        for uid, tok in step_out.items():
            req = self._inflight.get(uid)
            if req is None or req.state.terminal:
                continue
            req.state = RequestState.DECODE
            req.push_token(int(tok), now=now)
            n += 1
            seq = self.engine.state.get(uid)
            if seq is not None and seq.done:
                req.finalize(RequestState.FINISHED, "eos")
            elif len(req.tokens) >= req.max_new_tokens:
                req.finalize(RequestState.FINISHED, "length")
                self.engine.finish(uid)
        if n:
            self.metrics.on_tokens(n)

    def _expire_and_cancel(self):
        now = time.monotonic()
        with self._lock:
            queued = list(self._queue)
            inflight = list(self._inflight.values())
        for req in queued:
            if req.cancelled_requested or req.expired:
                with self._lock:
                    if req in self._queue:
                        self._queue.remove(req)
                self._finalize_expired(req, now)
                # never reached the engine: settle metrics here (engine-
                # resident requests settle in _reap)
                self.metrics.on_finish(req)
        for req in inflight:
            if req.cancelled_requested or req.expired:
                self._finalize_expired(req, now)
                self.engine.finish(req.uid)

    def _finalize_expired(self, req: Request, now: float):
        if req.cancelled_requested:
            req.finalize(RequestState.CANCELLED, "cancelled")
        else:
            req.finalize(RequestState.TIMED_OUT, "timeout")

    def _reap(self):
        """Release engine state (KV blocks, sequence slots) for every done
        sequence and settle the owning requests."""
        reaped = self.engine.reap_finished()
        for uid in reaped:
            with self._lock:
                req = self._inflight.pop(uid, None)
            if req is None:
                continue
            if not req.state.terminal:
                # engine marked it done (eos) but no token crossed this step
                req.finalize(RequestState.FINISHED, "eos")
            self.metrics.on_finish(req)

    def _fail_all(self, why: str):
        with self._lock:
            victims = list(self._queue) + list(self._inflight.values())
            self._queue.clear()
            inflight = list(self._inflight)
            self._inflight.clear()
        for req in victims:
            req.finalize(RequestState.FAILED, "error", error=why)
            self.metrics.on_finish(req)
        for uid in inflight:
            try:
                self.engine.finish(uid)
            except Exception:
                pass
        try:
            self.engine.reap_finished()
        except Exception:
            logger.exception("serve loop: reap after failure also failed")
