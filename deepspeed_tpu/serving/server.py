"""Continuous-batching serve loop over ``InferenceEngineV2``.

Reference analog: DeepSpeed-MII's async pipeline — the missing layer the
SURVEY marks "serving layer (MII, external)" above the v2 ragged engine.
Architecture:

  submit() threads --> bounded admission queue --> serve loop (ONE thread)
                                                     |-- engine.admit / step
                                                     |-- KV tier rebalance
                                                     |   (demote/promote)
                                                     |-- degradation ladder
                                                     |-- token fan-out to
                                                     |   per-request streams
                                                     `-- deadline / cancel /
                                                         reap / metrics

The engine is single-threaded by construction (jit dispatch + host-side KV
bookkeeping), so ONLY the serve loop touches it; callers interact through
thread-safe ``Request`` objects. Admission control is two-tier: a bounded
queue (depth) plus a projected KV watermark — with the host KV offload
tier enabled, the projection spans BOTH tiers (device watermark + host
budget), so overload degrades to *slower* (requests wait demoted in host
RAM) before it degrades to *429*.

Serving under siege (this file + ``degradation.py`` + ``kv_tier.py``):

* the **degradation ladder** (healthy -> brownout -> shed -> degraded)
  turns overload into explicit, hysteresis-damped, trace-instrumented
  states — see ``degradation.py``;
* **request-level fault isolation**: engine-step exceptions are classified
  through ``comm.guard.classify_exception``; only FATAL classes latch the
  sticky degraded 503. Transient faults evict a suspect request (retried
  with its KV recomputed, quarantined past its retry budget) and health
  auto-recovers after N clean steps;
* the serve tick is chaos-drillable (``DSTPU_CHAOS_SERVE_*``) and every
  transition is an edge-triggered dstrace instant, so a whole overload
  episode reconstructs from the trace + deterministic counters alone
  (``bench_serve``).
"""

import dataclasses
import itertools
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from deepspeed_tpu.comm.guard import CommOutcome, classify_exception
from deepspeed_tpu.config import constants as C
from deepspeed_tpu.resilience.chaos import REPLICA_ID_ENV, monkey_from_env
from deepspeed_tpu.serving.degradation import (DegradationLadder,
                                               LadderConfig, ServeLevel)
from deepspeed_tpu.serving.kv_tier import (effective_usable_blocks,
                                           plan_demotions,
                                           plan_prefix_evictions,
                                           plan_promotions, tier_pressure)
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.request import Request, RequestState
from deepspeed_tpu.telemetry.tracer import get_tracer, request_tid
from deepspeed_tpu.utils.logging import logger


#: an un-trippable demote line for cache trims outside the offload tier
#: (module-level so the hot tick never calls float() itself)
_NO_DEMOTE_LINE = float("inf")

#: flight-recorder directory (set by the fleet launcher on every replica
#: worker): when present, a dying/shedding replica atomically dumps its
#: trace ring + live per-request ledgers here (write-then-rename), so the
#: router can fold the dump into the stitched request timeline post-mortem
FLIGHT_DIR_ENV = "DSTPU_FLIGHT_DIR"

#: throttle between shed-triggered flight dumps: a shedding replica 429s
#: many requests per second and one black box per episode is the point
FLIGHT_SHED_INTERVAL_S = 5.0

#: the serving-tick stage clocks `dstpu plan --serve` attributes: the
#: server times admission/demote/promote/drain segments itself, the engine
#: reports prefill/decode from inside step() (``last_step_timing``), and
#: the remainder of the tick is residual
_TICK_STAGES = ("admission", "prefill", "decode", "demote", "promote",
                "drain")

#: stage -> retro-span name for the server-timed segments (prefill/decode
#: spans are emitted by the engine inside serve/engine_step)
_TICK_SPAN_NAMES = {"admission": "serve/admit", "demote": "serve/demote",
                    "promote": "serve/promote", "drain": "serve/drain"}


class BackpressureError(RuntimeError):
    """Admission rejected: queue full, projected KV occupancy over the
    watermark, or the degradation ladder is shedding. ``retry_after_s`` is
    the client backoff hint (HTTP 429 + Retry-After in the front-end)."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class ServerClosedError(RuntimeError):
    """Submission refused: the server is draining, stopped, or degraded."""


#: the ``serving.scheduler`` sub-group (a nested dict so partial user
#: configs merge over these and ``from_ds_config`` passes the group
#: through verbatim): decode-first chunked prefill + the prefill/decode
#: role split. Every default = today's semantics (cap off, one engine).
SCHEDULER_DEFAULTS = {
    # per-tick prefill-token cap: chunked prefill interleaves with decode
    # so TPOT never spikes behind a long prompt. 0 = uncapped (pre-cap
    # planning, bit-identical). Must cover >= 1 KV block when set.
    "prefill_chunk_tokens": 0,
    # prefill-role/decode-role engine pair in one process with
    # block-granular KV handoff (serving/disagg.py); consumed by the
    # server builder, not the tick
    "role_split": False,
    # page codec for the in-process KV handoff ("none" | "int8" | "fp8");
    # "none" = full-width, bit-identical adoption
    "handoff_quantize": "none",
}


class _EngineStepError(RuntimeError):
    """Internal: ``engine.step`` raised. Carries the original exception as
    ``__cause__`` so the fault handler can classify it (fatal -> sticky
    degraded; transient -> evict a suspect request and keep serving)."""


@dataclass
class ServingConfig:
    max_queue_depth: int = 64            # bounded admission queue
    kv_high_watermark: float = 0.95      # projected KV-occupancy reject line
    default_max_new_tokens: int = 64
    default_timeout_s: Optional[float] = None   # per-request deadline
    retry_after_s: float = 1.0           # backoff hint on rejection
    idle_poll_s: float = 0.002           # loop sleep when no work
    monitor_export_every: int = 0        # engine steps between monitor
    # exports; 0 disables the fan-out even when a monitor is attached

    # --- degradation ladder (degradation.py) ---
    brownout_pressure: float = 0.85      # pressure >= this -> BROWNOUT
    shed_pressure: float = 0.97          # pressure >= this -> SHED (429s)
    ladder_hysteresis: float = 0.10      # descend below threshold - this
    ladder_cooldown_ticks: int = 20      # calm ticks before descending
    brownout_max_new_tokens: int = 16    # admission-time cap in brownout

    # --- host KV offload tier (kv_tier.py; default OFF = the pre-tier
    # admission semantics, same opt-in discipline as async_pipeline) ---
    kv_offload_enabled: bool = False
    host_kv_budget_bytes: int = 256 << 20   # host-RAM demotion budget
    kv_demote_watermark: float = 0.90       # demote above this device frac
    kv_demote_watermark_brownout: float = 0.60   # aggressive in brownout
    min_active_requests: int = 1            # never demote below this
    # host-tier page codec ("none" | "int8" | "fp8"): demoted pages are
    # stored narrow with per-page fp32 scales — ~2x (bf16->fp8) to ~4x
    # (fp32->int8) more effective blocks under the same host budget;
    # promotion dequantizes back to device width (tolerance-bounded).
    # Device-fp8 pages are never re-quantized (bit-identical round-trip
    # preserved)
    host_kv_quantize: str = "none"

    # --- radix prefix cache over KV pages (inference/v2/prefix_cache.py;
    # default OFF = every prompt prefills from scratch) ---
    prefix_cache_enabled: bool = False
    # soft cap on UNPINNED cached blocks trimmed every tick (0 = only
    # pressure evicts); pinned shared pages are never evicted
    prefix_cache_max_blocks: int = 0

    # --- request-level fault isolation ---
    poison_retry_budget: int = 1         # evict+retry this many times,
    # then quarantine (FAILED, reason "quarantined")
    recover_clean_steps: int = 8         # clean engine steps to declare a
    # fault episode over (serve/recovered instant + counter)
    max_consecutive_step_faults: int = 8  # latch degraded past this many
    # engine-step faults with no clean step in between

    # --- async serve scheduler (SCHEDULER_DEFAULTS above): decode-first
    # chunked prefill + the prefill/decode role split; a partial dict
    # merges over the defaults in __post_init__ ---
    scheduler: dict = dataclasses.field(
        default_factory=lambda: dict(SCHEDULER_DEFAULTS))

    def __post_init__(self):
        merged = dict(SCHEDULER_DEFAULTS)
        unknown = sorted(set(self.scheduler or {}) - set(merged))
        if unknown:
            raise ValueError(
                f"unknown 'serving.scheduler' keys: {unknown}; "
                f"known: {sorted(merged)}")
        merged.update(self.scheduler or {})
        self.scheduler = merged
        if int(merged["prefill_chunk_tokens"]) < 0:
            raise ValueError(
                f"serving.scheduler.prefill_chunk_tokens must be >= 0, "
                f"got {merged['prefill_chunk_tokens']}")
        from deepspeed_tpu.inference.v2.kv_offload import KV_CODECS
        if merged["handoff_quantize"] not in KV_CODECS:
            raise ValueError(
                f"serving.scheduler.handoff_quantize must be one of "
                f"{KV_CODECS}, got {merged['handoff_quantize']!r}")

    @classmethod
    def from_ds_config(cls, ds_config: dict) -> "ServingConfig":
        """Build from a DeepSpeed-style config dict's ``"serving"`` group
        (key constant ``config.constants.SERVING``; unknown keys are an
        error — config drift must not fail silently)."""
        group = dict(ds_config.get(C.SERVING, {}) or {})
        names = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(group) - names)
        if unknown:
            raise ValueError(
                f"unknown '{C.SERVING}' config keys: {unknown}; "
                f"known: {sorted(names)}")
        return cls(**group)


class InferenceServer:
    """Drives one ``InferenceEngineV2`` from a background thread with
    continuous batching, streaming fan-out, tiered admission control, a
    degradation ladder, request-level fault isolation, and graceful drain
    (the shutdown AND elastic-resize hook: drain, resize or recreate the
    engine, start a fresh server)."""

    def __init__(self, engine, config: Optional[ServingConfig] = None,
                 monitor=None, membership=None, chaos=None):
        self.engine = engine
        self.config = config or ServingConfig()
        # optional resilience.membership.MembershipView: a wedged/lost peer
        # flips this replica to degraded (503) BEFORE the serve tick walks
        # into a collective that would hang it forever
        self.membership = membership
        # deterministic fault injection for the serve tick (chaos drills);
        # picked up from DSTPU_CHAOS_SERVE_* env when not passed explicitly
        self.chaos = chaos if chaos is not None else monkey_from_env()
        if not 0.0 < self.config.kv_high_watermark <= 1.0:
            # the watermark IS the no-mid-decode-exhaustion invariant: the
            # sum of accepted requests' worst-case blocks never exceeds
            # watermark * usable blocks, so lazy per-step reservation can't
            # run dry; above 1.0 that guarantee is gone (with the offload
            # tier enabled, the tier policy re-establishes it dynamically)
            raise ValueError(
                f"kv_high_watermark must be in (0, 1], got "
                f"{self.config.kv_high_watermark}")
        self.metrics = ServingMetrics()
        self.monitor = monitor
        self.ladder = DegradationLadder(LadderConfig(
            brownout_pressure=self.config.brownout_pressure,
            shed_pressure=self.config.shed_pressure,
            hysteresis=self.config.ladder_hysteresis,
            cooldown_ticks=self.config.ladder_cooldown_ticks))
        self._uid = itertools.count(1)
        self._lock = threading.Lock()          # queue + tables, never engine
        self._queue: List[Request] = []        # accepted, not yet in engine
        self._inflight: Dict[int, Request] = {}  # uid -> engine-resident
        self._demoted: List[int] = []          # uids in the host tier (FIFO)
        self._draining = False
        self._stopped = False
        self._degraded: Optional[str] = None   # sticky engine-failure reason
        self._kv_drifted = False   # edge detector for the kv_drift instant
        self._kv_watermark_scale = 1.0   # drift-recalibrated multiplier
        self._wake = threading.Event()         # submit() nudges the loop
        self._thread: Optional[threading.Thread] = None
        # the offload tier needs the engine-side hooks (real engines have
        # them; minimal doubles in tests may not)
        self._tier_capable = (self.config.kv_offload_enabled
                              and hasattr(engine, "demote_kv"))
        from deepspeed_tpu.inference.v2.kv_offload import KV_CODECS
        if self.config.host_kv_quantize not in KV_CODECS:
            raise ValueError(
                f"host_kv_quantize must be one of {KV_CODECS}, got "
                f"{self.config.host_kv_quantize!r}")
        # radix prefix cache: the serving knob flips it on at the engine
        # (where admission lives); minimal test doubles without the hook
        # simply run uncached
        if self.config.prefix_cache_enabled and \
                hasattr(engine, "enable_prefix_cache"):
            engine.enable_prefix_cache(self.config.prefix_cache_max_blocks)
        self._prefix_capable = (self.config.prefix_cache_enabled
                                and getattr(engine, "prefix_cache", None)
                                is not None)
        # decode-first chunked prefill: wire the scheduler sub-group's cap
        # into the engine's SplitFuse planner (minimal test doubles without
        # the hook simply run uncapped); cap 0 touches nothing, so the
        # default config leaves planning bit-identical
        cap = int(self.config.scheduler.get("prefill_chunk_tokens", 0) or 0)
        if cap > 0 and hasattr(engine, "configure_chunked_prefill"):
            engine.configure_chunked_prefill(cap)
        self._block_bytes_cache: Optional[int] = None
        # serving-tick stage clocks (serve-loop-private): cumulative busy
        # seconds per stage + cumulative tick seconds, feeding the
        # serve/tick_stage_share counter track (/metrics + dstrace)
        self._tick_stage_cum = {s: 0.0 for s in _TICK_STAGES}
        self._tick_cum_s = 0.0
        # fleet identity: set by the fleet launcher on replica workers
        # (-1 standalone); reported on /healthz so the router can key
        # affinity/retirement by replica, and matched by the chaos
        # replica-kill knob
        try:
            self.replica_id = int(os.environ.get(REPLICA_ID_ENV, "-1")
                                  or "-1")
        except ValueError:
            self.replica_id = -1
        # predecessor prefix-handoff files queued for adoption; imported
        # by the serve loop between ticks (the thread that owns the engine)
        self._handoff_paths: List[str] = []
        self.handoff_stats = {"imported_chains": 0, "imported_blocks": 0,
                              "skipped_chains": 0}
        # fault-isolation state (serve-loop-private except the flag)
        self._tick = 0
        self._consecutive_faults = 0
        self._clean_steps = 0
        self._fault_episode = False            # read by health() under lock
        self._admitted_since_clean: List[int] = []
        # flight recorder: last dump's monotonic stamp (shed throttle)
        self._last_flight_dump: Optional[float] = None
        if self.chaos is not None:
            # SIGKILL is uncatchable, so the black box cannot be a signal
            # handler: the chaos monkey exposes a pre-kill hook and the
            # flight dump runs SYNCHRONOUSLY before os.kill fires
            self.chaos.on_replica_kill = self._flight_on_kill

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "InferenceServer":
        if self._thread is not None:
            raise RuntimeError("server already started")
        self._thread = threading.Thread(target=self._serve_loop,
                                        name="dstpu-serve", daemon=True)
        self._thread.start()
        return self

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop accepting new requests; keep stepping until every accepted
        request reaches a terminal state. Returns True when fully drained
        (False on timeout, with requests still in flight)."""
        with self._lock:
            self._draining = True
        self._wake.set()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                live = len(self._queue) + len(self._inflight)
            if live == 0:
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(self.config.idle_poll_s)

    def stop(self, drain_timeout: Optional[float] = 30.0):
        """Graceful shutdown: drain, then stop the loop. Requests still
        live after the drain timeout are force-cancelled."""
        if self._thread is None or not self._thread.is_alive():
            # no serve loop to honor cancellations: settle accepted
            # requests directly instead of polling a drain that can't
            # progress (callers blocked in result() would hang forever)
            with self._lock:
                self._draining = True
            self._fail_all("server stopped before the serve loop ran")
            with self._lock:
                self._stopped = True
            return
        drained = self.drain(timeout=drain_timeout)
        if not drained:
            with self._lock:
                leftovers = list(self._queue) + list(self._inflight.values())
            for req in leftovers:
                req.cancel()
            self.drain(timeout=5.0)
        with self._lock:
            self._stopped = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    # ------------------------------------------------------------------
    # fleet prefix handoff (retirement export / successor adoption)
    # ------------------------------------------------------------------
    def adopt_prefix_handoff(self, path: str) -> None:
        """Queue a predecessor's prefix-handoff file for adoption. The
        serve loop — the only thread that owns the engine — imports it
        between ticks, so this is safe to call from the frontend's admin
        route while requests are in flight. With no serve loop running
        (worker startup), the import runs inline."""
        if not hasattr(self.engine, "import_prefix_handoff"):
            raise ValueError("engine has no prefix-handoff support")
        if not self.running:
            self._import_handoff(path)
            return
        with self._lock:
            self._handoff_paths.append(path)
        self._wake.set()

    def _adopt_handoffs(self) -> None:
        with self._lock:
            paths, self._handoff_paths = self._handoff_paths, []
        for p in paths:
            self._import_handoff(p)

    def _import_handoff(self, path: str) -> None:
        try:
            got = self.engine.import_prefix_handoff(path)
        except Exception:
            logger.exception(f"serve: prefix handoff import failed ({path})")
            return
        self.handoff_stats["imported_chains"] += got.get("chains", 0)
        self.handoff_stats["imported_blocks"] += got.get("blocks", 0)
        self.handoff_stats["skipped_chains"] += got.get("skipped", 0)
        get_tracer().instant("serve/prefix_handoff_adopt", cat="serve",
                             **{k: int(v) for k, v in got.items()})
        logger.info(f"serve: adopted prefix handoff {path}: {got}")

    def export_prefix_handoff(self, path: str,
                              quantize: Optional[str] = None) -> dict:
        """Drain-time export of the warm prefix cache for a successor
        (retirement: drain -> stop -> export -> successor adopts). Must
        run with the serve loop stopped — the export gathers device pages
        and may not race the tick."""
        if self.running:
            raise RuntimeError(
                "export_prefix_handoff requires a stopped server "
                "(drain + stop first)")
        if not hasattr(self.engine, "export_prefix_handoff"):
            return {"chains": 0, "blocks": 0}
        q = quantize if quantize is not None else self.config.host_kv_quantize
        got = self.engine.export_prefix_handoff(path, quantize=q)
        get_tracer().instant("serve/prefix_handoff_export", cat="serve",
                             **{k: int(v) for k, v in got.items()})
        logger.info(f"serve: exported prefix handoff {path}: {got}")
        return got

    @property
    def running(self) -> bool:
        return (self._thread is not None and self._thread.is_alive()
                and not self._stopped)

    @property
    def draining(self) -> bool:
        return self._draining

    def health(self) -> dict:
        with self._lock:
            queued, inflight = len(self._queue), len(self._inflight)
            demoted = len(self._demoted)
            degraded = self._degraded
            fault_episode = self._fault_episode
        state = ("stopped" if self._stopped else
                 # a FATAL engine-step failure means the KV/sequence state
                 # is suspect: report unhealthy (503 at /healthz) so load
                 # balancers stop routing here — sticky until the engine is
                 # replaced (drain + recreate), not self-clearing.
                 # Transient step faults do NOT land here (they run the
                 # evict/retry/quarantine path and auto-recover).
                 "degraded" if degraded else
                 "draining" if self._draining else
                 "serving" if self.running else "not_started")
        level = self.ladder.level
        out = {"status": state, "ok": state == "serving",
               "level": level.name.lower(),
               "level_reason": self.ladder.reason,
               "queued": queued, "inflight": inflight,
               "demoted": demoted,
               "fault_episode": fault_episode,
               "step_faults": self.metrics.engine_step_faults,
               "kv_occupancy": self.engine.kv_occupancy(),
               # the fleet router's affinity + retirement signals
               "replica_id": self.replica_id,
               "draining": self._draining,
               "prefix_cache_blocks": (
                   self.engine.prefix_cache.cached_blocks()
                   if getattr(self.engine, "prefix_cache", None) is not None
                   else 0)}
        if degraded:
            out["degraded_reason"] = degraded
        if self._tier_capable:
            out["host_kv_bytes"] = self.engine.host_kv_bytes()
        if self.membership is not None:
            out["membership"] = self.membership.summary()
        return out

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------
    def _blocks_for(self, req: Request) -> int:
        # worst case AT COMPLETION: prompt + full budget. Invariant under
        # eviction/re-admission (generated tokens move from budget to
        # prompt, the total is unchanged)
        return self.engine.kv.blocks_needed(
            len(req.prompt_tokens) + req.max_new_tokens)

    def _block_bytes(self) -> int:
        if self._block_bytes_cache is None:
            fn = getattr(self.engine, "kv_block_bytes", None)
            self._block_bytes_cache = fn() if fn is not None else 0
        return self._block_bytes_cache

    def _host_budget_blocks(self) -> int:
        """The host tier's capacity expressed in device-block equivalents
        — what admission projects against beyond the device watermark."""
        if not self._tier_capable:
            return 0
        bb = self._block_bytes()
        if bb <= 0:
            return 0
        return self.config.host_kv_budget_bytes // bb

    def submit(self, prompt_tokens: Sequence[int],
               max_new_tokens: Optional[int] = None,
               timeout_s: Optional[float] = None,
               priority: int = 0,
               trace_id: Optional[str] = None) -> Request:
        """Accept a request (thread-safe) or reject synchronously.
        Raises ``ServerClosedError`` when draining/stopped/degraded and
        ``BackpressureError`` when the ladder sheds, the queue is full, or
        the projected KV occupancy (both tiers) is over its limit.
        ``priority < 0`` marks low-priority work whose engine admission is
        paused during brownout. ``trace_id`` (the router's X-Dstpu-Trace
        value) makes the request's lifecycle spans stitchable fleet-wide
        (``req/`` twins carrying the id)."""
        cfg = self.config
        if max_new_tokens is None:
            max_new_tokens = cfg.default_max_new_tokens
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new_tokens}")
        if not isinstance(priority, int) or isinstance(priority, bool):
            # the serve loop compares priorities every admission scan — a
            # stringly-typed priority must be a 400 at the door, not a
            # TypeError on the loop thread
            raise ValueError(f"priority must be an int, got {priority!r}")
        level = self.ladder.level
        if level >= ServeLevel.BROWNOUT and level < ServeLevel.DEGRADED:
            # degrade-to-slower: cap the generation budget at the door (the
            # request still gets tokens, just fewer — 200, not 429)
            max_new_tokens = min(max_new_tokens, cfg.brownout_max_new_tokens)
        req = Request(uid=next(self._uid), prompt_tokens=prompt_tokens,
                      max_new_tokens=max_new_tokens,
                      timeout_s=(timeout_s if timeout_s is not None
                                 else cfg.default_timeout_s),
                      priority=priority)
        # the ladder level this request was accepted under rides on its
        # lifecycle retro-spans, so `dstpu plan --serve` can report
        # TTFT/TPOT per ladder level (healthy vs brownout tails)
        req.ladder_level = level.name.lower()
        if trace_id is not None:
            req.trace_id = str(trace_id)
        if not req.prompt_tokens:
            raise ValueError("empty prompt")
        max_ctx = self.engine.state.max_context_length
        if len(req.prompt_tokens) + req.max_new_tokens > max_ctx:
            # past max_seq_len the decode would silently clamp positions
            # (garbage RoPE rotations), so reject at the door
            raise ValueError(
                f"prompt ({len(req.prompt_tokens)}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds max context {max_ctx}")
        with self._lock:
            if self._draining or self._stopped:
                raise ServerClosedError("server is draining; not accepting "
                                        "new requests")
            if self._degraded:
                # new work on a suspect engine would fail anyway — refuse at
                # the door (503) until the replica is drained and replaced
                raise ServerClosedError(
                    f"server degraded ({self._degraded}); not accepting "
                    "new requests")
            if level is ServeLevel.SHED:
                # the ladder's explicit overload rung: reject with a
                # backoff hint (429 + Retry-After) BEFORE burning queue/
                # projection arithmetic on a request we can't take
                self.metrics.on_shed()
                get_tracer().instant("serve/backpressure", cat="serve",
                                     kind="shed")
                # shed-to-429 is a flight-recorder trigger: the black box
                # explains WHY clients got 429s (throttled — one dump per
                # episode, not one per refused request)
                self.flight_dump("shed",
                                 min_interval_s=FLIGHT_SHED_INTERVAL_S,
                                 _locked=True)
                raise BackpressureError(
                    f"shedding load (pressure "
                    f"{self.ladder.last_pressure:.2f}); retry after "
                    f"{cfg.retry_after_s:.1f}s", cfg.retry_after_s)
            if len(self._queue) >= cfg.max_queue_depth:
                self.metrics.on_reject()
                get_tracer().instant("serve/backpressure", cat="serve",
                                     kind="queue_full")
                raise BackpressureError(
                    f"admission queue full ({cfg.max_queue_depth}); retry "
                    f"after {cfg.retry_after_s:.1f}s", cfg.retry_after_s)
            # projected occupancy at completion: worst-case blocks of every
            # accepted request (queued AND in flight — an admitted request
            # keeps reserving blocks as it decodes) + this one, admitted
            # against BOTH tiers: the (drift-recalibrated) device watermark
            # plus the host tier's budget in block equivalents
            total_blocks = max(self.engine.kv_usable_blocks(), 1)
            projected = (sum(self._blocks_for(r) for r in self._queue)
                         + sum(self._blocks_for(r)
                               for r in self._inflight.values())
                         + self._blocks_for(req))
            watermark = cfg.kv_high_watermark * self._kv_watermark_scale
            capacity = watermark * total_blocks + self._host_budget_blocks()
            if projected > capacity:
                self.metrics.on_reject()
                get_tracer().instant("serve/backpressure", cat="serve",
                                     kind="kv_watermark")
                raise BackpressureError(
                    f"projected KV occupancy {projected} blocks over "
                    f"two-tier capacity {capacity:.0f} (watermark "
                    f"{watermark:.2f}); retry after "
                    f"{cfg.retry_after_s:.1f}s", cfg.retry_after_s)
            self._queue.append(req)
        self.metrics.on_submit()
        self._wake.set()
        return req

    def cancel(self, uid: int) -> bool:
        """Request cancellation by uid; True if the request was found live."""
        with self._lock:
            for r in self._queue:
                if r.uid == uid:
                    r.cancel()
                    return True
            req = self._inflight.get(uid)
        if req is not None:
            req.cancel()
            return True
        return False

    # ------------------------------------------------------------------
    # the serve loop (single thread; sole owner of the engine)
    # ------------------------------------------------------------------
    def _serve_loop(self):
        while True:
            if self._stopped:
                return
            try:
                worked = self._serve_once()
            except _EngineStepError as e:
                self._on_step_fault(e)
                worked = False
            except Exception:
                # non-engine bookkeeping glitch: requests are still healthy,
                # log and keep serving
                logger.exception("serve loop: non-fatal tick error")
                worked = False
            if not worked:
                # nothing to do: block until a submit() nudge (bounded so
                # deadline expiry of QUEUED requests is still noticed)
                self._wake.wait(timeout=self.config.idle_poll_s * 10)
                self._wake.clear()

    def _serve_once(self) -> bool:
        t_tick0 = time.monotonic()
        self._tick += 1
        marks: List[tuple] = []     # the tick's stage timeline (see _mark)
        if self.chaos is not None:
            self.chaos.serve_slow_tick(self._tick)
            # fleet drill: SIGKILL this replica mid-decode when it is the
            # configured victim (has_work == live streams to fail over)
            self.chaos.maybe_kill_replica(self._tick, self.engine.has_work())
        if self._handoff_paths:
            # rare (successor adoption at retirement); one attr check per
            # tick otherwise
            self._adopt_handoffs()
        if self.membership is not None and self._degraded is None:
            if not self._check_membership():
                return False
        t0 = time.monotonic()
        self._expire_and_cancel()
        self._mark(marks, "drain", t0)
        stolen_frac = (self.chaos.serve_kv_pressure(self._tick)
                       if self.chaos is not None else 0.0)
        moved = 0
        if self._tier_capable:
            moved += self._rebalance_kv_tiers(stolen_frac, marks)
        elif self._prefix_capable:
            # no offload tier: the cache still honors its soft cap (the
            # demote line doesn't exist, so pass an un-trippable one)
            self._trim_prefix_cache(self.engine.kv_reserved_blocks(),
                                    _NO_DEMOTE_LINE)
        t0 = time.monotonic()
        moved += self._admit_from_queue(stolen_frac)
        self._mark(marks, "admission", t0)
        worked = False
        if self.engine.has_work():
            try:
                if self.chaos is not None:
                    self.chaos.maybe_poison_serve(self._active_uids())
                with get_tracer().span("serve/engine_step", cat="serve",
                                       tick=self._tick):
                    out = self.engine.step()
            except Exception as e:
                raise _EngineStepError(str(e)) from e
            self.metrics.on_step()
            # role-split engines time each prefill->decode KV handoff;
            # drain those stamps into the SLO histogram every tick (plain
            # float handover — no host sync, nothing when absent). Traced
            # requests also get a req/handoff span here: the engine knows
            # the uid, only the server knows the trace id.
            pop_handoff = getattr(self.engine, "pop_handoff_latencies", None)
            if pop_handoff is not None:
                for uid, lat_s in pop_handoff():
                    self.metrics.on_handoff_latency(lat_s)
                    with self._lock:
                        req = self._inflight.get(uid)
                    if req is not None and req.trace_id is not None:
                        get_tracer().complete(
                            "req/handoff", lat_s, cat="serve",
                            tid=request_tid(uid), trace_id=req.trace_id,
                            uid=uid)
            self._note_clean_step()
            worked = True
            t0 = time.monotonic()
            self._fan_out(out)
            self._mark(marks, "drain", t0)
        elif self._fault_episode:
            # an idle server is trivially clean: age the fault episode out
            # on empty ticks too, or a drained replica would advertise
            # "fault_episode" on /healthz forever (recovery must not
            # require traffic). The consecutive-fault streak is NOT reset
            # here — only a real clean step proves the engine healthy.
            with self._lock:
                idle = not self._queue and not self._inflight
            if idle:
                self._clean_steps += 1
                self._maybe_recover()
        t0 = time.monotonic()
        self._reap()
        self._mark(marks, "drain", t0)
        with self._lock:
            queued, inflight = len(self._queue), len(self._inflight)
            # the admission model's worst-case projection, re-derived at
            # tick time over everything still live (same sum submit()
            # admits against)
            projected_blocks = (sum(self._blocks_for(r) for r in self._queue)
                                + sum(self._blocks_for(r)
                                      for r in self._inflight.values()))
        self._reconcile_kv(projected_blocks)
        self._prefix_gauges()
        self._observe_ladder(queued, stolen_frac)
        self.metrics.set_gauges(queue_depth=queued, inflight=inflight,
                                kv_occupancy=self.engine.kv_occupancy())
        every = self.config.monitor_export_every
        if every and self.metrics.engine_steps % every == 0:
            try:
                self.metrics.export(self.monitor, self.metrics.engine_steps)
            except Exception:
                logger.exception("serve loop: monitor export failed")
        if worked or moved:
            # only ticks that did something land in the ring: an idle
            # server polling its queue must not flood the bounded trace
            self._emit_tick_spans(marks, t_tick0, worked, queued, inflight)
        return worked

    def _mark(self, marks: list, stage: str, t0: float, **args) -> None:
        """Record one tick-timeline segment ``(stage, t0, now, args)`` —
        pure host bookkeeping; the retro-spans are emitted in one batch by
        ``_emit_tick_spans`` at tick end (and only for working ticks)."""
        marks.append((stage, t0, time.monotonic(), args or None))

    def _emit_tick_spans(self, marks: list, t_tick0: float, worked: bool,
                         queued: int, inflight: int) -> None:
        """Emit the tick's stage timeline as dstrace retro-spans plus the
        ``serve/tick`` window span (the unit ``dstpu plan --serve``
        attributes: the stage ledger provably sums to this window), then
        fold the durations into the cumulative stage clocks."""
        stage_s = {s: 0.0 for s in _TICK_STAGES}
        timing = getattr(self.engine, "last_step_timing", None)
        if worked and timing:
            # the engine timed (and trace-spanned) its own step interior
            stage_s["prefill"] = timing.get("prefill_s", 0.0)
            stage_s["decode"] = timing.get("decode_s", 0.0)
        for stage, t0, t1, _args in marks:
            stage_s[stage] += t1 - t0
        t_end = time.monotonic()
        tracer = get_tracer()
        if tracer.enabled:
            for stage, t0, t1, args in marks:
                tracer.complete(_TICK_SPAN_NAMES[stage], t1 - t0,
                                cat="serve", end_ts=t1, tick=self._tick,
                                **(args or {}))
            tracer.complete("serve/tick", t_end - t_tick0, cat="serve",
                            end_ts=t_end, tick=self._tick, worked=worked,
                            queued=queued, inflight=inflight)
        self._tick_stage_gauges(stage_s, t_end - t_tick0, tracer)

    def _tick_stage_gauges(self, stage_s: dict, tick_s: float,
                           tracer) -> None:
        """Fold one tick's stage durations into the cumulative clocks and
        publish the tick-stage share gauges as ONE counter track
        (``serve/tick_stage_share``) — /metrics exposes it under the
        single ``dstpu_trace_counter`` TYPE block, Perfetto renders it as
        a stacked share series alongside the serve spans."""
        cum = self._tick_stage_cum
        for stage, dt in stage_s.items():
            cum[stage] += dt
        self._tick_cum_s += tick_s
        total = self._tick_cum_s
        if not tracer.enabled or total <= 0:
            return
        shares = {}
        attributed = 0.0
        for stage in _TICK_STAGES:
            attributed += cum[stage]
            shares[stage] = round(cum[stage] / total, 4)
        shares["residual"] = round(max(1.0 - attributed / total, 0.0), 4)
        tracer.counter("serve/tick_stage_share", cat="serve", **shares)

    def _active_uids(self) -> List[int]:
        """Engine-resident uids the next step will actually plan (demoted
        ones are paused)."""
        with self._lock:
            dem = set(self._demoted)
            return [u for u in self._inflight if u not in dem]

    # ------------------------------------------------------------------
    # host KV offload tier (policy in kv_tier.py; movement in the engine)
    # ------------------------------------------------------------------
    def _rebalance_kv_tiers(self, stolen_frac: float,
                            marks: Optional[list] = None) -> int:
        """Watermark-driven demotion (LIFO over admit order) and
        promotion-on-schedule (FIFO over demotion order). Bookkeeping is
        pure host arithmetic (DS002-registered); the page copies happen
        inside the engine demote/promote calls this decides to issue —
        each timed onto the tick timeline (``marks``) so the serve plan
        can attribute demote/promote churn. Returns pages moved (demotions
        + promotions)."""
        cfg = self.config
        usable = max(self.engine.kv_usable_blocks(), 1)
        effective = effective_usable_blocks(usable, stolen_frac)
        watermark = cfg.kv_high_watermark * self._kv_watermark_scale
        capacity = watermark * effective
        demote_wm = (cfg.kv_demote_watermark_brownout
                     if self.ladder.level >= ServeLevel.BROWNOUT
                     else cfg.kv_demote_watermark)
        with self._lock:
            dem = set(self._demoted)
            snapshot = list(self._inflight.items())
        # demotion candidates: engine-resident, not already demoted, and
        # not done (a done sequence is reaped this tick — gathering its
        # pages would be a wasted copy that skews the demotion counters)
        active = []
        for u, r in snapshot:
            if u in dem:
                continue
            seq = self.engine.state.get(u)
            if seq is None or seq.done:
                continue
            active.append(r)
        worst = [self._blocks_for(r) for r in active]
        held = [self.engine.kv_held_blocks(r.uid) for r in active]
        reserved = self.engine.kv_reserved_blocks()
        # ---- prefix-cache eviction FIRST (the demotion-ordering
        # contract): unpinned cached blocks are capacity nobody reads —
        # reclaiming them costs no copies and pauses no request, so they
        # go before any sequence is demoted. Pinned shared prefixes are
        # untouchable here and therefore outlive every unshared page
        if self._prefix_capable and \
                self._trim_prefix_cache(reserved, demote_wm * effective):
            reserved = self.engine.kv_reserved_blocks()
        # ---- demotion (most recently admitted first), bounded by the
        # host budget: once the host tier is full, demotion stops and the
        # pressure has to SURFACE (ladder -> brownout/shed) instead of
        # silently overflowing host RAM
        plan = plan_demotions(worst, held, reserved, capacity,
                              demote_wm * effective,
                              cfg.min_active_requests)
        bb = self._block_bytes()
        demoted_now = 0
        promoted_now = 0
        executed = set()
        for i in plan:
            victim = active[i]
            if (self.engine.host_kv_bytes()
                    + self.engine.kv_held_blocks(victim.uid) * bb
                    > cfg.host_kv_budget_bytes):
                break
            t0 = time.monotonic()
            freed = self.engine.demote_kv(
                victim.uid, quantize=cfg.host_kv_quantize)
            if marks is not None:
                self._mark(marks, "demote", t0, uid=victim.uid, bytes=freed)
            with self._lock:
                self._demoted.append(victim.uid)
            executed.add(i)
            demoted_now += 1
            self.metrics.on_demote(freed)
            get_tracer().instant("serve/kv_demote", cat="serve",
                                 uid=victim.uid, bytes=freed,
                                 stolen_frac=round(stolen_frac, 3))
        active_worst_sum = 0
        for i, w in enumerate(worst):
            if i not in executed:
                active_worst_sum += w
        # ---- promotion (longest-demoted first; done sequences are
        # reaped this tick — restoring their pages would be a wasted
        # host->device copy that skews the promotion counters) ----
        with self._lock:
            demoted_pairs = [(u, self._inflight[u]) for u in self._demoted
                             if u in self._inflight]
        demoted_reqs = []
        for u, req in demoted_pairs:
            seq = self.engine.state.get(u)
            if seq is None or seq.done:
                continue
            demoted_reqs.append(req)
        if demoted_reqs:
            d_worst = [self._blocks_for(r) for r in demoted_reqs]
            d_held = [self.engine.demoted_blocks(r.uid)
                      for r in demoted_reqs]
            n_promote = plan_promotions(d_worst, d_held, active_worst_sum,
                                        capacity, self.engine.kv.free_blocks,
                                        self.engine.kv_reserved_blocks(),
                                        demote_wm * effective)
            for r in demoted_reqs[:n_promote]:
                t0 = time.monotonic()
                restored = self.engine.promote_kv(r.uid)
                if restored is None:
                    break
                if marks is not None:
                    self._mark(marks, "promote", t0, uid=r.uid,
                               bytes=restored)
                promoted_now += 1
                with self._lock:
                    if r.uid in self._demoted:
                        self._demoted.remove(r.uid)
                self.metrics.on_promote(restored)
                get_tracer().instant("serve/kv_promote", cat="serve",
                                     uid=r.uid, bytes=restored)
        if demoted_now or demoted_reqs:
            tracer = get_tracer()
            if tracer.enabled:
                # the dsmem counter-track idiom: tier state as a stacked
                # Perfetto counter time-aligned with the serve spans
                tracer.counter(
                    "serve/kv_tier", cat="mem",
                    device_reserved_blocks=self.engine.kv_reserved_blocks(),
                    host_bytes=self.engine.host_kv_bytes(),
                    demoted_requests=len(self._demoted))
        return demoted_now + promoted_now

    # ------------------------------------------------------------------
    # radix prefix cache (trie in inference/v2/prefix_cache.py; policy
    # planner in kv_tier.plan_prefix_evictions)
    # ------------------------------------------------------------------
    def _trim_prefix_cache(self, reserved: int, demote_line: float) -> int:
        """Reclaim unpinned cached prefix blocks per the pure planner:
        down to the demote line under pressure, down to the soft cap
        always. Returns blocks freed. The planner is host-int
        arithmetic; the engine call it decides to issue releases blocks
        (a deliberate off-path device op, same contract as demote)."""
        if not self._prefix_capable:
            return 0
        cache = self.engine.prefix_cache
        want = plan_prefix_evictions(cache.evictable_blocks(),
                                     cache.over_cap_blocks(),
                                     reserved, demote_line)
        if want <= 0:
            return 0
        freed = self.engine.evict_prefix_blocks(want)
        if freed:
            self.metrics.on_prefix_evict(freed)
            get_tracer().instant("serve/prefix_evict", cat="serve",
                                 blocks=freed)
        return freed

    def _cache_evictable_blocks(self) -> int:
        """Unpinned cached blocks (reclaimable on demand) — subtracted
        from observed reservation wherever occupancy means 'blocks live
        requests are using': a warm-but-idle cache is capacity, and
        counting it as pressure would brownout an idle server, while
        counting it as observed sequence occupancy would fire spurious
        kv_drift edges and recalibrate admission down on every warm
        cache (pinned pages DO count — live readers are using them)."""
        if not self._prefix_capable:
            return 0
        return self.engine.prefix_cache.evictable_blocks()

    def _prefix_gauges(self) -> None:
        """Fold the engine's prefix/prefill counters into the serving
        metrics each tick (pure host reads — the counters are plain
        ints the engine already maintains) and emit the dsmem-idiom
        counter track so cache occupancy lines up with the serve spans
        on the trace timeline."""
        stats_fn = getattr(self.engine, "prefix_stats", None)
        if stats_fn is None:
            return
        stats = stats_fn()
        resident = self.engine.resident_tokens()
        resident_bytes = self.engine.kv_resident_bytes()
        host = getattr(self.engine, "host_kv", None)
        self.metrics.set_prefix_gauges(
            stats, resident_tokens=resident, resident_bytes=resident_bytes,
            host_compression=(host.compression_ratio()
                              if host is not None else 1.0))
        if self._prefix_capable:
            tracer = get_tracer()
            if tracer.enabled:
                tracer.counter(
                    "serve/prefix_cache", cat="mem",
                    cached_blocks=int(stats.get("prefix_cached_blocks", 0)),
                    pinned_blocks=int(stats.get("prefix_pinned_blocks", 0)),
                    hit_tokens=int(stats.get("prefix_hit_tokens", 0)))

    # ------------------------------------------------------------------
    # degradation ladder
    # ------------------------------------------------------------------
    def _observe_ladder(self, queued: int, stolen_frac: float) -> None:
        """One pressure observation per tick. Pure host arithmetic
        (DS002-registered); the ladder emits its own edge instants."""
        usable = max(self.engine.kv_usable_blocks(), 1)
        effective = effective_usable_blocks(usable, stolen_frac)
        reserved_fn = getattr(self.engine, "kv_reserved_blocks", None)
        if reserved_fn is not None:
            reserved = reserved_fn()
        else:
            reserved = int(self.engine.kv_occupancy() * usable)
        # a warm cache is reclaimable capacity, not pressure: without
        # this an idle server with an absorbed-history cache would sit
        # in brownout forever (evictable blocks free on demand)
        reserved = max(reserved - self._cache_evictable_blocks(), 0)
        host_bytes = (self.engine.host_kv_bytes()
                      if self._tier_capable else 0)
        pressure, reason = tier_pressure(
            reserved, effective, queued, self.config.max_queue_depth,
            host_bytes, self.config.host_kv_budget_bytes
            if self._tier_capable else 0)
        edge = self.ladder.observe(pressure, reason=reason)
        if edge is not None:
            self.metrics.on_ladder_transition(*edge)
        self.metrics.set_tier_gauges(int(self.ladder.level), host_bytes)

    def _latch_degraded(self, reason: str) -> None:
        """The sticky 503 — reserved for REAL engine faults (fatal
        classification, lost peers, repeated unattributable step faults)."""
        get_tracer().instant("serve/degraded", cat="serve", reason=reason)
        with self._lock:
            self._degraded = reason
        edge = self.ladder.latch_degraded(reason)
        if edge is not None:
            # the ->DEGRADED edge counts like every other ladder edge, so
            # metrics.ladder_transitions ties out against ladder.transitions
            self.metrics.on_ladder_transition(*edge)
        self.metrics.on_degraded_latch()
        # a latched replica leaves rotation for good: dump the black box
        # BEFORE _fail_all clears the ledgers it records
        self.flight_dump(f"degraded: {reason}")

    # ------------------------------------------------------------------
    # flight recorder (the serving black box)
    # ------------------------------------------------------------------
    def _flight_on_kill(self, tick: int) -> None:
        """Pre-SIGKILL hook the chaos monkey calls synchronously — the
        only moment this process can still explain itself."""
        self.flight_dump(f"chaos_replica_kill@tick{tick}")

    def flight_dump(self, reason: str, min_interval_s: float = 0.0,
                    _locked: bool = False) -> Optional[str]:
        """Atomically dump this replica's black box: the trace ring (a
        Chrome dump, so reqtrace/crossrank load it like any other ring)
        plus every live request's ledger under ``otherData.flight``.
        Write-then-rename into ``$DSTPU_FLIGHT_DIR`` (the PR 17
        status-artifact idiom) so the router only ever reads complete
        dumps. No-op without the env var; ``min_interval_s`` throttles
        repeat triggers (shed storms); ``_locked`` means the caller
        already holds ``self._lock`` (the shed branch). Returns the dump
        path, or None when disabled/throttled/failed."""
        dirpath = os.environ.get(FLIGHT_DIR_ENV)
        if not dirpath:
            return None
        now = time.monotonic()
        if (min_interval_s > 0.0 and self._last_flight_dump is not None
                and now - self._last_flight_dump < min_interval_s):
            return None
        self._last_flight_dump = now
        tracer = get_tracer()
        tracer.instant("serve/flight_dump", cat="serve", reason=reason,
                       replica=self.replica_id, tick=self._tick)
        if _locked:
            inflight = [r.describe() for r in self._inflight.values()]
            queued = [r.describe() for r in self._queue]
        else:
            with self._lock:
                inflight = [r.describe() for r in self._inflight.values()]
                queued = [r.describe() for r in self._queue]
        doc = tracer.to_chrome()
        doc.setdefault("otherData", {})["flight"] = {
            "reason": reason,
            "replica_id": self.replica_id,
            "pid": os.getpid(),
            "tick": self._tick,
            "inflight": inflight,
            "queued": queued,
        }
        path = os.path.join(
            dirpath, f"flight_replica{self.replica_id}_{os.getpid()}.json")
        tmp = f"{path}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError:
            logger.exception(f"serve: flight dump to {path} failed")
            return None
        logger.warning(f"serve: flight recorder dumped ({reason}) -> {path}")
        return path

    # ------------------------------------------------------------------
    # request-level fault isolation
    # ------------------------------------------------------------------
    def _note_clean_step(self) -> None:
        """A successful engine step: reset the fault window; after N clean
        steps a fault episode is declared over (health auto-recovery — the
        anti-sticky-503 half of the isolation story)."""
        self._consecutive_faults = 0
        if self._admitted_since_clean:
            self._admitted_since_clean.clear()
        if self._fault_episode:
            self._clean_steps += 1
            self._maybe_recover()

    def _maybe_recover(self) -> None:
        if self._clean_steps >= self.config.recover_clean_steps:
            with self._lock:
                self._fault_episode = False
            self._clean_steps = 0
            self.metrics.on_recovered()
            get_tracer().instant("serve/recovered", cat="serve",
                                 clean_steps=self.config.recover_clean_steps)

    def _on_step_fault(self, err: _EngineStepError) -> None:
        """Classify an engine-step exception through the PR 6 taxonomy:
        FATAL latches the sticky degraded 503 (the only thing that
        should); TRANSIENT/TIMEOUT evicts a suspect request — retried with
        its KV recomputed, quarantined past its retry budget — so one bad
        request cannot take the replica down."""
        cause = err.__cause__ if err.__cause__ is not None else err
        outcome = classify_exception(cause)
        self.metrics.on_step_fault()
        self._consecutive_faults += 1
        self._clean_steps = 0
        with self._lock:
            self._fault_episode = True
        get_tracer().instant("serve/step_fault", cat="serve",
                             outcome=outcome.value,
                             consecutive=self._consecutive_faults,
                             error=repr(cause)[:200])
        if outcome is CommOutcome.FATAL:
            # the KV cache / sequence state may be inconsistent after a
            # fatal step failure: every engine-resident request is
            # compromised and the replica must stop advertising itself
            logger.exception("serve loop: FATAL engine step failure; "
                             "failing in-flight requests")
            self._latch_degraded(f"engine step failed: {cause}")
            self._fail_all("engine step raised (fatal)")
            return
        logger.warning(f"serve loop: transient engine step fault "
                       f"#{self._consecutive_faults}: {cause!r}")
        # the fixed fault budget only applies once isolation has run out
        # of suspects: while every fault still evicts someone, the suspect
        # pool strictly shrinks (evicted retries are held from
        # re-admission during the fault window), so blame WILL reach the
        # poison even when it was admitted first among many — latching on
        # a raw count mid-search would 503 the replica over one bad
        # request with a deep batch. The 4x backstop still bounds
        # pathological churn absolutely.
        suspect = self._pick_suspect()
        if suspect is None or self._consecutive_faults >= \
                4 * max(self.config.max_consecutive_step_faults, 1):
            if self._consecutive_faults >= \
                    self.config.max_consecutive_step_faults:
                # nothing left to evict (or the backstop tripped) and the
                # engine still faults — the engine itself is sick
                self._latch_degraded(
                    f"{self._consecutive_faults} consecutive engine step "
                    f"faults, last: {cause}")
                self._fail_all("engine step raised repeatedly")
            return
        self._evict_for_retry(suspect, cause)

    def _pick_suspect(self) -> Optional[Request]:
        """The most recently admitted ACTIVE request that has never
        survived a clean step — the request whose arrival correlates with
        the engine starting to fault. Falls back to the most recent active
        admission. Demoted (paused) requests are never suspects: they are
        not in the step plan, so they cannot have caused the fault —
        blaming one would quarantine an innocent while the real poison
        keeps faulting."""
        with self._lock:
            dem = set(self._demoted)
            for uid in reversed(self._admitted_since_clean):
                req = self._inflight.get(uid)
                if (req is not None and uid not in dem
                        and not req.state.terminal):
                    return req
            for uid in reversed(list(self._inflight)):
                req = self._inflight[uid]
                if uid not in dem and not req.state.terminal:
                    return req
        return None

    def _evict_for_retry(self, req: Request, cause: BaseException) -> None:
        """Remove a suspect from the engine. Within its retry budget it
        goes back to the queue for retry (its stream continues — the
        already-sent tokens become prompt, KV recomputed at re-admission);
        past the budget it is quarantined (FAILED, never retried)."""
        with self._lock:
            self._inflight.pop(req.uid, None)
            if req.uid in self._admitted_since_clean:
                self._admitted_since_clean.remove(req.uid)
            if req.uid in self._demoted:
                self._demoted.remove(req.uid)
        try:
            self.engine.finish(req.uid)
            # the reap may flush OTHER sequences already marked done this
            # tick (cancel/timeout/eos) — settle them, or their requests
            # leak in _inflight forever (drain would never converge)
            self._settle_reaped(self.engine.reap_finished())
        except Exception:
            logger.exception("serve loop: evicting suspect %s failed",
                             req.uid)
        req.fault_count += 1
        if req.fault_count > self.config.poison_retry_budget:
            get_tracer().instant("serve/quarantine", cat="serve",
                                 uid=req.uid, faults=req.fault_count)
            logger.error(f"serve loop: quarantining request {req.uid} "
                         f"after {req.fault_count} engine-step faults")
            req.finalize(RequestState.FAILED, "quarantined",
                         error=f"engine step fault x{req.fault_count}: "
                               f"{cause}")
            self.metrics.on_quarantine()
            self.metrics.on_finish(req)
            return
        recompute = len(req.prompt_tokens) + len(req.tokens)
        self.metrics.on_recompute(recompute)
        get_tracer().instant("serve/evicted", cat="serve", uid=req.uid,
                             faults=req.fault_count,
                             recompute_tokens=recompute)
        req.state = RequestState.QUEUED
        with self._lock:
            # BACK of the queue: co-evicted suspects rotate through
            # re-admission order, so blame cycles across the suspect set
            # instead of pinning the same (possibly innocent) request
            self._queue.append(req)
        self._wake.set()

    # ------------------------------------------------------------------
    # KV drift reconciliation (projected model vs engine reality)
    # ------------------------------------------------------------------
    def _reconcile_kv(self, projected_blocks: int) -> None:
        """Reconcile the projected KV watermark (admission control's model
        of memory) against what the engine actually reserved — so the
        model itself is observable: ``kv_projected_bytes`` vs
        ``kv_observed_bytes`` gauges on ``/metrics``, a ``serve/kv_bytes``
        counter track on the dstrace timeline, and an edge-triggered
        ``serve/kv_drift`` instant when they diverge >10%. A drift edge no
        longer passes silently: when the engine holds MORE than the model
        projected (the unsafe direction — leaked blocks, bookkeeping bug)
        the effective watermark is recalibrated down by the observed ratio
        (``serve/kv_recalibrate`` instant + counter) and restored to 1.0
        when the drift clears. The safe direction (projection worst-case >
        current reservation, expected mid-decode) recalibrates nothing.
        Pure host-int arithmetic — the serve tick stays sync-free."""
        block_bytes = getattr(self.engine, "kv_block_bytes", None)
        if block_bytes is None:
            return
        bb = block_bytes()
        projected = projected_blocks * bb
        # evictable cache blocks are attributable to NO live request:
        # counting them as observed occupancy would fire a kv_drift edge
        # (and recalibrate admission down) on every warm cache, masking
        # the real leaks this detector exists for. Pinned pages stay in:
        # live readers hold them and the projection covers those readers
        observed = (self.engine.kv_reserved_blocks()
                    - self._cache_evictable_blocks()) * bb
        self.metrics.set_kv_bytes(projected, observed)
        tracer = get_tracer()
        if tracer.enabled:
            tracer.counter("serve/kv_bytes", cat="mem",
                           projected=projected, observed=observed)
        drifted = (max(projected, observed) > 0
                   and abs(projected - observed)
                   / max(projected, observed) > 0.10)
        if drifted and not self._kv_drifted:
            self.metrics.on_kv_drift()
            tracer.instant(
                "serve/kv_drift", cat="serve",
                projected_bytes=projected, observed_bytes=observed,
                drift_frac=round(abs(projected - observed)
                                 / max(projected, observed), 4))
        # recalibration tracks the ratio EVERY tick (the drift instant is
        # edge-triggered, the scale is not): a safe-direction episode that
        # flips unsafe mid-drift, or an unsafe one that worsens, must move
        # the watermark — only the >1% dead band keeps the instants from
        # firing every tick on ratio jitter
        if drifted and observed > projected:
            scale = max(projected / observed, 0.5)
        else:
            scale = 1.0
        if abs(scale - self._kv_watermark_scale) > 0.01:
            with self._lock:
                self._kv_watermark_scale = scale
            self.metrics.on_kv_recalibrate()
            tracer.instant("serve/kv_recalibrate", cat="serve",
                           watermark_scale=round(scale, 4),
                           direction=("observed_over_projected"
                                      if scale < 1.0 else "drift_cleared"))
        self._kv_drifted = drifted

    def _check_membership(self) -> bool:
        """Poll the membership view — the view throttles its own directory
        scans (``poll_lost``: half the lost_after window, same cadence the
        training runner uses), so this is cheap to call every serve tick.
        A lost peer means the next engine collective would wedge the tick
        forever: flip to sticky degraded (503) and fail in-flight requests
        NOW, while this thread can still run."""
        try:
            lost = self.membership.poll_lost()
        except Exception:
            logger.exception("serve loop: membership check failed")
            return True
        if not lost:                   # healthy, or throttled (None)
            return True
        reason = f"comm peer(s) lost: {lost}"
        logger.error(f"serve loop: {reason}; degrading replica instead of "
                     "stepping into a wedged collective")
        self._latch_degraded(reason)
        self._fail_all(reason)
        return False

    def _admit_from_queue(self, stolen_frac: float = 0.0) -> int:
        """FIFO admission while the engine has room for the request's FULL
        worst case (prompt + max_new_tokens) AND the active worst-case sum
        stays under the (possibly pressure-shrunk) capacity line — the
        dynamic form of the no-mid-decode-exhaustion invariant once the
        offload tier lets accepted work exceed device capacity. Brownout
        pauses low-priority admits (they wait in the queue, never silently
        dropped). Returns the number of requests admitted this tick."""
        admitted = 0
        brownout = self.ladder.level >= ServeLevel.BROWNOUT
        if self._tier_capable:
            # computed once, incremented per admission (the sum changes by
            # exactly the admitted request's worst case) — rescanning the
            # whole inflight table per admitted request would make a deep
            # queue drain O(queue x inflight) on the serve-loop thread
            usable = max(self.engine.kv_usable_blocks(), 1)
            effective = effective_usable_blocks(usable, stolen_frac)
            capacity = (self.config.kv_high_watermark
                        * self._kv_watermark_scale * effective)
            active_worst = self._active_worstcase()
        while True:
            # hold evicted retries while the fault window is open AND the
            # engine still has other work: re-admitting a retry into a
            # faulting batch makes it the "most recent admission" again and
            # blame-attribution would keep landing on it. When nothing
            # else can run, the retry IS admitted — alone, which is
            # exactly the isolation that disambiguates poison from victim
            hold_retries = (self._consecutive_faults > 0
                            and self.engine.has_work())
            with self._lock:
                req = None
                for cand in self._queue:
                    if brownout and cand.priority < 0:
                        continue
                    if cand.fault_count > 0 and hold_retries:
                        continue
                    req = cand
                    break
                if req is None:
                    return admitted
            need_blocks = self._blocks_for(req)
            if self._tier_capable and active_worst + need_blocks > capacity:
                return admitted
            need = len(req.prompt_tokens) + req.max_new_tokens
            if not self.engine.can_schedule([req.uid], [need]):
                return admitted
            with self._lock:
                self._queue.remove(req)
                self._inflight[req.uid] = req
                self._admitted_since_clean.append(req.uid)
            try:
                self.engine.admit(req.uid, req.engine_prompt())
            except Exception as e:
                # fail THIS request, not the batch (e.g. prompt longer than
                # the engine's max context)
                with self._lock:
                    self._inflight.pop(req.uid, None)
                    if req.uid in self._admitted_since_clean:
                        self._admitted_since_clean.remove(req.uid)
                req.finalize(RequestState.FAILED, "error", error=repr(e))
                self.metrics.on_finish(req)
                continue
            if req.admit_ts is None:
                # first admission only: re-admissions after eviction keep
                # the original queue-wait/TTFT edges
                req.admit_ts = time.monotonic()
            req.state = RequestState.PREFILL
            admitted += 1
            if self._tier_capable:
                active_worst += need_blocks

    def _active_worstcase(self) -> int:
        """Worst-case-at-completion block sum of ACTIVE (non-demoted)
        engine-resident requests — the left side of the dynamic admission
        invariant."""
        with self._lock:
            dem = set(self._demoted)
            total = 0
            for uid, r in self._inflight.items():
                if uid not in dem:
                    total += self._blocks_for(r)
            return total

    def _fan_out(self, step_out: Dict[int, int]):
        now = time.monotonic()
        n = 0
        ledger = getattr(self.engine, "sched_ledger", None)
        for uid, tok in step_out.items():
            req = self._inflight.get(uid)
            if req is None or req.state.terminal:
                continue
            req.state = RequestState.DECODE
            req.push_token(int(tok), now=now)
            if ledger is not None:
                # book this tick's decode work against the request — the
                # wall-clock-free per-request denominator (TickLedger
                # request attribution; settled into describe() at reap)
                ledger.attribute_request(uid, decode_tokens=1)
            n += 1
            seq = self.engine.state.get(uid)
            if seq is not None and seq.done:
                req.finalize(RequestState.FINISHED, "eos")
            elif len(req.tokens) >= req.max_new_tokens:
                req.finalize(RequestState.FINISHED, "length")
                self.engine.finish(uid)
        if n:
            self.metrics.on_tokens(n)

    def _expire_and_cancel(self):
        now = time.monotonic()
        with self._lock:
            queued = list(self._queue)
            inflight = list(self._inflight.values())
        for req in queued:
            if req.cancelled_requested or req.expired:
                with self._lock:
                    if req in self._queue:
                        self._queue.remove(req)
                self._finalize_expired(req, now)
                # never reached the engine: settle metrics here (engine-
                # resident requests settle in _reap)
                self.metrics.on_finish(req)
        for req in inflight:
            if req.cancelled_requested or req.expired:
                self._finalize_expired(req, now)
                self.engine.finish(req.uid)

    def _finalize_expired(self, req: Request, now: float):
        if req.cancelled_requested:
            req.finalize(RequestState.CANCELLED, "cancelled")
        else:
            req.finalize(RequestState.TIMED_OUT, "timeout")

    def _reap(self):
        """Release engine state (KV blocks in EITHER tier, sequence slots)
        for every done sequence and settle the owning requests."""
        self._settle_reaped(self.engine.reap_finished())

    def _settle_reaped(self, reaped) -> None:
        """Settle the owning requests of reaped uids — shared by the tick
        reap AND the fault-eviction path (whose reap_finished() may flush
        OTHER done sequences too; dropping those uids would leak their
        requests in ``_inflight`` forever)."""
        ledger = getattr(self.engine, "sched_ledger", None)
        for uid in reaped:
            with self._lock:
                req = self._inflight.pop(uid, None)
                if uid in self._demoted:
                    self._demoted.remove(uid)
                if uid in self._admitted_since_clean:
                    self._admitted_since_clean.remove(uid)
            if req is None:
                if ledger is not None:
                    ledger.pop_request(uid)
                continue
            if ledger is not None:
                # settle the request's tick attribution (also bounds the
                # ledger table: finished uids never linger there)
                req.sched_attribution = ledger.pop_request(uid)
            if not req.state.terminal:
                # engine marked it done (eos) but no token crossed this step
                req.finalize(RequestState.FINISHED, "eos")
            self.metrics.on_finish(req)

    def _fail_all(self, why: str):
        with self._lock:
            victims = list(self._queue) + list(self._inflight.values())
            self._queue.clear()
            inflight = list(self._inflight)
            self._inflight.clear()
            self._demoted.clear()
            self._admitted_since_clean.clear()
        for req in victims:
            req.finalize(RequestState.FAILED, "error", error=why)
            self.metrics.on_finish(req)
        for uid in inflight:
            try:
                self.engine.finish(uid)
            except Exception:
                pass
        try:
            self.engine.reap_finished()
        except Exception:
            logger.exception("serve loop: reap after failure also failed")
