"""Serving observability: per-request latency stats, rolling throughput,
KV-occupancy gauges.

Two export paths: ``events()`` emits ``(tag, value, step)`` tuples for the
``deepspeed_tpu.monitor`` fan-out (CSV / TensorBoard / WandB / Comet), and
``prometheus_text()`` renders a Prometheus text-format dump for the
front-end's ``/metrics`` endpoint.
"""

import collections
import threading
from typing import List

from deepspeed_tpu.monitor import Event
from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.timer import RateTracker

# bounded sample reservoirs: serving runs indefinitely, metric memory must not
_SAMPLE_WINDOW = 1024


class _LatencyStat:
    """Bounded-window latency aggregate (mean / p50 / p99 / max + lifetime
    count and sum — the count/sum pair is what Prometheus summaries carry)."""

    def __init__(self, window: int = _SAMPLE_WINDOW):
        self.samples = collections.deque(maxlen=window)
        self.count = 0
        self.sum = 0.0

    def add(self, v: float):
        self.samples.append(v)
        self.count += 1
        self.sum += v

    def mean(self) -> float:
        return sum(self.samples) / len(self.samples) if self.samples else 0.0

    def quantile(self, q: float) -> float:
        if not self.samples:
            return 0.0
        s = sorted(self.samples)
        i = min(int(q * len(s)), len(s) - 1)
        return s[i]

    def max(self) -> float:
        return max(self.samples) if self.samples else 0.0


class ServingMetrics:
    """All counters/gauges the serve loop maintains. Thread-safe: the serve
    loop writes, front-end threads read."""

    def __init__(self, rate_window_s: float = 30.0):
        self._lock = threading.Lock()
        # counters
        self.requests_submitted = 0
        self.requests_rejected = 0       # backpressure at submit()
        self.requests_completed = 0      # finished (length / eos)
        self.requests_cancelled = 0
        self.requests_timed_out = 0
        self.requests_failed = 0
        self.tokens_generated = 0
        self.engine_steps = 0
        # latency distributions (seconds)
        self.ttft = _LatencyStat()
        self.tpot = _LatencyStat()
        self.queue_wait = _LatencyStat()
        # gauges (set each serve-loop tick)
        self.queue_depth = 0
        self.inflight = 0
        self.kv_occupancy = 0.0
        self.kv_occupancy_peak = 0.0
        # projected-vs-observed KV reconciliation (dsmem satellite):
        # projected = admission control's worst-case byte sum, observed =
        # blocks the engine actually reserved; drift events count the
        # >10% divergence EDGES (episodes, not ticks)
        self.kv_projected_bytes = 0
        self.kv_observed_bytes = 0
        self.kv_drift_events = 0
        # rolling throughput
        self.token_rate = RateTracker(window_s=rate_window_s)
        self.request_rate = RateTracker(window_s=rate_window_s)

    # ---- serve-loop write API --------------------------------------------
    def on_submit(self):
        with self._lock:
            self.requests_submitted += 1

    def on_reject(self):
        with self._lock:
            self.requests_rejected += 1

    def on_tokens(self, n: int):
        with self._lock:
            self.tokens_generated += n
        self.token_rate.add(n)

    def on_step(self):
        with self._lock:
            self.engine_steps += 1

    def on_finish(self, req):
        """Fold a terminal request's latency samples in (any terminal state)."""
        from deepspeed_tpu.serving.request import RequestState
        with self._lock:
            if req.state == RequestState.FINISHED:
                self.requests_completed += 1
            elif req.state == RequestState.CANCELLED:
                self.requests_cancelled += 1
            elif req.state == RequestState.TIMED_OUT:
                self.requests_timed_out += 1
            else:
                self.requests_failed += 1
            if req.queue_wait_s is not None:
                self.queue_wait.add(req.queue_wait_s)
            if req.ttft_s is not None:
                self.ttft.add(req.ttft_s)
            if req.tpot_s is not None:
                self.tpot.add(req.tpot_s)
        self.request_rate.add(1)

    def set_gauges(self, queue_depth: int, inflight: int, kv_occupancy: float):
        with self._lock:
            self.queue_depth = queue_depth
            self.inflight = inflight
            self.kv_occupancy = kv_occupancy
            self.kv_occupancy_peak = max(self.kv_occupancy_peak, kv_occupancy)

    def set_kv_bytes(self, projected: int, observed: int):
        with self._lock:
            self.kv_projected_bytes = int(projected)
            self.kv_observed_bytes = int(observed)

    def on_kv_drift(self):
        with self._lock:
            self.kv_drift_events += 1

    # ---- export -----------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "requests_submitted": self.requests_submitted,
                "requests_rejected": self.requests_rejected,
                "requests_completed": self.requests_completed,
                "requests_cancelled": self.requests_cancelled,
                "requests_timed_out": self.requests_timed_out,
                "requests_failed": self.requests_failed,
                "tokens_generated": self.tokens_generated,
                "engine_steps": self.engine_steps,
                "queue_depth": self.queue_depth,
                "inflight": self.inflight,
                "kv_occupancy": self.kv_occupancy,
                "kv_occupancy_peak": self.kv_occupancy_peak,
                "kv_projected_bytes": self.kv_projected_bytes,
                "kv_observed_bytes": self.kv_observed_bytes,
                "kv_drift_events": self.kv_drift_events,
                "ttft_mean_s": self.ttft.mean(),
                "ttft_p50_s": self.ttft.quantile(0.5),
                "ttft_p99_s": self.ttft.quantile(0.99),
                "tpot_mean_s": self.tpot.mean(),
                "tpot_p50_s": self.tpot.quantile(0.5),
                "queue_wait_mean_s": self.queue_wait.mean(),
                "queue_wait_max_s": self.queue_wait.max(),
                "tokens_per_sec": self.token_rate.rate(),
                "requests_per_sec": self.request_rate.rate(),
            }

    def events(self, step: int) -> List[Event]:
        """(tag, value, step) tuples for ``MonitorMaster.write_events``."""
        return [(f"serving/{k}", float(v), step)
                for k, v in self.snapshot().items()]

    def export(self, monitor, step: int):
        """Fan the current snapshot out through a ``deepspeed_tpu.monitor``
        backend (anything with ``write_events``)."""
        if monitor is not None and getattr(monitor, "enabled", False):
            monitor.write_events(self.events(step))

    def prometheus_text(self) -> str:
        """Prometheus text exposition (counters + gauges + summary stats)."""
        snap = self.snapshot()
        counters = {"requests_submitted", "requests_rejected",
                    "requests_completed", "requests_cancelled",
                    "requests_timed_out", "requests_failed",
                    "tokens_generated", "engine_steps", "kv_drift_events"}
        lines = []
        with self._lock:
            summaries = [
                ("ttft_seconds", "time to first token (from arrival)",
                 self.ttft),
                ("tpot_seconds", "time per output token (decode phase)",
                 self.tpot),
                ("queue_wait_seconds", "admission queue wait", self.queue_wait),
            ]
            for name, help_text, stat in summaries:
                full = f"dstpu_serving_{name}"
                lines.append(f"# HELP {full} {help_text}")
                lines.append(f"# TYPE {full} summary")
                for q in (0.5, 0.9, 0.99):
                    lines.append(f'{full}{{quantile="{q}"}} '
                                 f"{stat.quantile(q):.9g}")
                lines.append(f"{full}_sum {stat.sum:.9g}")
                lines.append(f"{full}_count {stat.count}")
        for key in ("requests_submitted", "requests_rejected",
                    "requests_completed", "requests_cancelled",
                    "requests_timed_out", "requests_failed",
                    "tokens_generated", "engine_steps", "queue_depth",
                    "inflight", "kv_occupancy", "kv_occupancy_peak",
                    "kv_projected_bytes", "kv_observed_bytes",
                    "kv_drift_events",
                    "tokens_per_sec", "requests_per_sec"):
            full = f"dstpu_serving_{key}"
            kind = "counter" if key in counters else "gauge"
            lines.append(f"# TYPE {full} {kind}")
            lines.append(f"{full} {snap[key]:.9g}")
        # tracer-backed span summaries (request phase latencies straight
        # from the dstrace ring: serve/queued, serve/prefill, serve/decode)
        tracer = get_tracer()
        if tracer.enabled:
            # ONE call covering both families (serve spans + dsmem memory
            # tracks): two calls would emit the HELP/TYPE metadata block
            # twice, which the Prometheus text parser rejects wholesale
            lines.extend(tracer.prometheus_lines(prefix=("serve/", "mem/")))
        return "\n".join(lines) + "\n"
