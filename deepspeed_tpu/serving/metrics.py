"""Serving observability: per-request latency stats, rolling throughput,
KV-occupancy gauges.

Two export paths: ``events()`` emits ``(tag, value, step)`` tuples for the
``deepspeed_tpu.monitor`` fan-out (CSV / TensorBoard / WandB / Comet), and
``prometheus_text()`` renders a Prometheus text-format dump for the
front-end's ``/metrics`` endpoint.
"""

import collections
import threading
from typing import Dict, List

from deepspeed_tpu.monitor import Event
from deepspeed_tpu.telemetry import hist as dshist
from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.timer import RateTracker

# bounded sample reservoirs: serving runs indefinitely, metric memory must not
_SAMPLE_WINDOW = 1024

#: the SLO histogram families this module exports on /metrics, as
#: ``(family, attr, help)`` — one fixed-log-bucket histogram each
#: (``telemetry.hist``), fed from monotonic-stamp differences only.
#: bench_serve's proof set and env_report's inventory both derive from
#: THIS tuple, so a new family can never reach /metrics unlisted.
REQ_HIST_FAMILIES = (
    ("dstpu_req_ttft_seconds", "hist_ttft",
     "time to first token (from arrival, includes queue wait)"),
    ("dstpu_req_tpot_seconds", "hist_tpot",
     "time per output token (decode phase)"),
    ("dstpu_req_queue_wait_seconds", "hist_queue_wait",
     "admission queue wait"),
    ("dstpu_req_handoff_seconds", "hist_handoff",
     "prefill->decode KV handoff latency (role-split engines)"),
)


class _LatencyStat:
    """Bounded-window latency aggregate (mean / p50 / p99 / max + lifetime
    count and sum — the count/sum pair is what Prometheus summaries carry)."""

    def __init__(self, window: int = _SAMPLE_WINDOW):
        self.samples = collections.deque(maxlen=window)
        self.count = 0
        self.sum = 0.0

    def add(self, v: float):
        self.samples.append(v)
        self.count += 1
        self.sum += v

    def mean(self) -> float:
        return sum(self.samples) / len(self.samples) if self.samples else 0.0

    def quantile(self, q: float) -> float:
        if not self.samples:
            return 0.0
        s = sorted(self.samples)
        i = min(int(q * len(s)), len(s) - 1)
        return s[i]

    def max(self) -> float:
        return max(self.samples) if self.samples else 0.0


class ServingMetrics:
    """All counters/gauges the serve loop maintains. Thread-safe: the serve
    loop writes, front-end threads read."""

    def __init__(self, rate_window_s: float = 30.0):
        self._lock = threading.Lock()
        # counters
        self.requests_submitted = 0
        self.requests_rejected = 0       # backpressure at submit()
        self.requests_shed = 0           # rejected BY THE LADDER (SHED)
        self.requests_completed = 0      # finished (length / eos)
        self.requests_cancelled = 0
        self.requests_timed_out = 0
        self.requests_failed = 0
        self.requests_quarantined = 0    # poison requests past retry budget
        self.tokens_generated = 0
        self.engine_steps = 0
        # request-level fault isolation (non-fatal engine-step failures)
        self.engine_step_faults = 0
        self.fault_recoveries = 0        # clean-tick recovery episodes
        self.recomputed_tokens = 0       # KV rebuilt for evicted retries
        self.degraded_latches = 0        # sticky-503 latches (fatal only)
        # host KV offload tier
        self.kv_demotions = 0
        self.kv_promotions = 0
        self.kv_demoted_bytes = 0
        self.kv_promoted_bytes = 0
        self.host_kv_bytes = 0           # gauge
        # radix prefix cache (counters mirrored from the engine's
        # prefix_stats each tick — the engine owns the source of truth,
        # these are the thread-safe read surface for /metrics)
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_evictions = 0        # blocks reclaimed by the tick
        self.prefill_tokens_total = 0
        self.prefill_tokens_saved = 0
        self.prefill_tokens_computed = 0
        self.prefix_cache_hit_ratio = 0.0   # gauge (hit/lookup tokens)
        self.prefix_cached_blocks = 0       # gauge
        self.prefix_pinned_blocks = 0       # gauge
        # quantized host tier / capacity efficiency
        self.host_kv_compression_ratio = 1.0   # gauge (raw/stored)
        self.bytes_per_resident_token = 0.0    # gauge (both tiers)
        # degradation ladder
        self.ladder_level = 0            # gauge (ServeLevel int)
        self.ladder_transitions = 0
        self.brownout_entries = 0
        self.shed_entries = 0
        # projected-KV watermark recalibration (kv_drift satellite)
        self.kv_recalibrations = 0
        # latency distributions (seconds)
        self.ttft = _LatencyStat()
        self.tpot = _LatencyStat()
        self.queue_wait = _LatencyStat()
        # SLO histograms (deterministic fixed log buckets; lifetime, not
        # windowed — delta_from two slo_snapshot()s for a measured run)
        self.hist_ttft = dshist.LogHistogram()
        self.hist_tpot = dshist.LogHistogram()
        self.hist_queue_wait = dshist.LogHistogram()
        self.hist_handoff = dshist.LogHistogram()
        # gauges (set each serve-loop tick)
        self.queue_depth = 0
        self.inflight = 0
        self.kv_occupancy = 0.0
        self.kv_occupancy_peak = 0.0
        # projected-vs-observed KV reconciliation (dsmem satellite):
        # projected = admission control's worst-case byte sum, observed =
        # blocks the engine actually reserved; drift events count the
        # >10% divergence EDGES (episodes, not ticks)
        self.kv_projected_bytes = 0
        self.kv_observed_bytes = 0
        self.kv_drift_events = 0
        # rolling throughput
        self.token_rate = RateTracker(window_s=rate_window_s)
        self.request_rate = RateTracker(window_s=rate_window_s)

    # ---- serve-loop write API --------------------------------------------
    def on_submit(self):
        with self._lock:
            self.requests_submitted += 1

    def on_reject(self):
        with self._lock:
            self.requests_rejected += 1

    def on_tokens(self, n: int):
        with self._lock:
            self.tokens_generated += n
        self.token_rate.add(n)

    def on_step(self):
        with self._lock:
            self.engine_steps += 1

    def on_finish(self, req):
        """Fold a terminal request's latency samples in (any terminal state)."""
        from deepspeed_tpu.serving.request import RequestState
        with self._lock:
            if req.state == RequestState.FINISHED:
                self.requests_completed += 1
            elif req.state == RequestState.CANCELLED:
                self.requests_cancelled += 1
            elif req.state == RequestState.TIMED_OUT:
                self.requests_timed_out += 1
            else:
                self.requests_failed += 1
            if req.queue_wait_s is not None:
                self.queue_wait.add(req.queue_wait_s)
                self.hist_queue_wait.observe(req.queue_wait_s)
            if req.ttft_s is not None:
                self.ttft.add(req.ttft_s)
                self.hist_ttft.observe(req.ttft_s)
            if req.tpot_s is not None:
                self.tpot.add(req.tpot_s)
                self.hist_tpot.observe(req.tpot_s)
        self.request_rate.add(1)

    def on_handoff_latency(self, lat_s: float):
        """Fold one completed prefill->decode KV handoff's latency in
        (role-split engines; the serve loop drains these each tick)."""
        with self._lock:
            self.hist_handoff.observe(lat_s)

    def set_gauges(self, queue_depth: int, inflight: int, kv_occupancy: float):
        with self._lock:
            self.queue_depth = queue_depth
            self.inflight = inflight
            self.kv_occupancy = kv_occupancy
            self.kv_occupancy_peak = max(self.kv_occupancy_peak, kv_occupancy)

    def set_kv_bytes(self, projected: int, observed: int):
        with self._lock:
            self.kv_projected_bytes = int(projected)
            self.kv_observed_bytes = int(observed)

    def on_kv_drift(self):
        with self._lock:
            self.kv_drift_events += 1

    def on_kv_recalibrate(self):
        with self._lock:
            self.kv_recalibrations += 1

    def on_shed(self):
        with self._lock:
            self.requests_rejected += 1
            self.requests_shed += 1

    def on_quarantine(self):
        with self._lock:
            self.requests_quarantined += 1

    def on_step_fault(self):
        with self._lock:
            self.engine_step_faults += 1

    def on_recovered(self):
        with self._lock:
            self.fault_recoveries += 1

    def on_recompute(self, tokens: int):
        with self._lock:
            self.recomputed_tokens += tokens

    def on_degraded_latch(self):
        with self._lock:
            self.degraded_latches += 1

    def on_prefix_evict(self, blocks: int):
        with self._lock:
            self.prefix_evictions += blocks

    def set_prefix_gauges(self, stats: dict, resident_tokens: int,
                          resident_bytes: int, host_compression: float):
        """Mirror the engine's prefix/prefill counters (one tick's
        consistent view) and derive bytes-per-resident-token — the
        capacity-efficiency headline the quantized host tier moves."""
        with self._lock:
            self.prefill_tokens_total = int(
                stats.get("prefill_tokens_total", 0))
            self.prefill_tokens_saved = int(
                stats.get("prefill_tokens_saved", 0))
            self.prefill_tokens_computed = int(
                stats.get("prefill_tokens_computed", 0))
            self.prefix_hits = int(stats.get("prefix_hits", 0))
            self.prefix_misses = int(stats.get("prefix_misses", 0))
            self.prefix_cache_hit_ratio = float(
                stats.get("prefix_hit_ratio", 0.0))
            self.prefix_cached_blocks = int(
                stats.get("prefix_cached_blocks", 0))
            self.prefix_pinned_blocks = int(
                stats.get("prefix_pinned_blocks", 0))
            self.host_kv_compression_ratio = float(host_compression)
            self.bytes_per_resident_token = (
                resident_bytes / resident_tokens if resident_tokens else 0.0)

    def on_demote(self, nbytes: int):
        with self._lock:
            self.kv_demotions += 1
            self.kv_demoted_bytes += nbytes

    def on_promote(self, nbytes: int):
        with self._lock:
            self.kv_promotions += 1
            self.kv_promoted_bytes += nbytes

    def on_ladder_transition(self, frm, to):
        """Fold a ladder edge in; ``to`` is a ``ServeLevel``."""
        with self._lock:
            self.ladder_transitions += 1
            if to.name == "BROWNOUT":
                self.brownout_entries += 1
            elif to.name == "SHED":
                self.shed_entries += 1

    def set_tier_gauges(self, ladder_level: int, host_kv_bytes: int):
        with self._lock:
            self.ladder_level = int(ladder_level)
            self.host_kv_bytes = int(host_kv_bytes)

    # ---- export -----------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "requests_submitted": self.requests_submitted,
                "requests_rejected": self.requests_rejected,
                "requests_shed": self.requests_shed,
                "requests_completed": self.requests_completed,
                "requests_cancelled": self.requests_cancelled,
                "requests_timed_out": self.requests_timed_out,
                "requests_failed": self.requests_failed,
                "requests_quarantined": self.requests_quarantined,
                "tokens_generated": self.tokens_generated,
                "engine_steps": self.engine_steps,
                "engine_step_faults": self.engine_step_faults,
                "fault_recoveries": self.fault_recoveries,
                "recomputed_tokens": self.recomputed_tokens,
                "degraded_latches": self.degraded_latches,
                "kv_demotions": self.kv_demotions,
                "kv_promotions": self.kv_promotions,
                "kv_demoted_bytes": self.kv_demoted_bytes,
                "kv_promoted_bytes": self.kv_promoted_bytes,
                "host_kv_bytes": self.host_kv_bytes,
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "prefix_evictions": self.prefix_evictions,
                "prefill_tokens_total": self.prefill_tokens_total,
                "prefill_tokens_saved": self.prefill_tokens_saved,
                "prefill_tokens_computed": self.prefill_tokens_computed,
                "prefix_cache_hit_ratio": self.prefix_cache_hit_ratio,
                "prefix_cached_blocks": self.prefix_cached_blocks,
                "prefix_pinned_blocks": self.prefix_pinned_blocks,
                "host_kv_compression_ratio": self.host_kv_compression_ratio,
                "bytes_per_resident_token": self.bytes_per_resident_token,
                "ladder_level": self.ladder_level,
                "ladder_transitions": self.ladder_transitions,
                "brownout_entries": self.brownout_entries,
                "shed_entries": self.shed_entries,
                "kv_recalibrations": self.kv_recalibrations,
                "queue_depth": self.queue_depth,
                "inflight": self.inflight,
                "kv_occupancy": self.kv_occupancy,
                "kv_occupancy_peak": self.kv_occupancy_peak,
                "kv_projected_bytes": self.kv_projected_bytes,
                "kv_observed_bytes": self.kv_observed_bytes,
                "kv_drift_events": self.kv_drift_events,
                "ttft_mean_s": self.ttft.mean(),
                "ttft_p50_s": self.ttft.quantile(0.5),
                "ttft_p99_s": self.ttft.quantile(0.99),
                "tpot_mean_s": self.tpot.mean(),
                "tpot_p50_s": self.tpot.quantile(0.5),
                "queue_wait_mean_s": self.queue_wait.mean(),
                "queue_wait_max_s": self.queue_wait.max(),
                "tokens_per_sec": self.token_rate.rate(),
                "requests_per_sec": self.request_rate.rate(),
            }

    def slo_snapshot(self) -> Dict[str, dict]:
        """One consistent snapshot of every SLO histogram, keyed by its
        /metrics family name — the bench_serve proof set. Diff two of
        these (``LogHistogram.from_snapshot`` + ``delta_from``) for the
        warmed-run window."""
        with self._lock:
            return {family: getattr(self, attr).snapshot()
                    for family, attr, _help in REQ_HIST_FAMILIES}

    def events(self, step: int) -> List[Event]:
        """(tag, value, step) tuples for ``MonitorMaster.write_events``."""
        return [(f"serving/{k}", float(v), step)
                for k, v in self.snapshot().items()]

    def export(self, monitor, step: int):
        """Fan the current snapshot out through a ``deepspeed_tpu.monitor``
        backend (anything with ``write_events``)."""
        if monitor is not None and getattr(monitor, "enabled", False):
            monitor.write_events(self.events(step))

    def prometheus_text(self) -> str:
        """Prometheus text exposition (counters + gauges + summary stats)."""
        snap = self.snapshot()
        counters = {"requests_submitted", "requests_rejected",
                    "requests_shed", "requests_completed",
                    "requests_cancelled", "requests_timed_out",
                    "requests_failed", "requests_quarantined",
                    "tokens_generated", "engine_steps", "kv_drift_events",
                    "engine_step_faults", "fault_recoveries",
                    "recomputed_tokens", "degraded_latches",
                    "kv_demotions", "kv_promotions", "kv_demoted_bytes",
                    "kv_promoted_bytes", "ladder_transitions",
                    "brownout_entries", "shed_entries",
                    "kv_recalibrations", "prefix_hits", "prefix_misses",
                    "prefix_evictions", "prefill_tokens_total",
                    "prefill_tokens_saved", "prefill_tokens_computed"}
        lines = []
        with self._lock:
            summaries = [
                ("ttft_seconds", "time to first token (from arrival)",
                 self.ttft),
                ("tpot_seconds", "time per output token (decode phase)",
                 self.tpot),
                ("queue_wait_seconds", "admission queue wait", self.queue_wait),
            ]
            for name, help_text, stat in summaries:
                full = f"dstpu_serving_{name}"
                lines.append(f"# HELP {full} {help_text}")
                # namespace inlined so the TYPE claim is statically scoped
                # to dstpu_serving_* (DS008)
                lines.append(f"# TYPE dstpu_serving_{name} summary")
                for q in (0.5, 0.9, 0.99):
                    lines.append(f'{full}{{quantile="{q}"}} '
                                 f"{stat.quantile(q):.9g}")
                lines.append(f"{full}_sum {stat.sum:.9g}")
                lines.append(f"{full}_count {stat.count}")
            # SLO histograms: the dstpu_req_* namespace, one DS008-clean
            # block per family (fixed log buckets -> per-replica pages
            # merge counterwise into fleet-wide distributions)
            for family, attr, help_text in REQ_HIST_FAMILIES:
                lines.extend(dshist.prometheus_histogram_lines(
                    family, getattr(self, attr), help_text=help_text))
        # every snapshot key renders except the latency aggregates (the
        # *_s keys), which are exposed as proper summaries above — derived
        # from the snapshot itself so a new counter/gauge can never be in
        # one list but not the other
        for key, val in snap.items():
            if key.endswith("_s"):
                continue
            full = f"dstpu_serving_{key}"
            kind = "counter" if key in counters else "gauge"
            lines.append(f"# TYPE dstpu_serving_{key} {kind}")
            lines.append(f"{full} {val:.9g}")
        # tracer-backed span summaries (request phase latencies straight
        # from the dstrace ring: serve/queued, serve/prefill, serve/decode)
        tracer = get_tracer()
        if tracer.enabled:
            # ONE call covering both families (serve spans + dsmem memory
            # tracks): two calls would emit the HELP/TYPE metadata block
            # twice, which the Prometheus text parser rejects wholesale
            lines.extend(tracer.prometheus_lines(prefix=("serve/", "mem/")))
        return "\n".join(lines) + "\n"
