"""Continuous-batching serving layer over the v2 ragged engine (MII analog).

Request lifecycle + serve loop + admission control + observability + an
stdlib HTTP front door. See docs/serving.md.
"""

from deepspeed_tpu.serving.frontend import ServingFrontend
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.request import Request, RequestState
from deepspeed_tpu.serving.server import (BackpressureError, InferenceServer,
                                          ServerClosedError, ServingConfig)

__all__ = [
    "BackpressureError",
    "InferenceServer",
    "Request",
    "RequestState",
    "ServerClosedError",
    "ServingConfig",
    "ServingFrontend",
    "ServingMetrics",
]
