"""Continuous-batching serving layer over the v2 ragged engine (MII analog).

Request lifecycle + serve loop + tiered admission control (host-RAM KV
offload) + degradation ladder + request-level fault isolation +
observability + an stdlib HTTP front door + the bench_serve load harness.
See docs/serving.md.
"""

from deepspeed_tpu.serving.degradation import (DegradationLadder,
                                               LadderConfig, ServeLevel)
from deepspeed_tpu.serving.fleet import (FleetConfig, FleetRouter,
                                         ReplicaHandle)
from deepspeed_tpu.serving.frontend import ServingFrontend
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.request import Request, RequestState
from deepspeed_tpu.serving.server import (BackpressureError, InferenceServer,
                                          ServerClosedError, ServingConfig)

__all__ = [
    "BackpressureError",
    "DegradationLadder",
    "FleetConfig",
    "FleetRouter",
    "InferenceServer",
    "LadderConfig",
    "ReplicaHandle",
    "Request",
    "RequestState",
    "ServeLevel",
    "ServerClosedError",
    "ServingConfig",
    "ServingFrontend",
    "ServingMetrics",
]
