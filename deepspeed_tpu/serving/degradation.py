"""The serving degradation ladder — explicit, drillable overload states.

ROADMAP item 1 demands the serving layer "degrade to 'slower' before
'429'". The ladder makes that a real state machine instead of an emergent
property:

    HEALTHY ──pressure≥brownout──> BROWNOUT ──pressure≥shed──> SHED
       ^                              |   ^                      |
       └──── calm for cooldown ───────┘   └── calm for cooldown ─┘

                     (any fatal engine fault)
    HEALTHY/BROWNOUT/SHED ────────────────────> DEGRADED   (sticky)

* **HEALTHY** — normal admission.
* **BROWNOUT** — degrade to slower: new admissions get their
  ``max_new_tokens`` capped, low-priority queue entries wait (admits
  paused), and the KV tier demotes more aggressively. Still 200s.
* **SHED** — new submissions are rejected with 429 + ``Retry-After``;
  everything already accepted keeps running.
* **DEGRADED** — sticky 503, reserved for REAL engine faults (fatal
  classification through ``comm.guard.classify_exception``); pressure
  alone can never latch it, and it never self-clears — the replica must
  be drained and replaced.

Upward transitions are edge-triggered and immediate (overload must not
wait). Downward transitions carry hysteresis: pressure must stay below
``threshold - hysteresis`` for ``cooldown_ticks`` consecutive observations
before the ladder steps down ONE rung — so a load oscillating around a
threshold cannot flap the server between accepting and shedding.

Every transition emits an edge-triggered ``serve/ladder`` dstrace instant
(from/to/pressure/reason), which is how a whole overload episode is
reconstructed from the trace alone (the bench_serve/chaos-drill
contract). ``observe`` is a registered DS002 hot path: pure host
arithmetic, never a device touch.
"""

import enum
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from deepspeed_tpu.telemetry.tracer import get_tracer


class ServeLevel(enum.IntEnum):
    HEALTHY = 0
    BROWNOUT = 1
    SHED = 2
    DEGRADED = 3


@dataclass
class LadderConfig:
    brownout_pressure: float = 0.85   # climb to BROWNOUT at/above
    shed_pressure: float = 0.97       # climb to SHED at/above
    hysteresis: float = 0.10          # descend below threshold - this
    cooldown_ticks: int = 20          # consecutive calm ticks to descend

    def validate(self) -> "LadderConfig":
        if not 0.0 < self.brownout_pressure < self.shed_pressure:
            raise ValueError(
                f"need 0 < brownout_pressure ({self.brownout_pressure}) < "
                f"shed_pressure ({self.shed_pressure})")
        if self.hysteresis < 0.0 or self.cooldown_ticks < 1:
            raise ValueError("hysteresis must be >= 0 and "
                             "cooldown_ticks >= 1")
        return self


class DegradationLadder:
    """Single-writer state machine: only the serve loop calls ``observe``
    / ``latch_degraded``; other threads read ``level``/``reason`` (enum /
    str attribute reads, GIL-atomic)."""

    def __init__(self, config: Optional[LadderConfig] = None):
        self.config = (config or LadderConfig()).validate()
        self.level = ServeLevel.HEALTHY
        self.reason = ""
        self.last_pressure = 0.0
        self._calm_ticks = 0
        # lifetime transition counters keyed "FROM->TO" plus per-level
        # entry counts — the deterministic proof surface for bench_serve
        self.transitions: Dict[str, int] = {}
        self.entries: Dict[str, int] = {lv.name.lower(): 0
                                        for lv in ServeLevel}

    # ------------------------------------------------------------------
    def _threshold(self, level: ServeLevel) -> float:
        if level is ServeLevel.SHED:
            return self.config.shed_pressure
        if level is ServeLevel.BROWNOUT:
            return self.config.brownout_pressure
        return 0.0

    def _target(self, pressure: float) -> ServeLevel:
        if pressure >= self.config.shed_pressure:
            return ServeLevel.SHED
        if pressure >= self.config.brownout_pressure:
            return ServeLevel.BROWNOUT
        return ServeLevel.HEALTHY

    def _transition(self, to: ServeLevel, pressure: float, reason: str
                    ) -> Tuple[ServeLevel, ServeLevel]:
        frm = self.level
        self.level = to
        self.reason = reason
        self._calm_ticks = 0
        key = f"{frm.name}->{to.name}"
        self.transitions[key] = self.transitions.get(key, 0) + 1
        self.entries[to.name.lower()] += 1
        get_tracer().instant(
            "serve/ladder", cat="serve", frm=frm.name.lower(),
            to=to.name.lower(), pressure=round(pressure, 4), reason=reason)
        return frm, to

    # ------------------------------------------------------------------
    def observe(self, pressure: float, reason: str = ""
                ) -> Optional[Tuple[ServeLevel, ServeLevel]]:
        """Feed one tick's pressure scalar; returns the (from, to) edge
        when the ladder moved, else None. DEGRADED is sticky — pressure is
        recorded but cannot move the ladder."""
        self.last_pressure = pressure
        if self.level is ServeLevel.DEGRADED:
            return None
        target = self._target(pressure)
        if target > self.level:
            # overload climbs immediately (and may jump rungs)
            return self._transition(target, pressure, reason)
        if target < self.level:
            # descend one rung only after a full calm cooldown below the
            # CURRENT level's threshold minus the hysteresis band
            if pressure < self._threshold(self.level) - self.config.hysteresis:
                self._calm_ticks += 1
            else:
                self._calm_ticks = 0
            if self._calm_ticks >= self.config.cooldown_ticks:
                down = ServeLevel(self.level - 1)
                return self._transition(down, pressure, "pressure_lifted")
            return None
        self._calm_ticks = 0
        return None

    def latch_degraded(self, reason: str
                       ) -> Optional[Tuple[ServeLevel, ServeLevel]]:
        """Sticky latch for real engine faults — the ONLY path to
        DEGRADED, and there is no path out (drain + replace the replica)."""
        if self.level is ServeLevel.DEGRADED:
            return None
        return self._transition(ServeLevel.DEGRADED, self.last_pressure,
                                reason)
