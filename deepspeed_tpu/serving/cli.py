"""``dstpu_serve`` — stand up the serving stack on one host.

Demo-grade entry point: builds a model from a named preset (random-init
unless a checkpoint is supplied), wraps it in ``InferenceEngineV2`` +
``InferenceServer`` + the HTTP front-end, and serves until SIGINT (which
triggers a graceful drain). The hermetic CPU default (``--preset tiny``)
is the zero-to-first-token path:

    dstpu_serve --port 8000 &
    curl -s localhost:8000/generate -d '{"prompt_tokens": [1,2,3]}'
"""

import argparse
import signal
import sys


def main(argv=None):
    p = argparse.ArgumentParser(prog="dstpu_serve", description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--preset", default="tiny",
                   help="model preset: tiny (CPU demo) or a name from "
                        "deepspeed_tpu.models.llama (e.g. LLAMA3_8B)")
    p.add_argument("--checkpoint", default=None,
                   help="msgpack/orbax params path (random init when unset)")
    p.add_argument("--max-queue-depth", type=int, default=64)
    p.add_argument("--max-new-tokens", type=int, default=64,
                   help="default per-request generation budget")
    p.add_argument("--kv-num-blocks", type=int, default=512)
    p.add_argument("--kv-block-size", type=int, default=64)
    p.add_argument("--kv-high-watermark", type=float, default=0.95)
    p.add_argument("--request-timeout-s", type=float, default=None)
    p.add_argument("--kv-offload", action="store_true",
                   help="enable the host-RAM KV offload tier (overload "
                        "demotes queued/idle requests' KV pages to host "
                        "RAM instead of rejecting)")
    p.add_argument("--host-kv-budget-mb", type=int, default=256,
                   help="host-RAM budget for demoted KV pages")
    p.add_argument("--brownout-pressure", type=float, default=0.85,
                   help="degradation-ladder brownout threshold")
    p.add_argument("--shed-pressure", type=float, default=0.97,
                   help="degradation-ladder shed (429) threshold")
    p.add_argument("--brownout-max-new-tokens", type=int, default=16,
                   help="per-request generation cap while browned out")
    args = p.parse_args(argv)

    import jax
    import numpy as np

    from deepspeed_tpu.inference.v2.engine_v2 import (InferenceEngineV2,
                                                      V2EngineConfig)
    from deepspeed_tpu.models import llama as llama_lib
    from deepspeed_tpu.serving import (InferenceServer, ServingConfig,
                                       ServingFrontend)

    if args.preset == "tiny":
        cfg = llama_lib.TINY_LLAMA
    else:
        cfg = getattr(llama_lib, args.preset, None)
        if cfg is None:
            p.error(f"unknown preset {args.preset!r}")
    model = llama_lib.LlamaForCausalLM(cfg)
    if args.checkpoint:
        # training checkpoints carry optimizer state and need an engine;
        # the serving path wants a bare fp32 params npz (universal format,
        # flat "a/b/c" keys) re-nested into a params tree
        from deepspeed_tpu.checkpoint.universal import load_fp32_state
        params = {}
        for key, arr in load_fp32_state(args.checkpoint).items():
            node = params
            *parents, leaf = key.split("/")
            for part in parents:
                node = node.setdefault(part, {})
            node[leaf] = arr
    else:
        batch = {"input_ids": np.zeros((1, 8), np.int32)}
        params = model.init(jax.random.PRNGKey(0), batch)["params"]

    engine = InferenceEngineV2(params, cfg, V2EngineConfig(
        kv_block_size=args.kv_block_size, kv_num_blocks=args.kv_num_blocks))
    server = InferenceServer(engine, ServingConfig(
        max_queue_depth=args.max_queue_depth,
        default_max_new_tokens=args.max_new_tokens,
        default_timeout_s=args.request_timeout_s,
        kv_high_watermark=args.kv_high_watermark,
        kv_offload_enabled=args.kv_offload,
        host_kv_budget_bytes=args.host_kv_budget_mb << 20,
        brownout_pressure=args.brownout_pressure,
        shed_pressure=args.shed_pressure,
        brownout_max_new_tokens=args.brownout_max_new_tokens)).start()
    frontend = ServingFrontend(server, host=args.host, port=args.port).start()
    print(f"dstpu_serve: {frontend.url} (preset={args.preset}, "
          f"kv_blocks={args.kv_num_blocks})", flush=True)

    import threading
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    print("dstpu_serve: draining...", flush=True)
    server.stop(drain_timeout=30.0)
    frontend.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
