"""Prefill/decode disaggregation: a role-split engine pair in one process.

The first true disaggregation step (DeepSpeed-MII's split, PAPER.md L6):
one ``InferenceEngineV2`` owns the prefill role (admission, prefix cache,
chunked SplitFuse prefill), a second owns the decode role (steady-state
decode batches, the KV offload tier). The boundary is a block-granular KV
handoff through ``HostKVStore`` + the quantized page codec
(``kv_offload.quantize_pages``) — the fleet handoff-file path generalized
to in-process adoption (``InferenceEngineV2.adopt_kv_handoff``): demote
out of the prefill engine, adopt into the decode engine, no filesystem.

``DisaggregatedEngine`` presents the single-engine serving surface, so
``InferenceServer`` drives the pair unchanged. Gated behind
``serving.scheduler.role_split`` (default off = one engine, today's
semantics).

Handoff correctness envelope: "none" codec round-trips pages bit-identical
(device-fp8 pages always travel full-width with their scales); "int8"/
"fp8" round-trips are tolerance-bounded by ``quantize_error_bound``. Under
greedy sampling the handed-off sequence continues to the same tokens as a
single-engine run (pinned by tests/test_sched.py).
"""

import time
from typing import Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.runtime.sched import TickLedger
from deepspeed_tpu.telemetry.tracer import get_tracer


class _PairStateView:
    """The two role engines' sequence tables behind the single-engine
    ``engine.state`` read surface the serve loop uses (get/contains/len/
    all + max_context_length). Admission writes go through the pair's
    ``admit``, never through this view."""

    def __init__(self, prefill, decode):
        self._p = prefill
        self._d = decode

    @property
    def max_context_length(self) -> int:
        return self._d.state.max_context_length

    @property
    def max_tracked_sequences(self) -> int:
        return self._d.state.max_tracked_sequences

    def get(self, uid: int):
        seq = self._d.state.get(uid)
        return seq if seq is not None else self._p.state.get(uid)

    def all(self):
        return list(self._p.state.all()) + list(self._d.state.all())

    def __contains__(self, uid: int) -> bool:
        return uid in self._p.state or uid in self._d.state

    def __len__(self) -> int:
        return len(self._p.state) + len(self._d.state)


class DisaggregatedEngine:
    """Drives a prefill-role/decode-role ``InferenceEngineV2`` pair as one
    engine: admission and prefix cache on the prefill engine, the KV
    offload tier and steady-state decode on the decode engine, and the
    block-granular KV handoff between them inside ``step()``."""

    def __init__(self, prefill_engine, decode_engine,
                 handoff_quantize: str = "none"):
        if prefill_engine.kv.cfg.block_size != \
                decode_engine.kv.cfg.block_size:
            raise ValueError(
                "role engines must share KV block geometry: "
                f"{prefill_engine.kv.cfg.block_size} != "
                f"{decode_engine.kv.cfg.block_size}")
        self.prefill = prefill_engine
        self.decode = decode_engine
        self.handoff_quantize = handoff_quantize
        self.state = _PairStateView(prefill_engine, decode_engine)
        self.sched_ledger = TickLedger()
        self.last_step_timing = {"prefill_s": 0.0, "decode_s": 0.0}
        self.last_step_counters = {"prefill_tokens": 0, "chunks": 0,
                                   "decode_tokens": 0}
        self.handoff_stats = {"handoffs": 0, "handoff_blocks": 0,
                              "handoff_bytes": 0, "handoff_raw_bytes": 0,
                              "handoff_deferred": 0}
        # (uid, seconds) per completed handoff since the last drain — the
        # serve loop pops these each tick and folds them into the SLO
        # histograms (and the traced request's req/handoff span)
        self._handoff_latencies: List[Tuple[int, float]] = []

    # -- pass-through config surfaces ----------------------------------
    @property
    def config(self):
        return self.decode.config

    @property
    def kv(self):
        # tier planning (demotions/promotions, free-block headroom) is a
        # decode-role concern — that's where sequences live out their KV
        return self.decode.kv

    @property
    def prefix_cache(self):
        return self.prefill.prefix_cache

    def enable_prefix_cache(self, max_cached_blocks: int = 0) -> None:
        self.prefill.enable_prefix_cache(max_cached_blocks)

    def configure_chunked_prefill(self, prefill_chunk_tokens: int) -> None:
        self.prefill.configure_chunked_prefill(prefill_chunk_tokens)

    # -- admission (prefill role) --------------------------------------
    def query(self, uid: int, max_request_length: int) -> Tuple[int, int]:
        return self.prefill.query(uid, max_request_length)

    def can_schedule(self, uids: Sequence[int],
                     lengths: Sequence[int]) -> bool:
        fresh = [u for u in uids if u not in self.state]
        return self.prefill.can_schedule(uids, lengths) and \
            len(self.decode.state) + len(fresh) <= \
            self.decode.state.max_tracked_sequences

    def admit(self, uid: int, prompt_tokens: Sequence[int]):
        return self.prefill.admit(uid, prompt_tokens)

    # -- the step: prefill role, handoff, decode role ------------------
    def step(self) -> Dict[int, int]:
        t0 = time.perf_counter()
        out = self.prefill.step()
        out.update(self.decode.step())
        # handoff AFTER both role steps: a uid is resident on exactly one
        # engine at plan time, so the merged dict never clobbers a token
        # and the pair keeps the single-engine one-token-per-tick cadence
        # (adopting between the steps would decode the fresh sequence a
        # second time in the same tick, dropping its first token)
        self._handoff()
        pc, dc = self.prefill.last_step_counters, self.decode.last_step_counters
        pt, dt = self.prefill.last_step_timing, self.decode.last_step_timing
        self.last_step_timing = {
            "prefill_s": pt["prefill_s"] + dt["prefill_s"],
            "decode_s": pt["decode_s"] + dt["decode_s"]}
        counters = {
            "prefill_tokens": pc["prefill_tokens"] + dc["prefill_tokens"],
            "chunks": pc["chunks"] + dc["chunks"],
            "decode_tokens": pc["decode_tokens"] + dc["decode_tokens"]}
        self.last_step_counters = counters
        if counters["chunks"] or counters["decode_tokens"]:
            # the pair's OWN ledger sees one combined tick — decode-stall
            # semantics (prefill tokens a decode tick waited behind) apply
            # to the pair as a unit, not to each role engine alone
            self.sched_ledger.observe_tick(
                counters["prefill_tokens"], counters["chunks"],
                counters["decode_tokens"],
                cap=self.prefill.config.scheduler.prefill_chunk_tokens)
        tracer = get_tracer()
        if tracer.enabled:
            tracer.complete("disagg/tick", time.perf_counter() - t0,
                            cat="serve",
                            prefill_tokens=counters["prefill_tokens"],
                            decode_tokens=counters["decode_tokens"],
                            chunks=counters["chunks"])
        return out

    def _handoff(self) -> None:
        """Move every sequence that finished prefill this tick across the
        role boundary: demote its pages out of the prefill engine (the
        codec path tier demotion uses), adopt them into the decode
        engine, drop the donor-side residue. A decode engine that can't
        cover the entry right now defers the sequence (it stays paused
        with its host entry, invisible to the prefill planner) and the
        handoff retries next tick."""
        for seq in list(self.prefill.state.all()):
            if seq.done or seq.in_prefill:
                continue
            uid = seq.uid
            # per-handoff latency window: demote -> adopt for this tick's
            # attempt. A deferred handoff accrues only its successful
            # retry tick's work — the wait between ticks is queue time,
            # already visible as the gap before the handoff span.
            t_h0 = time.perf_counter()
            if not seq.paused:
                # freshly completed prefill (first token already sampled):
                # gather+release its pages into the prefill engine's host
                # store through the handoff codec
                self.prefill.demote_kv(uid, quantize=self.handoff_quantize)
            entry = self.prefill.host_kv.get(uid)
            if entry is None:
                continue
            if self.decode.adopt_kv_handoff(uid, seq.prompt_tokens,
                                            seq.generated, entry):
                self.prefill.host_kv.pop(uid)
                self.prefill.state.pop(uid)
                lat_s = time.perf_counter() - t_h0
                self.handoff_stats["handoffs"] += 1
                self.handoff_stats["handoff_blocks"] += entry.blocks
                self.handoff_stats["handoff_bytes"] += entry.nbytes
                self.handoff_stats["handoff_raw_bytes"] += entry.raw_nbytes
                self._handoff_latencies.append((uid, lat_s))
                get_tracer().instant("disagg/handoff", cat="serve",
                                     uid=uid, blocks=entry.blocks,
                                     bytes=entry.nbytes,
                                     quantize=self.handoff_quantize)
            else:
                self.handoff_stats["handoff_deferred"] += 1

    def pop_handoff_latencies(self) -> List[Tuple[int, float]]:
        """Drain the completed-handoff latencies accumulated since the
        last call: ``[(uid, seconds), ...]``. The serve loop calls this
        each tick to feed the handoff SLO histogram and, for traced
        requests, the ``req/handoff`` span."""
        out = self._handoff_latencies
        self._handoff_latencies = []
        return out

    # -- lifecycle -----------------------------------------------------
    def finish(self, uid: int) -> None:
        self.prefill.finish(uid)
        self.decode.finish(uid)

    def finished_uids(self) -> List[int]:
        return self.prefill.finished_uids() + self.decode.finished_uids()

    def reap_finished(self) -> Dict[int, List[int]]:
        out = self.prefill.reap_finished()
        out.update(self.decode.reap_finished())
        return out

    def flush(self, uid: int) -> List[int]:
        if uid in self.prefill.state:
            return self.prefill.flush(uid)
        return self.decode.flush(uid)

    def has_work(self) -> bool:
        # a deferred handoff is paused on the prefill engine (its own
        # has_work ignores paused) but is very much pending work here
        return any(not s.done for s in self.prefill.state.all()) or \
            self.decode.has_work()

    # -- KV tier hooks (decode role) -----------------------------------
    def demote_kv(self, uid: int, quantize: str = "none") -> int:
        return self.decode.demote_kv(uid, quantize=quantize)

    def promote_kv(self, uid: int) -> Optional[int]:
        return self.decode.promote_kv(uid)

    def demoted_uids(self) -> List[int]:
        return self.decode.demoted_uids()

    def demoted_blocks(self, uid: int) -> int:
        return self.decode.demoted_blocks(uid)

    def kv_held_blocks(self, uid: int) -> int:
        return self.prefill.kv_held_blocks(uid) + \
            self.decode.kv_held_blocks(uid)

    def host_kv_bytes(self) -> int:
        # deferred handoff entries sit in the prefill engine's store until
        # adoption — they are host bytes all the same
        return self.prefill.host_kv_bytes() + self.decode.host_kv_bytes()

    # -- prefix handoff files (prefill role owns the cache) ------------
    def export_prefix_handoff(self, path: str,
                              quantize: str = "none") -> Dict[str, int]:
        return self.prefill.export_prefix_handoff(path, quantize=quantize)

    def import_prefix_handoff(self, path: str) -> Dict[str, int]:
        return self.prefill.import_prefix_handoff(path)

    def evict_prefix_blocks(self, want: int) -> int:
        return self.prefill.evict_prefix_blocks(want)

    # -- gauges (pair sums) --------------------------------------------
    def kv_usable_blocks(self) -> int:
        return self.prefill.kv_usable_blocks() + \
            self.decode.kv_usable_blocks()

    def kv_reserved_blocks(self) -> int:
        return self.prefill.kv_reserved_blocks() + \
            self.decode.kv_reserved_blocks()

    def kv_occupancy(self) -> float:
        usable = self.kv_usable_blocks()
        return self.kv_reserved_blocks() / max(usable, 1)

    def kv_block_bytes(self) -> int:
        return self.decode.kv_block_bytes()

    def resident_tokens(self) -> int:
        return self.prefill.resident_tokens() + self.decode.resident_tokens()

    def kv_resident_bytes(self) -> int:
        return self.prefill.kv_resident_bytes() + \
            self.decode.kv_resident_bytes()

    def kv_ledger(self) -> Dict[str, int]:
        led = dict(self.prefill.kv_ledger())
        for k, v in self.decode.kv_ledger().items():
            if k == "host_compression_ratio":
                continue
            led[k] = led.get(k, 0) + v
        raw = self.prefill.host_kv.raw_bytes + self.decode.host_kv.raw_bytes
        stored = led["host_bytes"]
        led["host_compression_ratio"] = (raw / stored) if stored else 1.0
        return led

    def prefix_stats(self) -> Dict[str, float]:
        out = dict(self.prefill.prefix_stats())
        for k, v in self.decode.prefix_stats().items():
            if k.endswith("_ratio"):
                continue
            out[k] = out.get(k, 0) + v
        return out

    def speculative_stats(self) -> Dict[str, float]:
        return self.decode.speculative_stats()

    def sched_mark(self) -> None:
        self.sched_ledger.reset_window()
        self.prefill.sched_mark()
        self.decode.sched_mark()

    def sched_stats(self, gap_unit_tokens: int = 0) -> Dict[str, object]:
        return self.sched_ledger.snapshot(
            cap=self.prefill.config.scheduler.prefill_chunk_tokens,
            gap_unit_tokens=gap_unit_tokens)
