"""Tier policy for the host-RAM KV offload — the decision half.

Storage and page movement live on the engine
(``inference/v2/kv_offload.py`` + ``InferenceEngineV2.demote_kv`` /
``promote_kv``); this module is the pure arithmetic the serve tick runs
every iteration to decide WHO moves. Both planners are registered DS002
hot paths: the per-tick bookkeeping is plain host-int arithmetic over the
request tables and must never grow a device sync — the actual page copies
happen inside the engine calls the server then issues, off these
functions.

Policy (documented in docs/serving.md):

* **Prefix-cache eviction precedes sequence demotion** — under pressure
  the tick first reclaims UNPINNED cached prefix blocks (capacity nobody
  is reading; freeing them costs no copies and pauses no request), and
  only then demotes live sequences. Pinned shared-prefix pages are never
  discarded: they outlive every unshared page, and when their last
  reader demotes they travel to the host tier inside that reader's
  entry (``demote_kv``) instead of being dropped — the demotion-ordering
  contract: unpinned cache -> unshared sequences (LIFO) -> shared
  prefixes last, via the host tier.
* **Demotion is LIFO over the admit order** — the most recently admitted
  active request spills first, so the oldest requests keep running to
  completion (FIFO fairness preserved; same victim order as vLLM's
  recompute-preemption).
* **Promotion is FIFO over the demotion order** — the longest-demoted
  (most starved) request returns first, as soon as its worst-case blocks
  fit under the capacity line AND its held pages fit in free blocks.
* Two trigger lines: the *capacity* line (worst-case sum of active
  requests must fit under ``watermark x effective usable`` — the
  no-mid-decode-exhaustion invariant, re-established dynamically when
  chaos/pressure shrinks effective capacity) and the *demote* line
  (observed reserved blocks over ``demote_watermark x effective usable``
  — brownout lowers it, demoting more aggressively to keep headroom).
"""

from typing import List, Sequence, Tuple


def effective_usable_blocks(usable: int, stolen_frac: float) -> int:
    """Usable device blocks after chaos/pressure steals ``stolen_frac``
    (the ``DSTPU_CHAOS_SERVE_KV_PRESSURE`` drill surface); never < 1."""
    if stolen_frac <= 0.0:
        return max(usable, 1)
    kept = int(usable * (1.0 - stolen_frac))
    return max(kept, 1)


def plan_prefix_evictions(evictable_blocks: int, over_cap_blocks: int,
                          reserved_blocks: int,
                          demote_line_blocks: float) -> int:
    """How many unpinned cached prefix blocks to reclaim THIS tick,
    before any sequence is considered for demotion: enough to bring
    observed reservation back under the demote line (pressure relief
    that costs no copies and pauses nobody), plus any cache overhang
    past the configured cap — bounded by what is actually evictable.
    Pure host-int arithmetic (DS002 hot path); the engine executes the
    plan via ``evict_prefix_blocks``."""
    want = over_cap_blocks
    if reserved_blocks > demote_line_blocks:
        want = max(want, reserved_blocks - int(demote_line_blocks))
    return min(max(want, 0), max(evictable_blocks, 0))


def plan_demotions(worst_blocks: Sequence[int], held_blocks: Sequence[int],
                   reserved_blocks: int, capacity_blocks: float,
                   demote_line_blocks: float, min_active: int) -> List[int]:
    """Indices of ACTIVE requests to demote this tick, chosen from the
    tail of the admit-ordered lists (LIFO). ``worst_blocks[i]`` is request
    i's worst-case-at-completion block count, ``held_blocks[i]`` its
    currently reserved blocks. Demote until the active worst-case sum fits
    under the capacity line AND observed reservation is back under the
    demote line, keeping at least ``min_active`` requests running so the
    engine always makes progress. A victim is skipped (kept active) when
    demoting it would not help the binding constraint — e.g. a
    freshly-admitted prefill holding zero blocks frees nothing against the
    demote line; pausing it would just collapse throughput."""
    n = len(worst_blocks)
    worst_sum = 0
    for w in worst_blocks:
        worst_sum += w
    reserved = reserved_blocks
    out: List[int] = []
    kept = n
    i = n - 1
    while (i >= 0 and kept > max(min_active, 1)
           and (worst_sum > capacity_blocks
                or reserved > demote_line_blocks)):
        helps = worst_sum > capacity_blocks or held_blocks[i] > 0
        if helps:
            out.append(i)
            kept -= 1
            worst_sum -= worst_blocks[i]
            reserved -= held_blocks[i]
        i -= 1
    return out


def plan_promotions(demoted_worst: Sequence[int],
                    demoted_held: Sequence[int],
                    active_worst_sum: int, capacity_blocks: float,
                    free_blocks: int, reserved_blocks: int,
                    demote_line_blocks: float) -> int:
    """How many demoted requests (FIFO head of the demotion order) to
    promote this tick: each must fit under the capacity line with the
    already-active worst-case sum, its held pages must fit in currently
    free device blocks, AND restoring it must keep observed reservation
    under the demote line — the demote line doubles as the promotion
    hysteresis band, so one tick can never demote a request and promote it
    straight back (tier ping-pong). Progress guard: when NOTHING is active
    (every resident request is demoted) the FIFO head is promoted on free
    blocks alone — a paused server must always be able to restart."""
    k = 0
    worst_sum = active_worst_sum
    free = free_blocks
    reserved = reserved_blocks
    for w, h in zip(demoted_worst, demoted_held):
        if h > free:
            break
        if worst_sum + w > capacity_blocks or reserved + h > demote_line_blocks:
            if k == 0 and worst_sum == 0:
                return 1          # progress guard
            break
        k += 1
        worst_sum += w
        free -= h
        reserved += h
    return k


def tier_pressure(reserved_blocks: int, effective_usable: int,
                  queued: int, max_queue_depth: int,
                  host_bytes: int, host_budget_bytes: int
                  ) -> Tuple[float, str]:
    """The scalar the degradation ladder climbs on: the max of the three
    normalized exhaustion fractions, plus which one dominates (the
    ladder's transition ``reason``)."""
    device_frac = reserved_blocks / max(effective_usable, 1)
    queue_frac = queued / max(max_queue_depth, 1)
    host_frac = (host_bytes / host_budget_bytes
                 if host_budget_bytes > 0 else 0.0)
    pressure, reason = device_frac, "device_kv"
    if queue_frac > pressure:
        pressure, reason = queue_frac, "queue"
    if host_frac > pressure:
        pressure, reason = host_frac, "host_kv"
    return pressure, reason
