"""Stdlib-only HTTP front-end for ``InferenceServer``.

Reference analog: MII's REST/gRPC front door, reduced to what the standard
library provides (``http.server.ThreadingHTTPServer`` — one thread per
connection, fine for the request rates a single engine can absorb; a
production deployment would terminate HTTP elsewhere and speak to the serve
loop directly).

Endpoints:
  POST /generate  {"prompt_tokens": [..], "max_new_tokens": N,
                   "timeout_s": S, "priority": P, "stream": false}
      -> 200 {"uid", "tokens", "finish_reason", ...}
      -> with "stream": true, chunked JSON-lines: one {"token": t} per
         generated token, then a final {"done": true, ...} record
      -> 429 + Retry-After on backpressure (queue/KV watermark) AND when
         the degradation ladder sheds; 503 while draining or degraded
  GET /metrics    Prometheus text format
  GET /healthz    200 {"status": "serving", "level": "healthy" |
                  "brownout" | "shed", ...} / 503 otherwise ("level" +
                  "level_reason" expose the degradation ladder; brownout
                  and shed still answer 200 — the replica is alive, it is
                  shedding per-request, so LBs should keep it in rotation)
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from deepspeed_tpu.serving.request import RequestState
from deepspeed_tpu.serving.server import (BackpressureError, InferenceServer,
                                          ServerClosedError)
from deepspeed_tpu.utils.logging import logger


class ServingFrontend:
    """Binds an ``InferenceServer`` to a localhost HTTP socket. ``port=0``
    picks an ephemeral port (tests); read it back from ``.port``."""

    def __init__(self, server: InferenceServer, host: str = "127.0.0.1",
                 port: int = 0, request_timeout_s: float = 120.0):
        self.serving = server
        self.request_timeout_s = request_timeout_s
        frontend = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):   # route to our logger
                logger.debug("frontend: " + fmt % args)

            def _json(self, code: int, payload: dict, headers=()):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    h = frontend.serving.health()
                    self._json(200 if h["ok"] else 503, h)
                elif self.path == "/metrics":
                    body = frontend.serving.metrics.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                # drain the body FIRST: responding with unread body bytes on
                # the socket corrupts the next keep-alive request
                raw = self.rfile.read(int(self.headers.get("Content-Length",
                                                           0) or 0))
                if self.path != "/generate":
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    body = json.loads(raw or b"{}")
                    prompt = body["prompt_tokens"]
                except (ValueError, KeyError, TypeError) as e:
                    # TypeError: valid JSON that isn't an object
                    self._json(400, {"error": f"bad request: {e!r}"})
                    return
                try:
                    req = frontend.serving.submit(
                        prompt,
                        max_new_tokens=body.get("max_new_tokens"),
                        timeout_s=body.get("timeout_s"),
                        priority=body.get("priority", 0))
                except (TypeError, ValueError) as e:
                    # type-malformed payloads (non-list prompt, string
                    # max_new_tokens, ...) are client errors, not 500s
                    self._json(400, {"error": f"bad request: {e!r}"})
                    return
                except BackpressureError as e:
                    self._json(429, {"error": str(e),
                                     "retry_after_s": e.retry_after_s},
                               headers=[("Retry-After",
                                         f"{e.retry_after_s:.0f}")])
                    return
                except ServerClosedError as e:
                    self._json(503, {"error": str(e)})
                    return
                if body.get("stream"):
                    self._stream_response(req)
                else:
                    try:
                        req.result(timeout=frontend.request_timeout_s)
                    except TimeoutError:
                        # a 200 here would pass truncated output off as
                        # success; 504 lets the caller retry deliberately
                        req.cancel()
                        req.wait(timeout=5.0)
                        self._json(504, req.describe()
                                   | {"tokens": req.tokens,
                                      "error": "generation timed out "
                                               "server-side"})
                        return
                    # status mirrors the terminal state: only a normal
                    # finish is a 200 — FAILED/TIMED_OUT with a 200 would
                    # pass a broken or truncated generation off as success
                    code = {RequestState.FINISHED: 200,
                            RequestState.TIMED_OUT: 504,
                            RequestState.FAILED: 500}.get(req.state, 200)
                    self._json(code, req.describe() | {"tokens": req.tokens})

            def _stream_response(self, req):
                self.send_response(200)
                self.send_header("Content-Type", "application/jsonlines")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(obj):
                    data = (json.dumps(obj) + "\n").encode()
                    self.wfile.write(f"{len(data):x}\r\n".encode()
                                     + data + b"\r\n")
                    self.wfile.flush()

                try:
                    for tok in req.stream(timeout=frontend.request_timeout_s):
                        chunk({"token": tok})
                    chunk({"done": True} | req.describe())
                    self.wfile.write(b"0\r\n\r\n")
                except Exception:
                    # per-token timeout or client gone: free the engine slot
                    # and try to terminate the chunked stream so a live
                    # client isn't left waiting on a response that never
                    # ends; either way this connection is done
                    req.cancel()
                    try:
                        chunk({"done": True, "error": "stream aborted"}
                              | req.describe())
                        self.wfile.write(b"0\r\n\r\n")
                    except Exception:
                        pass
                    self.close_connection = True

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServingFrontend":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="dstpu-frontend", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
