"""Stdlib-only HTTP front-end for ``InferenceServer``.

Reference analog: MII's REST/gRPC front door, reduced to what the standard
library provides (``http.server.ThreadingHTTPServer`` — one thread per
connection, fine for the request rates a single engine can absorb; a
production deployment would terminate HTTP elsewhere and speak to the serve
loop directly).

Endpoints:
  POST /generate  {"prompt_tokens": [..], "max_new_tokens": N,
                   "timeout_s": S, "priority": P, "stream": false}
      -> 200 {"uid", "tokens", "finish_reason", ...}
      -> with "stream": true, chunked JSON-lines: one {"token": t} per
         generated token, then a final {"done": true, ...} record
      -> 429 + Retry-After on backpressure (queue/KV watermark) AND when
         the degradation ladder sheds; 503 while draining or degraded
  GET /metrics    Prometheus text format
  GET /healthz    200 {"status": "serving", "level": "healthy" |
                  "brownout" | "shed", ...} / 503 otherwise ("level" +
                  "level_reason" expose the degradation ladder; brownout
                  and shed still answer 200 — the replica is alive, it is
                  shedding per-request, so LBs should keep it in rotation).
                  Carries the fleet router's signals too: ``replica_id``,
                  ``prefix_cache_blocks`` (affinity), ``draining``
                  (retirement)
  POST /admin/drain {"handoff_path": P?, "quantize": C?}
      -> 202; background: drain, stop the serve loop, export the warm
         prefix cache to P (fleet retirement — the successor adopts it),
         then fire ``on_retired`` (the fleet worker exits there)
  POST /admin/adopt {"handoff_path": P}
      -> 200; queues P for adoption by the serve loop (the engine-owning
         thread imports it between ticks)

Slow/malformed-client hardening: a declared Content-Length over
``max_body_bytes`` is refused with 413 WITHOUT reading the body (the
connection closes — draining a hostile body is exactly the wedge); a
body that stalls past ``read_timeout_s`` (socket-level deadline) or
arrives short gets 408. Either way the handler thread is released —
the accept loop never inherits a wedged connection.
"""

import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from deepspeed_tpu.serving.request import RequestState
from deepspeed_tpu.serving.server import (BackpressureError, InferenceServer,
                                          ServerClosedError)
from deepspeed_tpu.utils.logging import logger


class ServingFrontend:
    """Binds an ``InferenceServer`` to a localhost HTTP socket. ``port=0``
    picks an ephemeral port (tests); read it back from ``.port``."""

    def __init__(self, server: InferenceServer, host: str = "127.0.0.1",
                 port: int = 0, request_timeout_s: float = 120.0,
                 max_body_bytes: int = 1 << 20,
                 read_timeout_s: float = 30.0,
                 drain_timeout_s: float = 30.0):
        self.serving = server
        self.request_timeout_s = request_timeout_s
        self.max_body_bytes = max_body_bytes
        self.read_timeout_s = read_timeout_s
        self.drain_timeout_s = drain_timeout_s
        # fleet hook: called after an admin-initiated drain+retire
        # completes (the fleet worker exits its process there)
        self.on_retired: Optional[Callable[[], None]] = None
        # monotonic stamp of the last /healthz poll: /healthz reports the
        # gap since the PREVIOUS poll (last_poll_age_s) so the router's
        # blind window between polls is measured, not assumed
        self._last_healthz_mono: Optional[float] = None
        frontend = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # socket-level read deadline: applies to every blocking read
            # on the connection (request line, headers, body), so a
            # stalled client times out instead of parking this handler
            # thread and its keep-alive socket forever
            timeout = read_timeout_s

            def log_message(self, fmt, *args):   # route to our logger
                logger.debug("frontend: " + fmt % args)

            def _json(self, code: int, payload: dict, headers=()):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    now = time.monotonic()
                    prev = frontend._last_healthz_mono
                    frontend._last_healthz_mono = now
                    h = frontend.serving.health()
                    # seconds since the PREVIOUS poll (None on the first):
                    # the router's own blind window, measured replica-side
                    h["last_poll_age_s"] = (round(now - prev, 6)
                                            if prev is not None else None)
                    self._json(200 if h["ok"] else 503, h)
                elif self.path == "/metrics":
                    body = frontend.serving.metrics.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                try:
                    clen = int(self.headers.get("Content-Length", 0) or 0)
                except ValueError:
                    self.close_connection = True
                    self._json(400, {"error": "bad Content-Length"})
                    return
                if clen > frontend.max_body_bytes:
                    # refuse WITHOUT reading: draining an oversized body
                    # is exactly the wedge this cap exists to prevent —
                    # the connection closes with the 413 instead
                    self.close_connection = True
                    self._json(413, {"error": f"body of {clen} bytes over "
                                              f"cap {frontend.max_body_bytes}"})
                    return
                try:
                    # drain the body FIRST: responding with unread body
                    # bytes on the socket corrupts the next keep-alive
                    # request (the socket deadline bounds this read)
                    raw = self.rfile.read(clen)
                except (socket.timeout, OSError):
                    self.close_connection = True
                    try:
                        self._json(408, {"error": "request body read "
                                                  "timed out"})
                    except OSError:
                        pass    # client already gone
                    return
                if len(raw) < clen:
                    # client hung up (or stalled to EOF) mid-body
                    self.close_connection = True
                    self._json(408, {"error": "short request body"})
                    return
                if self.path.startswith("/admin/"):
                    self._admin(raw)
                    return
                if self.path != "/generate":
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    body = json.loads(raw or b"{}")
                    prompt = body["prompt_tokens"]
                except (ValueError, KeyError, TypeError) as e:
                    # TypeError: valid JSON that isn't an object
                    self._json(400, {"error": f"bad request: {e!r}"})
                    return
                # trace-ID contract: the X-Dstpu-Trace header wins (the
                # router's propagation channel); a body field is the
                # fallback for clients that cannot set headers
                trace_id = (self.headers.get("X-Dstpu-Trace")
                            or body.get("trace_id"))
                try:
                    req = frontend.serving.submit(
                        prompt,
                        max_new_tokens=body.get("max_new_tokens"),
                        timeout_s=body.get("timeout_s"),
                        priority=body.get("priority", 0),
                        trace_id=trace_id)
                except (TypeError, ValueError) as e:
                    # type-malformed payloads (non-list prompt, string
                    # max_new_tokens, ...) are client errors, not 500s
                    self._json(400, {"error": f"bad request: {e!r}"})
                    return
                except BackpressureError as e:
                    self._json(429, {"error": str(e),
                                     "retry_after_s": e.retry_after_s},
                               headers=[("Retry-After",
                                         f"{e.retry_after_s:.0f}")])
                    return
                except ServerClosedError as e:
                    self._json(503, {"error": str(e)})
                    return
                if body.get("stream"):
                    self._stream_response(req)
                else:
                    try:
                        req.result(timeout=frontend.request_timeout_s)
                    except TimeoutError:
                        # a 200 here would pass truncated output off as
                        # success; 504 lets the caller retry deliberately
                        req.cancel()
                        req.wait(timeout=5.0)
                        self._json(504, req.describe()
                                   | {"tokens": req.tokens,
                                      "error": "generation timed out "
                                               "server-side"})
                        return
                    # status mirrors the terminal state: only a normal
                    # finish is a 200 — FAILED/TIMED_OUT with a 200 would
                    # pass a broken or truncated generation off as success
                    code = {RequestState.FINISHED: 200,
                            RequestState.TIMED_OUT: 504,
                            RequestState.FAILED: 500}.get(req.state, 200)
                    self._json(code, req.describe() | {"tokens": req.tokens})

            def _admin(self, raw: bytes):
                try:
                    body = json.loads(raw or b"{}")
                    if not isinstance(body, dict):
                        raise TypeError("payload must be a JSON object")
                except (ValueError, TypeError) as e:
                    self._json(400, {"error": f"bad request: {e!r}"})
                    return
                if self.path == "/admin/adopt":
                    path = body.get("handoff_path")
                    if not isinstance(path, str) or not path:
                        self._json(400, {"error": "handoff_path required"})
                        return
                    try:
                        frontend.serving.adopt_prefix_handoff(path)
                    except (ValueError, AttributeError) as e:
                        self._json(400, {"error": f"cannot adopt: {e!r}"})
                        return
                    self._json(200, {"adopted": True, "handoff_path": path})
                elif self.path == "/admin/drain":
                    handoff = body.get("handoff_path")
                    threading.Thread(
                        target=frontend._drain_and_retire,
                        args=(handoff, body.get("quantize")),
                        name="dstpu-frontend-drain", daemon=True).start()
                    # 202: retirement runs in the background — watch
                    # /healthz flip to draining, then stopped
                    self._json(202, {"draining": True,
                                     "handoff_path": handoff})
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def _stream_response(self, req):
                self.send_response(200)
                self.send_header("Content-Type", "application/jsonlines")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(obj):
                    data = (json.dumps(obj) + "\n").encode()
                    self.wfile.write(f"{len(data):x}\r\n".encode()
                                     + data + b"\r\n")
                    self.wfile.flush()

                try:
                    for tok in req.stream(timeout=frontend.request_timeout_s):
                        chunk({"token": tok})
                    chunk({"done": True} | req.describe())
                    self.wfile.write(b"0\r\n\r\n")
                except Exception:
                    # per-token timeout or client gone: free the engine slot
                    # and try to terminate the chunked stream so a live
                    # client isn't left waiting on a response that never
                    # ends; either way this connection is done
                    req.cancel()
                    try:
                        chunk({"done": True, "error": "stream aborted"}
                              | req.describe())
                        self.wfile.write(b"0\r\n\r\n")
                    except Exception:
                        pass
                    self.close_connection = True

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def _drain_and_retire(self, handoff_path: Optional[str],
                          quantize: Optional[str]) -> None:
        """Admin-initiated retirement: drain + stop the serve loop, export
        the warm prefix chains for the successor, fire ``on_retired``."""
        try:
            self.serving.stop(drain_timeout=self.drain_timeout_s)
            if handoff_path:
                # write-then-rename: the file's existence is the router's
                # "handoff complete" signal, so it must appear atomically
                part = handoff_path + ".part"
                self.serving.export_prefix_handoff(part, quantize=quantize)
                os.replace(part, handoff_path)
        except Exception:
            logger.exception("frontend: drain/retire failed")
        cb = self.on_retired
        if cb is not None:
            try:
                cb()
            except Exception:
                logger.exception("frontend: on_retired callback failed")

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServingFrontend":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="dstpu-frontend", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
