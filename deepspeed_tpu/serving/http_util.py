"""Shared stdlib HTTP client for the serving tier (router, healthz
poller, bench_serve client lanes).

One place for the client-side discipline every fleet component needs:

* **deadline-bounded requests** — every call carries a socket timeout;
  a wedged replica becomes an exception the caller classifies, never a
  forever-hang on a router thread;
* **exponential backoff with deterministic jitter** — the retry delay is
  a pure function of ``(seed, salt, attempt)`` (the chaos ``_roll``
  idiom), so a drill's retry schedule replays bit-identically while
  still de-synchronizing real fleets; a server-sent ``Retry-After`` is a
  FLOOR over the schedule (the replica's own hint wins);
* **the comm-guard outcome taxonomy, reused** — transport failures are
  classified by ``comm.guard.classify_exception``: TRANSIENT retries,
  auth/fatal raises immediately (an auth failure retried is an account
  lockout, not resilience);
* **non-idempotent safety** — a POST is retried ONLY when the caller
  supplies an idempotency key (the fleet router's dedupe uid). Without
  one, a retried submit could double-admit a generation; the helper
  clamps such calls to a single attempt rather than trusting callers to
  remember.

Streaming (``open_stream``) returns the replica's chunked JSON-lines
response as an iterator of parsed records; ``http.client`` dechunks, and
the per-read socket timeout bounds every token wait. Non-200 statuses
come back as data (status + parsed error body), never as exceptions —
backpressure is routing input, not a failure.
"""

import dataclasses
import hashlib
import http.client
import json
import time
import urllib.parse
from typing import Dict, Iterator, Optional, Tuple

from deepspeed_tpu.comm.guard import CommOutcome, classify_exception
from deepspeed_tpu.utils.logging import logger


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with deterministic jitter."""

    max_attempts: int = 3
    backoff_s: float = 0.05          # first retry's base delay
    backoff_max_s: float = 2.0       # exponential cap
    jitter_frac: float = 0.25        # delay *= 1 + jitter_frac * roll
    seed: int = 0                    # jitter stream (sha-rolled, replayable)


def backoff_delay(policy: RetryPolicy, attempt: int,
                  retry_after_s: Optional[float] = None,
                  salt: int = 0) -> float:
    """Delay before retry ``attempt`` (1-based): ``backoff_s * 2^(a-1)``
    capped at ``backoff_max_s``, stretched by deterministic jitter. A
    server-sent ``Retry-After`` is honored as a floor — backing off less
    than the replica asked for just re-arrives into the same shed."""
    base = min(policy.backoff_s * (2.0 ** max(attempt - 1, 0)),
               policy.backoff_max_s)
    h = hashlib.sha256(
        f"{policy.seed}:{salt}:{attempt}".encode()).digest()
    roll = int.from_bytes(h[:8], "big") / 2 ** 64
    delay = base * (1.0 + policy.jitter_frac * roll)
    if retry_after_s is not None:
        delay = max(delay, float(retry_after_s))
    return delay


def _parse_retry_after(headers: Dict[str, str]) -> Optional[float]:
    raw = headers.get("retry-after")
    if raw is None:
        return None
    try:
        return max(float(raw), 0.0)
    except ValueError:
        return None


@dataclasses.dataclass
class HttpReply:
    """One completed (non-streaming) exchange."""

    status: int
    headers: Dict[str, str]          # lower-cased keys
    body: bytes
    attempts: int = 1

    def json(self) -> dict:
        try:
            out = json.loads(self.body or b"{}")
        except ValueError:
            return {"error": self.body[:200].decode(errors="replace")}
        return out if isinstance(out, dict) else {"value": out}

    def retry_after_s(self) -> Optional[float]:
        return _parse_retry_after(self.headers)


def _split(url: str) -> Tuple[str, int, str]:
    u = urllib.parse.urlsplit(url)
    if u.scheme not in ("http", ""):
        raise ValueError(f"http_util speaks plain http only, got {url!r}")
    return u.hostname or "127.0.0.1", u.port or 80, (u.path or "/") + (
        f"?{u.query}" if u.query else "")


def _one_request(method: str, url: str, body: Optional[bytes],
                 timeout_s: float,
                 headers: Optional[Dict[str, str]] = None) -> HttpReply:
    host, port, path = _split(url)
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        hdrs = {"Content-Type": "application/json"} if body else {}
        if headers:
            hdrs.update(headers)
        conn.request(method, path, body, hdrs)
        resp = conn.getresponse()
        data = resp.read()
        return HttpReply(resp.status,
                         {k.lower(): v for k, v in resp.getheaders()}, data)
    finally:
        conn.close()


def request_json(method: str, url: str, payload: Optional[dict] = None,
                 timeout_s: float = 5.0,
                 retry: Optional[RetryPolicy] = None,
                 retry_status: Tuple[int, ...] = (),
                 idempotency_key: Optional[object] = None,
                 headers: Optional[Dict[str, str]] = None) -> HttpReply:
    """One JSON request with bounded, classified retries.

    Transport failures retry only when ``classify_exception`` says
    TRANSIENT (auth/fatal raises immediately — reusing the comm-guard
    taxonomy, satellite contract). Statuses in ``retry_status`` (e.g.
    ``(429,)`` for bench lanes) retry with ``Retry-After`` honored as the
    backoff floor. A non-GET without ``idempotency_key`` is clamped to
    ONE attempt no matter what ``retry`` says: retrying a submit the
    server may already have admitted needs the router's dedupe uid to be
    safe."""
    policy = retry or RetryPolicy(max_attempts=1)
    attempts = policy.max_attempts
    if method.upper() != "GET" and idempotency_key is None:
        attempts = 1
    body = (json.dumps(payload).encode() if payload is not None else None)
    salt = hash((url, str(idempotency_key))) & 0xFFFF
    attempt = 0
    while True:
        attempt += 1
        try:
            reply = _one_request(method, url, body, timeout_s,
                                 headers=headers)
        except Exception as e:
            outcome = classify_exception(e)
            if outcome is not CommOutcome.TRANSIENT or attempt >= attempts:
                raise
            delay = backoff_delay(policy, attempt, salt=salt)
            logger.debug(f"http_util: {method} {url} failed transient "
                         f"({e!r}); retry {attempt}/{attempts} in "
                         f"{delay:.3f}s")
            time.sleep(delay)
            continue
        if reply.status in retry_status and attempt < attempts:
            time.sleep(backoff_delay(policy, attempt,
                                     retry_after_s=reply.retry_after_s(),
                                     salt=salt))
            continue
        reply.attempts = attempt
        return reply


class StreamReply:
    """A streamed ``/generate`` exchange: ``status`` + parsed error body
    for non-200, or an open connection whose ``records()`` yields the
    JSON-lines records (``{"token": t}`` ... ``{"done": true, ...}``).
    Transport death mid-stream raises from ``records()`` — the router's
    failover trigger. Always ``close()`` (records() closes on exit)."""

    def __init__(self, status: int, headers: Dict[str, str],
                 error: Optional[dict], conn=None, resp=None):
        self.status = status
        self.headers = headers
        self.error = error
        self._conn = conn
        self._resp = resp

    def retry_after_s(self) -> Optional[float]:
        return _parse_retry_after(self.headers)

    def records(self) -> Iterator[dict]:
        if self._resp is None:
            return
        try:
            for line in self._resp:
                line = line.strip()
                if line:
                    yield json.loads(line)
        finally:
            self.close()

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None
                self._resp = None


def open_stream(url: str, payload: dict, timeout_s: float = 30.0,
                headers: Optional[Dict[str, str]] = None) -> StreamReply:
    """POST ``payload`` and return the streamed reply. ``timeout_s`` is
    the per-socket-read deadline (bounds both connect and every token
    wait). Raises on transport failure BEFORE a status line; after that,
    non-200 statuses are returned as data with the parsed error body."""
    host, port, path = _split(url)
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    body = json.dumps(payload).encode()
    hdrs = {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    try:
        conn.request("POST", path, body, hdrs)
        resp = conn.getresponse()
    except Exception:
        conn.close()
        raise
    headers = {k.lower(): v for k, v in resp.getheaders()}
    if resp.status != 200:
        try:
            raw = resp.read()
        except Exception:
            raw = b""
        conn.close()
        try:
            err = json.loads(raw or b"{}")
        except ValueError:
            err = {"error": raw[:200].decode(errors="replace")}
        return StreamReply(resp.status, headers, err)
    return StreamReply(200, headers, None, conn=conn, resp=resp)
