"""Request lifecycle for the serving layer.

Reference analog: DeepSpeed-MII's request/response plumbing over the FastGen
engine (MII sits above ``InferenceEngineV2`` exactly as this module sits above
``deepspeed_tpu.inference.v2``). A request moves through
QUEUED -> PREFILL -> DECODE -> a terminal state; tokens fan out to a
per-request stream as the serve loop produces them, so callers iterate
tokens while the engine keeps batching other requests.
"""

import enum
import queue
import threading
import time
from typing import Iterator, List, Optional, Sequence

from deepspeed_tpu.telemetry.tracer import get_tracer, request_tid


class RequestState(enum.Enum):
    QUEUED = "queued"          # accepted, waiting for engine admission
    PREFILL = "prefill"        # admitted, prompt KV being built (SplitFuse)
    DECODE = "decode"          # generating tokens
    FINISHED = "finished"      # completed normally (length / eos)
    CANCELLED = "cancelled"    # caller cancel()
    TIMED_OUT = "timed_out"    # deadline exceeded
    FAILED = "failed"          # engine error

    @property
    def terminal(self) -> bool:
        return self in (RequestState.FINISHED, RequestState.CANCELLED,
                        RequestState.TIMED_OUT, RequestState.FAILED)


# stream sentinel: pushed once when a request reaches a terminal state
_END = object()


class Request:
    """One generation request. Created by ``InferenceServer.submit``; the
    caller consumes ``stream()`` (token-at-a-time) or ``result()``
    (block until terminal). All mutation happens on the serve loop thread
    except ``cancel()``, which only sets an event the loop polls."""

    def __init__(self, uid: int, prompt_tokens: Sequence[int],
                 max_new_tokens: int, timeout_s: Optional[float] = None,
                 priority: int = 0):
        self.uid = uid
        self.prompt_tokens: List[int] = [int(t) for t in prompt_tokens]
        self.max_new_tokens = max_new_tokens
        # scheduling class: < 0 is low priority — brownout pauses its
        # engine admission (it waits in the queue; never silently dropped)
        self.priority = priority
        self.state = RequestState.QUEUED
        self.finish_reason: Optional[str] = None
        self.error: Optional[str] = None
        self.tokens: List[int] = []
        # engine-step faults attributed to this request (fault isolation:
        # past the retry budget the request is quarantined, not retried)
        self.fault_count = 0
        # degradation-ladder level the server accepted this request under
        # (stamped by submit(); rides on the lifecycle retro-spans so the
        # serve plan reports latency tails per ladder level)
        self.ladder_level = "healthy"
        # fleet-wide trace id (stamped by submit() from the router's
        # X-Dstpu-Trace header). When set, the lifecycle retro-spans get
        # req/* twins carrying it — the join key reqtrace.py stitches the
        # router's and replicas' rings on. None (local callers) emits no
        # req/ spans at all, so single-process rings are unchanged.
        self.trace_id: Optional[str] = None
        # TickLedger request attribution, settled at reap: which slice of
        # the tick stream this request consumed (wall-clock-free; rides
        # describe() into responses and flight-recorder ledgers)
        self.sched_attribution: Optional[dict] = None

        # lifecycle timestamps (monotonic clock; durations only)
        self.arrival_ts = time.monotonic()
        self.admit_ts: Optional[float] = None        # engine admission
        self.first_token_ts: Optional[float] = None  # TTFT edge
        self.finish_ts: Optional[float] = None
        self.deadline: Optional[float] = (
            self.arrival_ts + timeout_s if timeout_s is not None else None)

        self._stream: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._cancel = threading.Event()

    # ---- caller-side API -------------------------------------------------
    def cancel(self):
        """Request cancellation; the serve loop honors it on its next tick
        (terminal state becomes CANCELLED unless already terminal)."""
        self._cancel.set()

    @property
    def cancelled_requested(self) -> bool:
        return self._cancel.is_set()

    def stream(self, timeout: Optional[float] = None) -> Iterator[int]:
        """Yield generated tokens in order as they are produced; returns
        when the request reaches a terminal state. ``timeout`` bounds the
        wait for EACH token (raises ``queue.Empty`` on expiry)."""
        while True:
            item = self._stream.get(timeout=timeout)
            if item is _END:
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until terminal; returns the full generated token list.
        Raises ``TimeoutError`` if the request is still live after
        ``timeout`` seconds."""
        if not self._done.wait(timeout=timeout):
            raise TimeoutError(f"request {self.uid} still "
                               f"{self.state.value} after {timeout}s")
        return list(self.tokens)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout=timeout)

    # ---- serve-loop-side API ---------------------------------------------
    def engine_prompt(self) -> List[int]:
        """Tokens to (re)admit with: the original prompt plus everything
        already generated — an evicted-and-retried request continues its
        stream instead of restarting it (tokens already fanned out cannot
        be unsent), at the cost of recomputing that KV (the
        ``recomputed_tokens`` counter)."""
        return self.prompt_tokens + self.tokens

    def push_token(self, tok: int, now: Optional[float] = None):
        if self.first_token_ts is None:
            self.first_token_ts = time.monotonic() if now is None else now
        self.tokens.append(tok)
        self._stream.put(tok)

    def finalize(self, state: RequestState, reason: str,
                 error: Optional[str] = None):
        if self.state.terminal:
            return
        self.state = state
        self.finish_reason = reason
        self.error = error
        self.finish_ts = time.monotonic()
        tracer = get_tracer()
        if tracer.enabled:
            self._trace_lifecycle(tracer)
        self._stream.put(_END)
        self._done.set()

    def _trace_lifecycle(self, tracer):
        """Emit the request's phase spans retroactively from the lifecycle
        timestamps (same monotonic clock as the tracer), one synthetic track
        per uid: queued (arrival→admit), prefill (admit→first token), decode
        (first token→finish), plus a terminal instant. TTFT/TPOT are
        derivable from the trace alone: TTFT = queued.dur + prefill.dur,
        TPOT = decode.dur / (tokens - 1)."""
        tid = request_tid(self.uid)
        level = self.ladder_level
        if self.admit_ts is not None:
            tracer.complete("serve/queued", self.admit_ts - self.arrival_ts,
                            cat="serve", end_ts=self.admit_ts, tid=tid,
                            uid=self.uid, level=level)
            if self.first_token_ts is not None:
                tracer.complete("serve/prefill",
                                self.first_token_ts - self.admit_ts,
                                cat="serve", end_ts=self.first_token_ts,
                                tid=tid, uid=self.uid, level=level,
                                prompt_tokens=len(self.prompt_tokens))
        if self.first_token_ts is not None and self.finish_ts is not None:
            tracer.complete("serve/decode",
                            self.finish_ts - self.first_token_ts,
                            cat="serve", end_ts=self.finish_ts, tid=tid,
                            uid=self.uid, level=level,
                            tokens=len(self.tokens))
        tracer.instant(f"serve/{self.state.value}", cat="serve", tid=tid,
                       uid=self.uid, reason=self.finish_reason)
        if self.trace_id is not None:
            self._trace_req_spans(tracer, tid)

    def _trace_req_spans(self, tracer, tid: int):
        """The trace_id-scoped twins of the lifecycle spans: same clock,
        same track, but named under ``req/`` and carrying the fleet-wide
        trace id so the offline stitcher can join this replica's phases
        with the router's ``req/wall`` envelope. Emitted ONLY for traced
        (fleet-routed) requests — local submits leave the ring exactly as
        it was before request tracing existed."""
        trace_id = self.trace_id
        if self.admit_ts is not None:
            tracer.complete("req/queue", self.admit_ts - self.arrival_ts,
                            cat="serve", end_ts=self.admit_ts, tid=tid,
                            trace_id=trace_id, uid=self.uid)
            if self.first_token_ts is not None:
                tracer.complete("req/prefill",
                                self.first_token_ts - self.admit_ts,
                                cat="serve", end_ts=self.first_token_ts,
                                tid=tid, trace_id=trace_id, uid=self.uid,
                                prompt_tokens=len(self.prompt_tokens))
        if self.first_token_ts is not None and self.finish_ts is not None:
            tracer.complete("req/decode",
                            self.finish_ts - self.first_token_ts,
                            cat="serve", end_ts=self.finish_ts, tid=tid,
                            trace_id=trace_id, uid=self.uid,
                            tokens=len(self.tokens), state=self.state.value)

    # ---- derived metrics -------------------------------------------------
    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.admit_ts is None:
            return None
        return self.admit_ts - self.arrival_ts

    @property
    def ttft_s(self) -> Optional[float]:
        """Time-to-first-token, measured from arrival (includes queue wait)."""
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.arrival_ts

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean time-per-output-token over the decode phase (2nd token on)."""
        if (self.first_token_ts is None or self.finish_ts is None
                or len(self.tokens) < 2):
            return None
        return (self.finish_ts - self.first_token_ts) / (len(self.tokens) - 1)

    @property
    def expired(self) -> bool:
        return self.deadline is not None and time.monotonic() > self.deadline

    def describe(self) -> dict:
        out = {
            "uid": self.uid,
            "state": self.state.value,
            "prompt_tokens": len(self.prompt_tokens),
            "generated_tokens": len(self.tokens),
            "finish_reason": self.finish_reason,
            "queue_wait_s": self.queue_wait_s,
            "ttft_s": self.ttft_s,
            "tpot_s": self.tpot_s,
        }
        if self.priority:
            out["priority"] = self.priority
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        if self.sched_attribution is not None:
            out["sched_attribution"] = dict(self.sched_attribution)
        if self.fault_count:
            out["fault_count"] = self.fault_count
        if self.error is not None:
            out["error"] = self.error
        return out
