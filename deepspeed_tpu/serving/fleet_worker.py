"""One fleet replica as a process: tiny hermetic engine + HTTP front.

``python -m deepspeed_tpu.serving.fleet_worker`` is what
``fleet.subprocess_launcher`` spawns — a ``build_tiny_server`` engine
behind a ``ServingFrontend``, publishing its URL through a ready file
(written atomically: the launcher polls for it). The process exits when
the front door's ``/admin/drain`` retirement completes (``on_retired``)
or on SIGTERM — so for the router, "process exited after drain" IS the
handoff-complete signal.

``DSTPU_REPLICA_ID`` identifies the replica in ``/healthz`` and selects
it for ``DSTPU_CHAOS_REPLICA_KILL`` drills; the launcher sets it, and a
bare CLI run defaults it to ``--replica-id``.
"""

import argparse
import json
import os
import signal
import sys
import threading
from typing import Optional, Sequence

from deepspeed_tpu.resilience.chaos import REPLICA_ID_ENV


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="fleet_worker", description=__doc__)
    p.add_argument("--replica-id", type=int, required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--ready-file", required=True,
                   help="JSON {url, pid, replica_id} written (atomically) "
                        "once the front door is up")
    p.add_argument("--kv-num-blocks", type=int, default=64)
    p.add_argument("--kv-block-size", type=int, default=16)
    p.add_argument("--host-kv-quantize", default="int8",
                   choices=("none", "int8", "fp8"))
    p.add_argument("--serving-overrides", default=None, metavar="JSON")
    p.add_argument("--adopt-handoff", default=None, metavar="PATH",
                   help="import this prefix handoff before serving")
    args = p.parse_args(argv)
    os.environ.setdefault(REPLICA_ID_ENV, str(args.replica_id))

    # heavyweight imports AFTER arg parsing (and after the env is set so
    # the chaos monkey + replica identity see it)
    from deepspeed_tpu.serving.bench_serve import build_tiny_server
    from deepspeed_tpu.serving.frontend import ServingFrontend

    overrides = (json.loads(args.serving_overrides)
                 if args.serving_overrides else {})
    server = build_tiny_server(
        kv_num_blocks=args.kv_num_blocks,
        kv_block_size=args.kv_block_size,
        host_kv_quantize=args.host_kv_quantize,
        serving_overrides=overrides).start()
    if args.adopt_handoff:
        server.adopt_prefix_handoff(args.adopt_handoff)
    done = threading.Event()
    frontend = ServingFrontend(server, host=args.host, port=args.port)
    frontend.on_retired = done.set
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    frontend.start()
    ready = {"url": frontend.url, "pid": os.getpid(),
             "replica_id": args.replica_id}
    tmp = args.ready_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ready, f)
    os.replace(tmp, args.ready_file)
    done.wait()
    frontend.stop()
    if server.running:            # SIGTERM path; retirement already stopped
        server.stop(drain_timeout=10.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
