"""ZeRO-Inference: weight-only quantization + host weight offload for
throughput inference on small hardware.

Reference analogs:
- ``deepspeed/inference/quantization/`` (post-training group-wise weight-only
  quantization swapped into HF models; config ``weight_quantization`` with
  ``quantized_initialization``/``post_init_quant`` — int8/int4 grouped)
- ZeRO-Inference weight/KV offload (weights pinned in CPU DRAM, streamed to the
  accelerator layer by layer so models ≫ HBM can generate; the "20× inference"
  README claim).

TPU-native shape:
- **Quantized storage**: matched ≥2-D leaves are replaced by
  ``QuantizedTensor`` pytree nodes (int8 codes + fp32 group scales, original
  shape as static aux data) — HBM cost ≈ ¼ of bf16. Dequantization happens
  *inside* the jitted forward
  (``dequantize_model_params``), where XLA fuses scale-multiply into the
  consumer matmul.
- **Host offload + layer streaming**: the (quantized) store lives in host RAM;
  ``streamed_forward`` runs a per-layer jitted block fn while ``device_put``
  prefetches the next layer's weights — double buffering over PCIe/DCN, the
  swap-in/compute overlap the reference gets from its pinned-memory prefetcher.
  Works for the Llama family's ``layer_{i}`` tree layout.
"""

import re
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import LlamaConfig, rope_freqs
from deepspeed_tpu.utils.logging import log_dist

@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """quantized codes + fp32 group scales; the original shape and the wire
    format ("int8" | "fp6" | "fp8" | "fp12") ride as *static* pytree aux
    data so dequantization stays jit-friendly. fp6/fp12 codes are the
    densely bit-packed uint8 buffers of ops.fp_formats (0.75/1.5 B/elem);
    fp8 codes are native float8_e4m3fn."""

    def __init__(self, codes, scale, shape, fmt: str = "int8"):
        self.codes = codes
        self.scale = scale
        self.shape = tuple(int(s) for s in shape)
        self.fmt = fmt

    def tree_flatten(self):
        return (self.codes, self.scale), (self.shape, self.fmt)

    @classmethod
    def tree_unflatten(cls, aux, children):
        shape, fmt = aux
        return cls(children[0], children[1], shape, fmt)

    @property
    def nbytes(self) -> int:
        # the asarray branch only runs when codes is a host container
        # (lists/bytes from a deserialized payload) — arrays short-circuit
        # dslint: disable=DS002 -- hasattr-guarded host fallback, arrays take the nbytes branch
        return (np.asarray(self.codes).nbytes if not hasattr(self.codes, "nbytes")
                else self.codes.nbytes) + self.scale.nbytes


def _is_qrecord(node) -> bool:
    return isinstance(node, QuantizedTensor)


def quantize_model_params(params: Any, q_bits: int = 8, group_size: int = 64,
                          modules: Optional[Sequence[str]] = None,
                          fmt: str = "int") -> Any:
    """Group-wise symmetric weight-only quantization of a params tree
    (reference: inference/quantization quantization.py _init_group_wise_weight_
    quantization + fp_quantizer FP_Quantize). ``modules``: regexes of leaf
    paths to quantize (default: every floating leaf with ndim >= 2).
    ``fmt="int"``: integer codes at any q_bits; q_bits=4 densely packs
    two codes per byte (int4 at true 0.5 B/element — reference
    csrc/quantization int4 layout), other widths store int8.
    ``fmt="fp"``: minifloat codes — q_bits 6/12 use the packed software
    formats (0.75/1.5 B per element), q_bits 8 native float8_e4m3fn."""
    if fmt not in ("int", "fp"):
        raise ValueError(f"fmt must be 'int' or 'fp', got {fmt!r}")
    if fmt == "fp":
        if q_bits not in (6, 8, 12):
            raise ValueError("fp weight quantization supports q_bits 6, 8, 12")
        pack_group = {6: 4, 8: 1, 12: 2}[q_bits]
        if group_size % pack_group:
            raise ValueError(
                f"fp{q_bits} packs {pack_group} codes per unit: group_size "
                f"{group_size} must be divisible by {pack_group}")
    if fmt == "int" and q_bits == 4 and group_size % 2:
        raise ValueError(
            f"int4 packs two codes per byte: group_size {group_size} "
            f"must be even")
    pats = [re.compile(p) for p in (modules or [".*"])]
    qmax = 2.0 ** (q_bits - 1) - 1

    def quant(path, leaf):
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        arr = np.asarray(leaf)
        if (arr.ndim < 2 or not np.issubdtype(arr.dtype, np.floating)
                or not any(p.search(name) for p in pats)):
            return arr
        flat = arr.astype(np.float32).ravel()
        pad = (-flat.size) % group_size
        g = np.pad(flat, (0, pad)).reshape(-1, group_size)
        if fmt == "fp":
            from deepspeed_tpu.ops.fp_formats import FPQuantizer
            codes, scale = FPQuantizer(q_bits).quantize(jnp.asarray(g))
            return QuantizedTensor(np.asarray(codes),
                                   np.asarray(scale, np.float32),
                                   arr.shape, f"fp{q_bits}")
        scale = np.maximum(np.abs(g).max(axis=1, keepdims=True) / qmax, 1e-12)
        codes = np.clip(np.round(g / scale), -qmax - 1, qmax).astype(np.int8)
        if q_bits == 4:
            # nibble-pack: group_size is even (>= 2 codes per group row)
            c = (codes + 8).astype(np.uint8).reshape(codes.shape[0], -1, 2)
            packed = (c[:, :, 0] | (c[:, :, 1] << 4)).astype(np.uint8)
            return QuantizedTensor(packed, scale.astype(np.float32),
                                   arr.shape, "int4")
        return QuantizedTensor(codes, scale.astype(np.float32), arr.shape)

    return jax.tree_util.tree_map_with_path(quant, params)


def dequantize_model_params(qparams: Any, dtype=jnp.bfloat16) -> Any:
    """Inverse transform, jit-friendly (called inside the compiled forward so
    XLA fuses the scale-multiply into consumers)."""
    def deq(node):
        if not _is_qrecord(node):
            return node
        n = int(np.prod(node.shape))
        if node.fmt == "int4":
            packed = jnp.asarray(node.codes)
            lo = (packed & 0xF).astype(jnp.int32) - 8
            hi = (packed >> 4).astype(jnp.int32) - 8
            codes = jnp.stack([lo, hi], axis=-1).reshape(packed.shape[0], -1)
            flat = (codes.astype(jnp.float32)
                    * jnp.asarray(node.scale)).ravel()
        elif node.fmt in ("fp6", "fp12"):
            from deepspeed_tpu.ops.fp_formats import FPQuantizer
            bits = int(node.fmt[2:])
            d = node.codes.shape[-1] * 8 // bits
            flat = FPQuantizer(bits).dequantize(
                jnp.asarray(node.codes), jnp.asarray(node.scale), d=d,
                dtype=jnp.float32).ravel()
        else:   # int8 and fp8 codes both dequantize as codes * scale
            flat = (jnp.asarray(node.codes).astype(jnp.float32)
                    * jnp.asarray(node.scale)).ravel()
        return flat[:n].reshape(node.shape).astype(dtype)
    return jax.tree_util.tree_map(deq, qparams, is_leaf=_is_qrecord)


def quantized_nbytes(qparams: Any) -> int:
    total = 0
    for leaf in jax.tree.leaves(qparams):
        total += np.asarray(leaf).nbytes
    return total


class ZeROInferenceEngine:
    """Weight-quantized (optionally host-offloaded, layer-streamed) inference.

    ``offload="none"``: quantized store lives in HBM; one jitted forward
    dequantizes in place (≈4× HBM saving vs bf16).
    ``offload="cpu"``: store stays in host RAM; ``forward`` streams weights
    layer by layer with double buffering (models larger than HBM).
    """

    def __init__(self, model, params, model_config: Optional[LlamaConfig] = None,
                 q_bits: int = 8, group_size: int = 64,
                 offload: str = "none", dtype=jnp.bfloat16,
                 modules: Optional[Sequence[str]] = None, fmt: str = "int"):
        self.model = model
        self.cfg = model_config or getattr(model, "config", None)
        self.dtype = dtype
        self.offload = offload
        self.qstore = quantize_model_params(params, q_bits, group_size,
                                            modules, fmt=fmt)
        if offload == "none":
            self.qstore = jax.device_put(self.qstore)
        orig = sum(np.asarray(x).nbytes for x in jax.tree.leaves(params))
        log_dist(f"zero-inference: {orig / 1e6:.1f}MB fp -> "
                 f"{quantized_nbytes(self.qstore) / 1e6:.1f}MB quantized "
                 f"(q{q_bits}, offload={offload})", ranks=[0])
        self._fwd = None

    # -- resident (HBM) path ------------------------------------------------
    def forward(self, batch):
        if self.offload == "cpu":
            return self._streamed_forward(batch)
        if self._fwd is None:
            model, dtype = self.model, self.dtype

            def fwd(qstore, batch):
                return model.apply({"params": dequantize_model_params(qstore, dtype)},
                                   batch, method=lambda m, b: m.model(b["input_ids"]))
            self._fwd = jax.jit(fwd)
        return self._fwd(self.qstore, batch)

    # -- host-offloaded, layer-streamed path --------------------------------
    def _streamed_forward(self, batch):
        """Per-layer streaming for the Llama-family tree layout: embed →
        [stream layer_i weights, run block] → final norm + head. Next layer's
        host→device transfer is issued before the current block runs
        (device_put is async), giving copy/compute overlap."""
        cfg = self.cfg
        if cfg is None:
            raise ValueError("streamed forward needs a LlamaConfig-style model config")
        m = self.qstore["model"]
        ids = jnp.asarray(batch["input_ids"])

        embed = dequantize_model_params(jax.device_put(m["embed"]), self.dtype)
        x = embed["embedding"][ids]
        if getattr(cfg, "scale_embeddings", False):   # gemma normalizer
            x = x * jnp.sqrt(jnp.asarray(cfg.hidden_size,
                                         jnp.float32)).astype(x.dtype)
        positions = jnp.arange(ids.shape[1])[None, :]
        block_fn = self._block_fn()

        nxt = jax.device_put(m["layer_0"])  # prefetch first layer
        for i in range(cfg.num_layers):
            cur = nxt
            if i + 1 < cfg.num_layers:
                nxt = jax.device_put(m[f"layer_{i + 1}"])  # async prefetch
            x = block_fn(dequantize_model_params(cur, self.dtype), x, positions)

        tail = dequantize_model_params(jax.device_put(
            {"final_norm": m["final_norm"],
             **({"lm_head": m["lm_head"]} if "lm_head" in m else {})}), self.dtype)
        return self._head_fn()(tail, embed, x)

    def _block_fn(self):
        if getattr(self, "_block_jit", None) is None:
            kv_block = self._block_kv_fn()
            self._block_jit = jax.jit(
                lambda lp, x, positions: kv_block(lp, x, positions)[0])
        return self._block_jit

    def _block_kv_fn(self):
        """Prefill block that also RETURNS the layer's K/V (host KV-offload
        generation: reference ZeRO-Inference keeps the KV cache off-device
        so decode is incremental instead of full-context recompute)."""
        if getattr(self, "_block_kv_jit", None) is None:
            cfg = self.cfg

            def block(lp, x, positions):
                from deepspeed_tpu.inference.v2.llama_decode import (_mlp,
                                                                     _qkv,
                                                                     _rms)
                from deepspeed_tpu.models.llama import (_xla_attention,
                                                        apply_rope)
                cos, sin = rope_freqs(cfg.head_dim_, cfg.max_seq_len,
                                      cfg.rope_theta)
                off = 1.0 if getattr(cfg, "rms_scale_offset", False) else 0.0
                h = _rms(x, lp["attn_norm"]["scale"] + off, cfg.rms_norm_eps)
                b, s, d = h.shape
                q, k, v = _qkv(lp, h.reshape(b * s, d), self.dtype)
                q = q.reshape(b, s, *q.shape[1:])
                k = k.reshape(b, s, *k.shape[1:])
                v = v.reshape(b, s, *v.shape[1:])
                q = apply_rope(q, jnp.asarray(cos), jnp.asarray(sin),
                               positions)
                k = apply_rope(k, jnp.asarray(cos), jnp.asarray(sin),
                               positions)
                attn = _xla_attention(q, k, v, causal=True,
                                      window=cfg.sliding_window)
                out = jnp.einsum("bshk,hkd->bsd", attn,
                                 lp["attn"]["wo"]["kernel"].astype(self.dtype))
                x = x + out
                h2 = _rms(x, lp["mlp_norm"]["scale"] + off, cfg.rms_norm_eps)
                x = x + _mlp(lp, h2, self.dtype,
                             act=getattr(cfg, "hidden_act", "silu"))
                return x, k, v
            self._block_kv_jit = jax.jit(block)
        return self._block_kv_jit

    def _block_decode_fn(self):
        """Single-token block against a fixed-capacity KV buffer: writes the
        new token's K/V at ``ctx_len`` and attends over positions
        ``<= ctx_len``. Capacity-stable shapes mean ONE compile per bucket
        size (the buffer doubles as the context grows), not one per step."""
        if getattr(self, "_block_dec_jit", None) is None:
            cfg = self.cfg

            def block(lp, x, pos, k_buf, v_buf, ctx_len):
                from deepspeed_tpu.inference.v2.llama_decode import (_mlp,
                                                                     _qkv,
                                                                     _rms)
                from deepspeed_tpu.models.llama import apply_rope
                cos, sin = rope_freqs(cfg.head_dim_, cfg.max_seq_len,
                                      cfg.rope_theta)
                off = 1.0 if getattr(cfg, "rms_scale_offset", False) else 0.0
                h = _rms(x, lp["attn_norm"]["scale"] + off, cfg.rms_norm_eps)
                b, s, d = h.shape                      # s == 1
                q, k, v = _qkv(lp, h.reshape(b * s, d), self.dtype)
                q = apply_rope(q.reshape(b, s, *q.shape[1:]),
                               jnp.asarray(cos), jnp.asarray(sin), pos)
                k = apply_rope(k.reshape(b, s, *k.shape[1:]),
                               jnp.asarray(cos), jnp.asarray(sin), pos)
                v = v.reshape(b, s, *v.shape[1:])
                k_buf = jax.lax.dynamic_update_slice_in_dim(k_buf, k,
                                                            ctx_len, axis=1)
                v_buf = jax.lax.dynamic_update_slice_in_dim(v_buf, v,
                                                            ctx_len, axis=1)
                # buffer index == absolute position; visible iff <= ctx_len
                hq, hkv = q.shape[2], k_buf.shape[2]
                kq = jnp.repeat(k_buf, hq // hkv, 2) if hq != hkv else k_buf
                vq = jnp.repeat(v_buf, hq // hkv, 2) if hq != hkv else v_buf
                s_ = jnp.einsum("bqhd,bkhd->bhqk", q, kq,
                                preferred_element_type=jnp.float32
                                ) / np.sqrt(q.shape[-1])
                kpos = jnp.arange(k_buf.shape[1])[None, None, None, :]
                mask = kpos <= ctx_len
                if cfg.sliding_window:
                    mask = jnp.logical_and(
                        mask, kpos > ctx_len - cfg.sliding_window)
                s_ = jnp.where(mask, s_, -1e30)
                p = jax.nn.softmax(s_, axis=-1).astype(vq.dtype)
                attn = jnp.einsum("bhqk,bkhd->bqhd", p, vq)
                out = jnp.einsum("bshk,hkd->bsd", attn,
                                 lp["attn"]["wo"]["kernel"].astype(self.dtype))
                x = x + out
                h2 = _rms(x, lp["mlp_norm"]["scale"] + off, cfg.rms_norm_eps)
                x = x + _mlp(lp, h2, self.dtype,
                             act=getattr(cfg, "hidden_act", "silu"))
                return x, k_buf, v_buf
            self._block_dec_jit = jax.jit(block)
        return self._block_dec_jit

    def _head_fn(self):
        if getattr(self, "_head_jit", None) is None:
            cfg = self.cfg

            def head(tail, embed, x):
                from deepspeed_tpu.inference.v2.llama_decode import _rms
                off = 1.0 if getattr(cfg, "rms_scale_offset", False) else 0.0
                x = _rms(x, tail["final_norm"]["scale"] + off, cfg.rms_norm_eps)
                if "lm_head" in tail:
                    logits = x.astype(jnp.float32) @ \
                        tail["lm_head"]["kernel"].astype(jnp.float32)
                else:
                    logits = x.astype(jnp.float32) @ \
                        embed["embedding"].astype(jnp.float32).T
                cap = getattr(cfg, "logits_soft_cap", None)
                if cap:
                    logits = cap * jnp.tanh(logits / cap)
                return logits
            self._head_jit = jax.jit(head)
        return self._head_jit

    # -- generation ---------------------------------------------------------
    def generate(self, prompt_tokens: Sequence[int], max_new_tokens: int = 32
                 ) -> List[int]:
        """Greedy generation. Resident mode uses the FastGen paged engine over
        the dequantized-on-the-fly weights; offload mode streams layer
        weights AND a host-offloaded KV cache per step (reference
        ZeRO-Inference KV offload) so decode is incremental."""
        if self.offload == "none" and self.cfg is not None:
            from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
            deq = jax.jit(lambda q: dequantize_model_params(q, self.dtype))(self.qstore)
            return InferenceEngineV2(deq, self.cfg).generate(
                list(prompt_tokens), max_new_tokens=max_new_tokens)
        return self._streamed_generate(list(prompt_tokens), max_new_tokens)

    def _streamed_generate(self, ids: List[int], max_new_tokens: int
                           ) -> List[int]:
        """Layer-streamed generation with a HOST-offloaded KV cache
        (reference: ZeRO-Inference's KV offload — the cache lives off the
        accelerator and streams in per layer per step). KV buffers are
        padded to power-of-2 buckets so the decode block compiles once per
        bucket size, not once per step."""
        cfg = self.cfg
        if cfg is None:
            raise ValueError("streamed generation needs a LlamaConfig-style "
                             "model config")
        if max_new_tokens <= 0:
            return []
        m = self.qstore["model"]
        embed = dequantize_model_params(jax.device_put(m["embed"]),
                                        self.dtype)
        scale_emb = jnp.sqrt(jnp.asarray(cfg.hidden_size, jnp.float32)) \
            if getattr(cfg, "scale_embeddings", False) else None

        def embed_tokens(tok_ids):
            x = embed["embedding"][jnp.asarray(tok_ids)]
            return x * scale_emb.astype(x.dtype) if scale_emb is not None \
                else x

        def bucket(n):
            return 1 << max(4, (n - 1).bit_length())

        # prefill: stream layers once over the prompt, parking each layer's
        # K/V on the host in bucket-padded buffers
        block_kv = self._block_kv_fn()
        x = embed_tokens(np.asarray([ids]))
        positions = jnp.arange(len(ids))[None, :]
        cap = bucket(len(ids) + max_new_tokens // 2)
        host_kv = []
        nxt_w = jax.device_put(m["layer_0"])
        for i in range(cfg.num_layers):
            cur = nxt_w
            if i + 1 < cfg.num_layers:
                nxt_w = jax.device_put(m[f"layer_{i + 1}"])
            x, k, v = block_kv(dequantize_model_params(cur, self.dtype),
                               x, positions)
            k, v = np.asarray(k), np.asarray(v)
            pad = ((0, 0), (0, cap - k.shape[1]), (0, 0), (0, 0))
            host_kv.append((np.pad(k, pad), np.pad(v, pad)))

        tail = dequantize_model_params(jax.device_put(
            {"final_norm": m["final_norm"],
             **({"lm_head": m["lm_head"]} if "lm_head" in m else {})}),
            self.dtype)
        head = self._head_fn()
        logits = head(tail, embed, x)
        out = [int(jnp.argmax(logits[0, -1]))]
        ids = ids + out[-1:]

        # decode: per token, per layer — stream the layer weights AND that
        # layer's host KV buffer; the block writes the new K/V in place
        block_dec = self._block_decode_fn()
        for _ in range(max_new_tokens - 1):
            ctx_len = len(ids) - 1             # new token's write index
            if ctx_len + 1 > cap:              # grow the bucket
                new_cap = bucket(ctx_len + 1)
                host_kv = [(np.pad(k, ((0, 0), (0, new_cap - cap), (0, 0),
                                       (0, 0))),
                            np.pad(v, ((0, 0), (0, new_cap - cap), (0, 0),
                                       (0, 0))))
                           for k, v in host_kv]
                cap = new_cap
            pos = jnp.asarray([[ctx_len]])
            x = embed_tokens(np.asarray([[ids[-1]]]))
            nxt_w = jax.device_put(m["layer_0"])
            for i in range(cfg.num_layers):
                cur = nxt_w
                if i + 1 < cfg.num_layers:
                    nxt_w = jax.device_put(m[f"layer_{i + 1}"])
                k_buf, v_buf = host_kv[i]
                x, k_buf, v_buf = block_dec(
                    dequantize_model_params(cur, self.dtype), x, pos,
                    jax.device_put(k_buf), jax.device_put(v_buf),
                    jnp.int32(ctx_len))
                host_kv[i] = (np.asarray(k_buf), np.asarray(v_buf))
            logits = head(tail, embed, x)
            out.append(int(jnp.argmax(logits[0, -1])))
            ids.append(out[-1])
        return out
