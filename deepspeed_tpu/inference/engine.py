"""Inference engine (v1-equivalent).

Reference analog: ``deepspeed/inference/engine.py:41`` (``InferenceEngine``) — wraps a
model, creates the TP group, applies kernel injection, and serves ``forward`` /
``generate``. TPU redesign: "kernel injection" is the XLA compiler (+ Pallas kernels
used inside the model); TP is a ``tensor``-axis sharding of the params; CUDA-graph
capture is subsumed by jit compilation. The FastGen-style ragged continuous-batching
engine (reference ``inference/v2/engine_v2.py``) lives in
``deepspeed_tpu.inference.v2``.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.comm import mesh as mesh_lib
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.inference.config import InferenceConfig
from deepspeed_tpu.runtime.precision import cast_to_compute
from deepspeed_tpu.runtime.zero.partition import build_param_shardings
from deepspeed_tpu.utils.logging import log_dist


class InferenceEngine:
    """Single-batch inference wrapper (reference: inference/engine.py:41).

    ``model``: flax Module (apply) or callable ``apply_fn(params, batch)``.
    ``params``: host or device pytree; sharded over the tensor axis per
    ``tensor_rules`` (the AutoTP analog) and replicated otherwise.
    """

    def __init__(self, model, config: InferenceConfig, params: Optional[Any] = None,
                 mesh=None, tensor_rules: Optional[Callable] = None):
        self.module = model
        self.config = config
        self._validate_config(config)
        if mesh is None:
            mesh = mesh_lib.create_mesh(MeshConfig(data=-1, tensor=config.tp_size))
        self.mesh = mesh
        self.dtype = {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
                      "float16": jnp.float16, "fp16": jnp.float16,
                      "float32": jnp.float32, "fp32": jnp.float32}[config.dtype]

        if hasattr(model, "apply"):
            self._apply_fn = lambda p, batch: model.apply({"params": p}, batch)
        elif callable(model):
            self._apply_fn = model
        else:
            raise TypeError(f"model must be flax Module or callable, got {type(model)}")

        self.params = None
        if params is not None:
            # TP sharding via rules; stage 0 (no fsdp) for inference
            shardings = build_param_shardings(params, self.mesh, stage=0,
                                              tensor_rules=tensor_rules)
            self.params = jax.device_put(params, shardings)
            self.params = cast_to_compute(self.params, self.dtype)
        self._forward = jax.jit(self._apply_fn)
        log_dist(f"inference engine: tp={config.tp_size} dtype={config.dtype}", ranks=[0])

    @staticmethod
    def _validate_config(config: InferenceConfig):
        if config.tp_size < 1:
            raise ValueError(f"tp_size must be >= 1, got {config.tp_size}")

    def forward(self, batch, params: Optional[Any] = None):
        """reference: engine.forward:579 (graph capture is jit compilation here)."""
        p = params if params is not None else self.params
        if p is None:
            raise ValueError("no params bound; pass params= at init or to forward()")
        return self._forward(p, batch)

    __call__ = forward
