"""Continuous-batching inference engine (FastGen equivalent).

Reference analog: ``deepspeed/inference/v2/engine_v2.py:30``
(``InferenceEngineV2``): ``put(batch_uids, batch_tokens)`` schedules a ragged
forward; ``query``/``can_schedule`` gate admission on free KV blocks; the state
manager + blocked KV cache hold per-sequence context.

TPU adaptation: per step, the SplitFuse plan becomes (a) one bucketed
``prefill_chunk`` call per admitted chunk and (b) one padded ``decode_step`` call
for all running decodes — every shape from a small bucket ladder, so steady-state
serving runs entirely from compiled programs.
"""

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.v2.generic_decode import (decode_step_g,
                                                       prefill_chunk_g,
                                                       verify_chunk_g)
from deepspeed_tpu.inference.v2.kv_cache import BlockedKVCache, KVCacheConfig
from deepspeed_tpu.inference.v2.kv_offload import (HostKVEntry, HostKVStore,
                                                   dequantize_pages,
                                                   quantize_pages)
from deepspeed_tpu.inference.v2.modules import policy_for
from deepspeed_tpu.inference.v2.prefix_cache import PrefixCache
from deepspeed_tpu.inference.v2.ragged_manager import SequenceDescriptor, StateManager
from deepspeed_tpu.inference.v2.sampling import SamplingConfig, sample_tokens
from deepspeed_tpu.inference.v2.scheduler import (
    PrefillChunk,
    SchedulerConfig,
    StepPlan,
    plan_step,
    snap_bucket,
)
from deepspeed_tpu.models.llama import LlamaConfig
from deepspeed_tpu.runtime.sched import TickLedger
from deepspeed_tpu.telemetry.tracer import get_tracer
from deepspeed_tpu.utils.logging import log_dist


@dataclasses.dataclass
class V2EngineConfig:
    kv_block_size: int = 64
    kv_num_blocks: int = 512
    max_tracked_sequences: int = 256
    scheduler: SchedulerConfig = dataclasses.field(default_factory=SchedulerConfig)
    decode_batch_buckets: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)
    ctx_block_buckets: Tuple[int, ...] = (4, 8, 16, 32, 64)   # blocks per table
    eos_token_id: Optional[int] = None
    greedy: bool = True            # back-compat; sampling is the full control
    sampling: SamplingConfig = dataclasses.field(default_factory=SamplingConfig)
    # attention implementation: auto (Pallas kernel on TPU, gather elsewhere),
    # kernel, kernel_interpret, gather — see llama_decode._paged_attn
    attn_impl: str = "auto"
    # KV page dtype: "model" stores pages in the model compute dtype; "fp8"
    # stores float8_e4m3 pages — HALF the KV memory vs bf16 (2x capacity:
    # bigger batches / longer contexts), with per-(head, page) fp32 scales
    # (grown on outliers, page requantized — reference group-scaled fp
    # quantizer, csrc/fp_quantizer) applied on load inside both attention
    # paths
    kv_cache_dtype: str = "model"
    # draft-free speculative decoding (prompt-lookup): propose the k tokens
    # that followed the last occurrence of the trailing n-gram, verify them
    # in ONE chunk forward, accept the longest argmax-matching prefix + one
    # bonus token — 1..k+1 tokens per step, greedy-equivalent up to batching
    # numerics (verified bitwise on CPU f32; on TPU bf16 the [bucket, D]
    # verify matmul can reorder reductions vs the 1-row decode and flip
    # argmax on near-ties). Beyond-reference: FastGen has no speculative
    # decoding. 0 = off; greedy-only (rejected at construction under
    # sampling)
    speculative_k: int = 0
    speculative_ngram: int = 3
    # block-granular radix prefix cache (prefix_cache.py): admission
    # reuses already-materialized KV blocks for the longest cached
    # prompt prefix (refcounted pins on shared pages) and only prefills
    # the novel suffix. Default OFF = pre-cache semantics (same opt-in
    # discipline as kv_offload / async_pipeline); the serving group's
    # `prefix_cache_enabled` flips it on through enable_prefix_cache()
    prefix_cache_enabled: bool = False
    # soft cap on UNPINNED cached blocks (0 = unlimited up to pool size);
    # the serve tick trims the cache down to it even without pressure
    prefix_cache_max_blocks: int = 0


class InferenceEngineV2:
    """Serves any registered arch (llama family incl. mistral/qwen2/phi3,
    falcon, opt, mixtral) — the policy registry picks the decode implementation
    from the model config type (reference: engine_factory + heuristics)."""

    def _page_dtype(self, spec):
        kinds = {"model": spec.dtype, "fp8": jnp.float8_e4m3fn}
        kvd = self.config.kv_cache_dtype
        if kvd not in kinds:
            raise ValueError(f"unknown kv_cache_dtype {kvd!r}; one of "
                             f"{sorted(kinds)}")
        return kinds[kvd]

    def __init__(self, params, model_config,
                 config: Optional[V2EngineConfig] = None):
        self.params = params
        self.model_config = model_config
        self.config = config or V2EngineConfig()
        if self.config.speculative_k > 0 and not self.config.greedy:
            # reject BEFORE any sequence state exists: failing inside
            # _speculative_step would leave a half-processed sequence whose
            # prefill already consumed KV blocks
            raise ValueError(
                "speculative_k > 0 requires greedy=True: proposal "
                "acceptance compares argmax chains, which sampling breaks")
        self.policy = policy_for(model_config)
        spec = self.policy.cache_spec(model_config)
        self.kv = BlockedKVCache(KVCacheConfig(
            num_layers=spec.num_layers,
            num_kv_heads=spec.num_kv_heads,
            head_dim=spec.head_dim,
            block_size=self.config.kv_block_size,
            num_blocks=self.config.kv_num_blocks,
            dtype=self._page_dtype(spec)))
        self.state = StateManager(
            max_tracked_sequences=self.config.max_tracked_sequences,
            max_context_length=spec.max_seq_len)
        if not self.config.greedy and \
                self.config.sampling.temperature <= 0.0:
            self.config = dataclasses.replace(
                self.config,
                sampling=dataclasses.replace(self.config.sampling,
                                             temperature=1.0))
        self._rng = jax.random.PRNGKey(self.config.sampling.seed)
        self._pending_logits: Dict[int, np.ndarray] = {}
        # persistent device-side decode tables: in steady-state decode the
        # block tables only change when a sequence crosses a block boundary,
        # so the [B, MB] table upload is skipped while the allocation
        # signature (bucket shape + every sequence's block-id list) is
        # unchanged (addresses the per-step host re-pad/re-upload cost;
        # tokens/positions are [B] ints and always refresh)
        self._table_sig = None
        self._dev_tables = None
        # host-RAM KV offload tier (serving demotion target; kv_offload.py)
        self.host_kv = HostKVStore()
        # radix prefix cache over KV pages (prefix_cache.py); None = off
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(self.config.kv_block_size,
                        self.config.prefix_cache_max_blocks)
            if self.config.prefix_cache_enabled else None)
        # prefill-work conservation counters (prefix_stats): at drain,
        # saved + computed == total exactly (never-prefilled remainders
        # of cancelled sequences are subtracted from total at flush)
        self._prefill_total = 0
        self._prefill_saved = 0
        self._prefill_computed = 0
        # last step's host-timed prefill/decode split (serve-tick clocks)
        self.last_step_timing = {"prefill_s": 0.0, "decode_s": 0.0}
        # deterministic per-tick scheduler counters (runtime/sched.py) — the
        # decode-first chunked-prefill proof set; fed every non-empty step
        # in BOTH modes so an uncapped run yields the A/B baseline counters
        self.sched_ledger = TickLedger()
        self.last_step_counters = {"prefill_tokens": 0, "chunks": 0,
                                   "decode_tokens": 0}
        # speculative-decoding counters (speculative_stats)
        self._spec_steps = 0
        self._spec_proposed = 0
        self._spec_accepted = 0

    def enable_prefix_cache(self, max_cached_blocks: int = 0) -> None:
        """Turn the radix prefix cache on (idempotent) — the serving
        layer's wiring point for the ``serving.prefix_cache_enabled``
        config key when the engine wasn't constructed with it."""
        if self.prefix_cache is None:
            self.prefix_cache = PrefixCache(self.config.kv_block_size,
                                            max_cached_blocks)

    def configure_chunked_prefill(self, prefill_chunk_tokens: int) -> None:
        """Set the decode-first prefill cap (the serving layer's wiring
        point for ``serving.scheduler.prefill_chunk_tokens``). The cap
        must cover at least one KV block: capped mid-prompt boundaries
        snap DOWN to block granularity, so a smaller cap could never
        make progress."""
        cap = int(prefill_chunk_tokens)
        if cap > 0 and cap < self.kv.cfg.block_size:
            raise ValueError(
                f"prefill_chunk_tokens={cap} is smaller than the KV block "
                f"size ({self.kv.cfg.block_size}): block-aligned chunking "
                f"could never make progress")
        self.config = dataclasses.replace(
            self.config, scheduler=dataclasses.replace(
                self.config.scheduler, prefill_chunk_tokens=cap))

    def sched_mark(self) -> None:
        """Start the measured counter window (bench: at the compile mark,
        so warm-wave ticks never leak into the measured maxima)."""
        self.sched_ledger.reset_window()

    def sched_stats(self, gap_unit_tokens: int = 0) -> Dict[str, object]:
        """The scheduler proof set (see TickLedger.snapshot)."""
        return self.sched_ledger.snapshot(
            cap=self.config.scheduler.prefill_chunk_tokens,
            gap_unit_tokens=gap_unit_tokens)

    # ------------------------------------------------------------------
    # admission control (reference: engine_v2.py:158 query, :184 can_schedule)
    # ------------------------------------------------------------------
    def query(self, uid: int, max_request_length: int) -> Tuple[int, int]:
        """Returns (max_new_blocks_needed, free_blocks)."""
        seq = self.state.get(uid)
        tracked = seq.total_tokens if seq else 0
        needed = self.kv.blocks_needed(tracked + max_request_length) - \
            (len(seq.blocks) if seq else 0)
        return needed, self.kv.free_blocks

    def can_schedule(self, uids: Sequence[int],
                     lengths: Sequence[int]) -> bool:
        total = 0
        for uid, n in zip(uids, lengths):
            needed, _ = self.query(uid, n)
            total += needed
        # unpinned cached prefix blocks count as schedulable capacity:
        # they are evicted on demand the moment a reservation needs them
        return total <= self.kv.free_blocks + self._evictable_blocks() and \
            len(self.state) + len([u for u in uids if u not in self.state]) <= \
            self.state.max_tracked_sequences

    # ------------------------------------------------------------------
    # block bookkeeping
    # ------------------------------------------------------------------
    def _evictable_blocks(self) -> int:
        return (self.prefix_cache.evictable_blocks()
                if self.prefix_cache is not None else 0)

    def _reserve(self, num_blocks: int) -> List[int]:
        """Reserve device blocks, reclaiming unpinned prefix-cache blocks
        on demand when the free list alone can't cover the request —
        cached-but-unreferenced pages are capacity, not occupancy."""
        if self.prefix_cache is not None and \
                num_blocks > self.kv.free_blocks:
            self.evict_prefix_blocks(num_blocks - self.kv.free_blocks)
        return self.kv.reserve(num_blocks)

    def evict_prefix_blocks(self, want: int) -> int:
        """Evict up to ``want`` unpinned cached blocks (LRU leaf-first)
        and release them to the allocator. Returns blocks actually
        freed. Called on-demand by reservation and by the serving tier's
        pressure policy (cache eviction ALWAYS precedes sequence
        demotion — see serving/kv_tier.plan_prefix_evictions)."""
        if self.prefix_cache is None or want <= 0:
            return 0
        blocks = self.prefix_cache.evict_blocks(
            self.prefix_cache.plan_evictions(want))
        if blocks:
            # refs == 0 by construction: no reader left, a plain release
            # (with its scale reset) is exactly right
            self.kv.release(blocks)
        return len(blocks)

    def _ensure_blocks(self, seq: SequenceDescriptor, up_to_tokens: int):
        need = self.kv.blocks_needed(up_to_tokens) - len(seq.blocks)
        if need > 0:
            seq.blocks.extend(self._reserve(need))

    def _block_table(self, seq: SequenceDescriptor, bucket_blocks: int) -> np.ndarray:
        trash = self.kv.cfg.num_blocks - 1
        table = np.full((bucket_blocks,), trash, dtype=np.int32)
        n = min(len(seq.blocks), bucket_blocks)
        table[:n] = seq.blocks[:n]
        return table

    def _ctx_bucket_blocks(self, tokens: int) -> int:
        blocks = self.kv.blocks_needed(max(tokens, 1))
        return snap_bucket(blocks, self.config.ctx_block_buckets)

    # ------------------------------------------------------------------
    # the step
    # ------------------------------------------------------------------
    def put(self, batch_uids: Sequence[int], batch_tokens: Sequence[Sequence[int]],
            do_checks: bool = True) -> Dict[int, int]:
        """Admit new/continued sequences and run ONE engine step
        (reference: engine_v2.put engine_v2.py:107). Returns {uid: next_token}
        for every sequence that produced a token this step."""
        if do_checks and not self.can_schedule(
                batch_uids, [len(t) for t in batch_tokens]):
            raise RuntimeError("cannot schedule batch: out of KV blocks or slots")
        for uid, toks in zip(batch_uids, batch_tokens):
            if uid in self.state:
                seq = self.state.get(uid)
                seq.prompt_tokens = np.concatenate(
                    [seq.prompt_tokens, np.asarray(toks, np.int32)])
                seq.done = False
                self._prefill_total += len(toks)
            else:
                self._prefix_admit(self.state.create(uid, toks))
        return self.step()

    def _prefix_admit(self, seq: SequenceDescriptor) -> int:
        """Prefix-cache admission for a freshly created sequence: pin the
        longest cached full-block prefix of its prompt, seed its block
        table with the shared pages, and mark that prefix as already
        seen — prefill then covers only the novel suffix. Returns the
        reused token count. Pure bookkeeping; no page moves."""
        self._prefill_total += len(seq.prompt_tokens)
        if self.prefix_cache is None:
            return 0
        blocks, matched = self.prefix_cache.admit_match(
            seq.uid, seq.prompt_tokens)
        if matched:
            seq.blocks = list(blocks)
            seq.seen_tokens = matched
            self._prefill_saved += matched
        return matched

    def step(self) -> Dict[int, int]:
        cap = self.config.scheduler.prefill_chunk_tokens
        plan = plan_step(self.state.decoding(), self.state.prefilling(),
                         self.config.scheduler,
                         block_tokens=self.kv.cfg.block_size)
        out: Dict[int, int] = {}
        # scaled fp8 pages carry their per-(head, page) scales through the
        # jitted steps as a (pages, scales) tuple
        cache = self.kv.data if self.kv.scales is None else \
            (self.kv.data, self.kv.scales)
        tracer = get_tracer()
        t_prefill = t_decode = 0.0
        t0 = time.monotonic()

        # --- prefill chunks (SplitFuse) ---
        for chunk in plan.prefill_chunks:
            seq = chunk.seq
            end = chunk.start + chunk.length
            self._ensure_blocks(seq, end)
            bucket = chunk.bucket
            tokens = np.zeros((bucket,), np.int32)
            tokens[:chunk.length] = seq.prompt_tokens[chunk.start:end]
            mb = self._ctx_bucket_blocks(end)
            table = self._block_table(seq, mb)
            t_chunk = time.monotonic()
            logits, cache = prefill_chunk_g(
                self.params, cache, jnp.asarray(tokens), chunk.start,
                jnp.asarray(table), chunk.length,
                policy=self.policy, cfg=self.model_config,
                block_size=self.kv.cfg.block_size,
                attn_impl=self.config.attn_impl)
            if cap > 0:
                # per-chunk sub-span (nested inside serve/step_prefill, same
                # exclusive stage) — only with chunking on, so cap-off trace
                # streams stay bit-identical to pre-cap serving
                tracer.complete("serve/prefill_chunk",
                                time.monotonic() - t_chunk, cat="serve",
                                uid=seq.uid, tokens=chunk.length,
                                bucket=chunk.bucket)
            seq.seen_tokens = end
            self._prefill_computed += chunk.length
            if self.prefix_cache is not None:
                # register the freshly materialized FULL prompt blocks so
                # concurrent arrivals with the same prefix reuse them
                # (pinned for this sequence's lifetime — the pin is what
                # keeps a shared page safe from release/demotion)
                self.prefix_cache.insert_from_seq(
                    seq.uid, seq.prompt_tokens, seq.blocks,
                    min(seq.seen_tokens, len(seq.prompt_tokens)))
            if not seq.in_prefill:
                tok = int(self._sample_batch(logits[None])[0])
                seq.generated.append(tok)
                out[seq.uid] = tok
        if plan.prefill_chunks:
            t_prefill = time.monotonic() - t0
            tracer.complete("serve/step_prefill", t_prefill, cat="serve",
                            chunks=len(plan.prefill_chunks))

        # --- decode batch ---
        t0 = time.monotonic()
        if plan.decode_seqs:
            seqs = plan.decode_seqs
            b = snap_bucket(len(seqs), self.config.decode_batch_buckets)
            max_ctx = max(s.total_tokens for s in seqs)
            mb = self._ctx_bucket_blocks(max_ctx)
            tokens = np.zeros((b,), np.int32)
            positions = np.zeros((b,), np.int32)
            valid = np.zeros((b,), bool)
            for j, seq in enumerate(seqs):
                self._ensure_blocks(seq, seq.total_tokens)
                tokens[j] = seq.generated[-1] if seq.generated else \
                    seq.prompt_tokens[-1]
                positions[j] = seq.total_tokens - 1
                valid[j] = True
            # signature covers the actual block ids: uid reuse after flush()
            # can hand a same-shaped batch different pages
            sig = (b, mb, tuple(tuple(s.blocks) for s in seqs))
            if sig != self._table_sig:
                tables = np.full((b, mb), self.kv.cfg.num_blocks - 1, np.int32)
                for j, seq in enumerate(seqs):
                    tables[j] = self._block_table(seq, mb)
                self._dev_tables = jnp.asarray(tables)
                self._table_sig = sig
            logits, cache = decode_step_g(
                self.params, cache, jnp.asarray(tokens), jnp.asarray(positions),
                self._dev_tables, jnp.asarray(valid),
                policy=self.policy, cfg=self.model_config,
                block_size=self.kv.cfg.block_size,
                attn_impl=self.config.attn_impl)
            # sample on device; only [B] token ids cross to the host — the
            # [B, vocab] logits D2H fetch is the decode-loop bottleneck on
            # tunneled / multi-host topologies
            toks = self._sample_batch(logits)
            for j, seq in enumerate(seqs):
                tok = int(toks[j])
                seq.seen_tokens = seq.total_tokens
                seq.generated.append(tok)
                out[seq.uid] = tok
                if self.config.eos_token_id is not None and \
                        tok == self.config.eos_token_id:
                    seq.done = True
            t_decode = time.monotonic() - t0
            tracer.complete("serve/step_decode", t_decode, cat="serve",
                            batch=len(plan.decode_seqs))

        if self.kv.scales is None:
            self.kv.data = cache
        else:
            self.kv.data, self.kv.scales = cache
        # the serve tick's stage clocks read these (serve/tick_stage_share
        # gauges + `dstpu plan --serve` prefill/decode attribution)
        self.last_step_timing = {"prefill_s": t_prefill,
                                 "decode_s": t_decode}
        prefill_tokens = sum(c.length for c in plan.prefill_chunks)
        decode_tokens = len(plan.decode_seqs)
        self.last_step_counters = {"prefill_tokens": prefill_tokens,
                                   "chunks": len(plan.prefill_chunks),
                                   "decode_tokens": decode_tokens}
        if not plan.empty:
            self.sched_ledger.observe_tick(prefill_tokens,
                                           len(plan.prefill_chunks),
                                           decode_tokens, cap=cap)
        return out

    def _sample_batch(self, logits) -> np.ndarray:
        """[B, V] device logits -> [B] host token ids (one small D2H)."""
        self._rng, key = jax.random.split(self._rng)
        return np.asarray(sample_tokens(logits, key, self.config.sampling))

    # ------------------------------------------------------------------
    # lifecycle (reference: engine_v2.flush)
    # ------------------------------------------------------------------
    def flush(self, uid: int) -> List[int]:
        """Release a sequence's KV blocks (both tiers); returns its
        generated tokens. With the prefix cache on, full blocks covering
        the materialized prompt+generated history are ABSORBED into the
        trie instead of freed (refcount 0, evictable) — the multi-turn
        win: the next turn's prompt starts with exactly these tokens —
        and blocks the cache owns are excluded from the allocator
        release (pinned pages additionally excluded from the fp8 scale
        reset inside ``BlockedKVCache.release``)."""
        seq = self.state.pop(uid)
        if seq.in_prefill:
            # cancelled mid-prefill: the never-computed remainder leaves
            # the conservation identity (saved + computed == total) exact
            self._prefill_total -= max(
                len(seq.prompt_tokens) - seq.seen_tokens, 0)
        if self.prefix_cache is not None:
            history = np.concatenate(
                [seq.prompt_tokens,
                 np.asarray(seq.generated, np.int32)]) if seq.generated \
                else seq.prompt_tokens
            self.prefix_cache.insert_from_seq(
                uid, history, seq.blocks, seq.seen_tokens, pin=False)
            self.prefix_cache.release_seq(uid)
            cache = self.prefix_cache
            # cache-owned blocks (pinned OR retained at refs 0) are
            # excluded outright — the owns() partition is what protects
            # shared pages and their fp8 scales here; release(pinned=)
            # remains the contract for callers without a partition
            self.kv.release([b for b in seq.blocks if not cache.owns(b)])
        else:
            self.kv.release(seq.blocks)
        self.host_kv.pop(uid)     # no-op unless the sequence was demoted
        return seq.generated

    # ------------------------------------------------------------------
    # host KV offload tier (serving demotion/promotion; kv_offload.py)
    # ------------------------------------------------------------------
    def demote_kv(self, uid: int, quantize: str = "none") -> int:
        """Spill a sequence's KV pages to host RAM and release its device
        blocks; the sequence pauses (invisible to the step planner) until
        ``promote_kv``. Returns host bytes now held for it (0 when the uid
        is unknown or already demoted). A deliberate device->host copy —
        called from the serving tier policy, never from the jitted step.

        ``quantize`` selects the host-tier page codec ("none"/"int8"/
        "fp8", the serving group's ``host_kv_quantize``): the gathered
        pages are stored narrow with per-page fp32 scales, roughly
        doubling-to-quadrupling the host budget's effective blocks.
        Device-fp8 pages are never re-quantized (their scales already
        ride along; the round-trip stays bit-identical).

        Prefix-cache composition: pages the cache owns are NOT discarded
        with the sequence — this reader's pins drop, but the pages stay
        on device for the surviving readers (or evictable at refcount 0)
        AND travel to the host tier inside this entry, so promotion is
        self-sufficient even if the cached copies get evicted meanwhile."""
        seq = self.state.get(uid)
        if seq is None or seq.paused or seq.done:
            # a done sequence is about to be reaped — gathering its pages
            # would be a pure wasted device->host copy
            return 0
        if seq.blocks:
            data, scales = self.kv.gather_blocks(seq.blocks)
        else:
            data, scales = None, None
        codec = "none"
        qscales = None
        raw = (int(data.nbytes) if data is not None else 0) + \
              (int(scales.nbytes) if scales is not None else 0)
        if data is not None and quantize != "none" and \
                self.kv.cfg.dtype != jnp.float8_e4m3fn:
            data, qscales = quantize_pages(data, quantize)
            codec = quantize
        entry = HostKVEntry(blocks=len(seq.blocks), data=data, scales=scales,
                            seen_tokens=seq.seen_tokens, codec=codec,
                            qscales=qscales, raw_nbytes=raw)
        self.host_kv.put(uid, entry)
        if self.prefix_cache is not None:
            self.prefix_cache.release_seq(uid)
            cache = self.prefix_cache
            self.kv.release([b for b in seq.blocks if not cache.owns(b)])
        else:
            self.kv.release(seq.blocks)
        seq.blocks = []
        seq.paused = True
        self._table_sig = None    # decode tables must rebuild
        return entry.nbytes

    def promote_kv(self, uid: int) -> Optional[int]:
        """Bring a demoted sequence back: reserve (possibly different)
        device blocks, scatter its host pages in, resume scheduling.
        Returns the bytes restored, or None when the uid is unknown or the
        device has too few free blocks right now."""
        seq = self.state.get(uid)
        entry = self.host_kv.get(uid)
        if seq is None or entry is None or seq.done:
            # a done sequence is about to be reaped (flush drops the host
            # entry) — restoring its pages would be a wasted copy
            return None
        if entry.blocks > self.kv.free_blocks + self._evictable_blocks():
            return None
        blocks = self._reserve(entry.blocks)
        if entry.blocks:
            # quantized entries dequantize back to the device page width
            # here (tolerance-bounded); full-width entries scatter
            # verbatim (bit-identical round-trip)
            data = dequantize_pages(entry.data, entry.qscales, entry.codec,
                                    np.dtype(np.float32)
                                    if entry.codec != "none"
                                    else entry.data.dtype)
            self.kv.scatter_blocks(blocks, data, entry.scales)
        seq.blocks = list(blocks)
        seq.paused = False
        self.host_kv.pop(uid, promoted=True)
        self._table_sig = None
        return entry.nbytes

    def adopt_kv_handoff(self, uid: int, prompt_tokens: Sequence[int],
                         generated: Sequence[int],
                         entry: HostKVEntry) -> bool:
        """In-process disaggregation adoption (serving/disagg.py): continue
        a sequence whose KV a prefill-role engine demoted into a
        ``HostKVEntry`` — create it here with its history, reserve device
        blocks, scatter the dequantized pages, and let the planner pick it
        up as a running decode. Prefix admission is bypassed: the prefill
        work was done (and conservation-counted) on the donor engine.
        Returns False with NOTHING mutated when this engine can't cover
        the entry right now (capacity / slots / uid collision) — the
        caller retries next tick. The PR 17 handoff-file path generalized
        to in-process adoption: same codec round-trip, no filesystem."""
        if uid in self.state or \
                len(self.state) >= self.state.max_tracked_sequences or \
                entry.blocks > self.kv.free_blocks + self._evictable_blocks():
            return False
        seq = self.state.create(uid, prompt_tokens)
        seq.generated = list(generated)
        blocks = self._reserve(entry.blocks)
        if entry.blocks:
            data = dequantize_pages(entry.data, entry.qscales, entry.codec,
                                    np.dtype(np.float32)
                                    if entry.codec != "none"
                                    else entry.data.dtype)
            self.kv.scatter_blocks(blocks, data, entry.scales)
        seq.blocks = list(blocks)
        seq.seen_tokens = int(entry.seen_tokens)
        self._table_sig = None
        return True

    # ------------------------------------------------------------------
    # fleet prefix handoff (drain-time export / adopt-time import)
    # ------------------------------------------------------------------
    def export_prefix_handoff(self, path: str,
                              quantize: str = "none") -> Dict[str, int]:
        """Serialize every cached prefix chain to ``path`` (npz): for each
        root-to-leaf trie chain, the token key plus its KV pages gathered
        from the device and stored through the host-tier codec
        (``quantize``: "none"/"int8"/"fp8" — the same ``quantize_pages``
        path demotion uses; device-fp8 pages are never re-quantized).
        This is a retiring fleet replica's warm-cache handoff: its
        successor adopts the file and the shared prefixes survive the
        retirement instead of being recomputed fleet-wide. A deliberate
        device->host gather — drain-time only, never on the serve tick."""
        cache = self.prefix_cache
        out = {"chains": 0, "blocks": 0, "stored_bytes": 0, "raw_bytes": 0}
        payload: Dict[str, np.ndarray] = {}
        for tokens, blocks in (cache.chains() if cache is not None else ()):
            data, scales = self.kv.gather_blocks(list(blocks))
            raw = int(data.nbytes) + (int(scales.nbytes)
                                      if scales is not None else 0)
            codec, qscales = "none", None
            if quantize != "none" and self.kv.cfg.dtype != jnp.float8_e4m3fn:
                data, qscales = quantize_pages(data, quantize)
                codec = quantize
            entry = HostKVEntry(blocks=len(blocks), data=data, scales=scales,
                                seen_tokens=len(tokens), codec=codec,
                                qscales=qscales, raw_nbytes=raw)
            i = out["chains"]
            payload[f"tokens_{i}"] = np.asarray(tokens, np.int32)
            payload[f"data_{i}"] = entry.data
            payload[f"codec_{i}"] = np.array(entry.codec)
            if entry.scales is not None:
                payload[f"scales_{i}"] = entry.scales
            if entry.qscales is not None:
                payload[f"qscales_{i}"] = entry.qscales
            out["chains"] += 1
            out["blocks"] += entry.blocks
            out["stored_bytes"] += entry.nbytes
            out["raw_bytes"] += raw
        payload["block_size"] = np.asarray(self.config.kv_block_size,
                                           np.int32)
        payload["num_chains"] = np.asarray(out["chains"], np.int32)
        with open(path, "wb") as f:
            np.savez(f, **payload)
        return out

    def import_prefix_handoff(self, path: str) -> Dict[str, int]:
        """Adopt a predecessor's exported prefix chains: reserve device
        blocks, scatter the dequantized pages, and register each chain in
        the trie as EVICTABLE nodes (refcount 0 — a warm start, not a
        pin: pressure can reclaim them like any flush-absorbed prefix).
        Chains that don't fit the free pool, mismatch the block geometry,
        or collide with incumbents are skipped/trimmed and counted —
        adoption is best-effort by design. Deliberate host->device
        copies — wire it through ``InferenceServer.adopt_prefix_handoff``
        so the serve-loop thread (the engine's owner) runs it between
        ticks."""
        out = {"chains": 0, "blocks": 0, "skipped": 0, "bytes": 0}
        cache = self.prefix_cache
        with np.load(path, allow_pickle=False) as z:
            n = int(z["num_chains"]) if "num_chains" in z else 0
            bs = int(z["block_size"]) if "block_size" in z else -1
            for i in range(n):
                tokens = [int(t) for t in z[f"tokens_{i}"]]
                stored = z[f"data_{i}"]
                codec = str(z[f"codec_{i}"])
                scales = z[f"scales_{i}"] if f"scales_{i}" in z.files else None
                qscales = (z[f"qscales_{i}"] if f"qscales_{i}" in z.files
                           else None)
                nb = int(stored.shape[3])
                if (cache is None or bs != self.config.kv_block_size
                        or nb > self.kv.free_blocks):
                    out["skipped"] += 1
                    continue
                blocks = self.kv.reserve(nb)
                try:
                    data = dequantize_pages(stored, qscales, codec,
                                            np.dtype(np.float32)
                                            if codec != "none"
                                            else stored.dtype)
                    self.kv.scatter_blocks(blocks, data, scales)
                except Exception:
                    # geometry mismatch (different model/layout): give the
                    # reservation back and skip — adoption must never
                    # poison a healthy successor
                    self.kv.release(blocks)
                    out["skipped"] += 1
                    continue
                added = cache.insert_from_seq(0, tokens, blocks,
                                              seen_tokens=len(tokens),
                                              pin=False)
                # chain prefixes the trie already held keep the incumbent
                # pages (first writer wins); unclaimed reservations go
                # straight back to the allocator via the owns() partition
                self.kv.release([b for b in blocks if not cache.owns(b)])
                out["chains"] += 1
                out["blocks"] += added
                out["bytes"] += int(stored.nbytes)
        return out

    def demoted_uids(self) -> List[int]:
        """Demotion-ordered uids currently in the host tier."""
        return self.host_kv.uids()

    def demoted_blocks(self, uid: int) -> int:
        """Device blocks a demoted sequence will need back at promotion."""
        entry = self.host_kv.get(uid)
        return entry.blocks if entry is not None else 0

    def kv_held_blocks(self, uid: int) -> int:
        """Device blocks a sequence holds right now (0 when demoted)."""
        seq = self.state.get(uid)
        return len(seq.blocks) if seq is not None else 0

    def host_kv_bytes(self) -> int:
        return self.host_kv.total_bytes

    def kv_ledger(self) -> Dict[str, int]:
        """Both tiers' occupancy in one dict — the serving drain test's
        "ledger returns to zero" surface and the bench_serve proof.
        ``device_blocks_reserved`` excludes prefix-cache-held blocks
        (reported separately as ``prefix_cached_blocks``): a drained
        server legitimately keeps a warm cache, and the drain invariant
        is "no SEQUENCE holds blocks", not "the cache is cold"."""
        cached = (self.prefix_cache.cached_blocks()
                  if self.prefix_cache is not None else 0)
        return {
            "device_blocks_reserved": self.kv_reserved_blocks() - cached,
            "device_block_bytes": self.kv_block_bytes(),
            "prefix_cached_blocks": cached,
            "host_entries": len(self.host_kv),
            "host_bytes": self.host_kv.total_bytes,
            "host_raw_bytes": self.host_kv.raw_bytes,
            "demotions": self.host_kv.demotions,
            "promotions": self.host_kv.promotions,
            "demoted_bytes": self.host_kv.demoted_bytes,
            "promoted_bytes": self.host_kv.promoted_bytes,
            "demoted_raw_bytes": self.host_kv.demoted_raw_bytes,
            "host_compression_ratio": self.host_kv.compression_ratio(),
        }

    # ------------------------------------------------------------------
    # prefix cache surface (serving gauges + bench_serve proof set)
    # ------------------------------------------------------------------
    def resident_tokens(self) -> int:
        """Tokens whose KV is resident in EITHER tier right now — the
        denominator of bytes-per-resident-token. Host int arithmetic."""
        total = 0
        for s in self.state.all():
            if not s.paused:
                total += s.seen_tokens
        for u in self.host_kv.uids():
            entry = self.host_kv.get(u)
            if entry is not None:
                total += entry.seen_tokens
        return total

    def kv_resident_bytes(self) -> int:
        """Bytes holding resident KV across both tiers (device blocks at
        block-byte width + host entries at stored width)."""
        return (self.kv_reserved_blocks() * self.kv_block_bytes()
                + self.host_kv.total_bytes)

    def prefix_stats(self) -> Dict[str, float]:
        """Prefix-cache counters + the prefill-work conservation triple:
        ``prefill_tokens_saved + prefill_tokens_computed ==
        prefill_tokens_total`` holds exactly once every admitted
        sequence has either finished prefill or been flushed."""
        out: Dict[str, float] = {
            "prefill_tokens_total": self._prefill_total,
            "prefill_tokens_saved": self._prefill_saved,
            "prefill_tokens_computed": self._prefill_computed,
        }
        if self.prefix_cache is not None:
            for k, v in self.prefix_cache.snapshot().items():
                out[f"prefix_{k}"] = v
            looked = max(self.prefix_cache.stats.lookup_tokens, 1)
            out["prefix_hit_ratio"] = \
                self.prefix_cache.stats.hit_tokens / looked
        return out

    # ------------------------------------------------------------------
    # serving hooks (consumed by deepspeed_tpu/serving: the serve loop
    # admits without stepping, steps in its own cadence, and reaps
    # finished sequences between steps)
    # ------------------------------------------------------------------
    def admit(self, uid: int, prompt_tokens: Sequence[int]) -> SequenceDescriptor:
        """Admission-only: create sequence state WITHOUT running a step.
        ``put`` couples admission to stepping; a serving loop needs them
        apart so a burst of arrivals lands in one SplitFuse plan."""
        if not self.can_schedule([uid], [len(prompt_tokens)]):
            raise RuntimeError(
                "cannot admit: out of KV blocks or sequence slots")
        seq = self.state.create(uid, prompt_tokens)
        self._prefix_admit(seq)
        return seq

    def finish(self, uid: int) -> None:
        """Mark a sequence done (length limit / cancel) so the scheduler
        stops planning it; KV blocks are released at reap time."""
        seq = self.state.get(uid)
        if seq is not None:
            seq.done = True

    def finished_uids(self) -> List[int]:
        return [s.uid for s in self.state.all() if s.done]

    def reap_finished(self) -> Dict[int, List[int]]:
        """Flush every done sequence (releasing its KV blocks); returns
        {uid: generated_tokens} for the reaped set."""
        return {uid: self.flush(uid) for uid in self.finished_uids()}

    def has_work(self) -> bool:
        """Any sequence the next step plan could advance — demoted (paused)
        sequences don't count until the tier policy promotes them."""
        return any(not s.done and not s.paused for s in self.state.all())

    def kv_usable_blocks(self) -> int:
        """Blocks available to sequences (the last block is the permanent
        trash page for padding writes and never allocates)."""
        return self.kv.cfg.num_blocks - 1

    def kv_occupancy(self) -> float:
        """Fraction of usable KV cache blocks currently reserved (0..1)."""
        usable = self.kv_usable_blocks()
        return (usable - self.kv.free_blocks) / max(usable, 1)

    def kv_reserved_blocks(self) -> int:
        """Blocks currently reserved by live sequences — the *observed*
        side of the serving layer's projected-vs-observed reconciliation."""
        return self.kv_usable_blocks() - self.kv.free_blocks

    def kv_block_bytes(self) -> int:
        """Device bytes per KV block across all layers/heads (metadata
        arithmetic on the cache array — never a transfer): the conversion
        the serving gauges use to state occupancy in bytes instead of
        blocks."""
        nbytes = int(getattr(self.kv.data, "nbytes", 0))
        if self.kv.scales is not None:
            nbytes += int(getattr(self.kv.scales, "nbytes", 0))
        return nbytes // max(self.kv.cfg.num_blocks, 1)

    def generate(self, prompt_tokens: Sequence[int], max_new_tokens: int = 32,
                 uid: int = 0) -> List[int]:
        """Convenience serial generation loop over the continuous-batching
        step; with ``speculative_k > 0`` each step verifies a prompt-lookup
        proposal in one chunk forward (1..k+1 tokens/step, exact greedy)."""
        self.put([uid], [list(prompt_tokens)])
        seq = self.state.get(uid)
        while len(seq.generated) < max_new_tokens and not seq.done:
            if self.config.speculative_k > 0 and not seq.in_prefill:
                self._speculative_step(seq)
            else:
                self.step()
        # a fully-accepted verify step can overshoot the budget by up to k
        return self.flush(uid)[:max_new_tokens]

    # ------------------------------------------------------------------
    # speculative decoding (draft-free prompt-lookup; no reference analog)
    # ------------------------------------------------------------------
    def _propose(self, seq: SequenceDescriptor) -> List[int]:
        """Prompt-lookup proposal: the k tokens that followed the previous
        occurrence of the context's trailing n-gram (exact match, most
        recent occurrence wins). Empty when the tail never repeats."""
        k, n = self.config.speculative_k, self.config.speculative_ngram
        ctx = np.concatenate([seq.prompt_tokens,
                              np.asarray(seq.generated, np.int32)])
        if len(ctx) < n + 1:
            return []
        tail = ctx[-n:]
        # vectorized scan over earlier n-gram positions; windows over
        # ctx[:-1] exclude the tail itself, so any hit has a nonempty
        # continuation — take the most recent
        windows = np.lib.stride_tricks.sliding_window_view(ctx[:-1], n)
        hits = np.flatnonzero((windows == tail).all(axis=1))
        if not hits.size:
            return []
        i = int(hits[-1])
        return [int(t) for t in ctx[i + n:i + n + k]]

    def _speculative_step(self, seq: SequenceDescriptor) -> None:
        """Verify [last_token, p1..pk] in one chunk forward: row i's argmax
        predicts position ctx+i, so accept p_{i+1} while it matches, then
        emit the first mismatch's argmax as the bonus/corrected token.
        Rejected rows' stale K/V sits beyond the accepted context (invisible
        under causal masking) and is overwritten by the next step. fp8
        caveat: a rejected row's K/V can still GROW its page's scale
        (monotone until release) — a precision effect on that page, same as
        any outlier write, not a correctness hole."""
        if not self.config.greedy:
            raise ValueError("speculative decoding is greedy-only: "
                             "proposal acceptance compares argmax chains")
        proposed = self._propose(seq)[:31]   # bucket ladder caps rows at 32
        if not proposed:
            # no lookup hit: the 1-row decode path is ~bucket x cheaper than
            # an empty verify chunk
            self.step()
            return
        last = seq.generated[-1] if seq.generated else \
            int(seq.prompt_tokens[-1])
        ctx = seq.total_tokens                    # last's position is ctx-1
        true_len = 1 + len(proposed)
        bucket = snap_bucket(true_len, (8, 16, 32))
        self._ensure_blocks(seq, ctx + true_len)
        mb = self._ctx_bucket_blocks(ctx + true_len)
        tokens = np.zeros((bucket,), np.int32)
        tokens[0] = last
        tokens[1:true_len] = proposed
        cache = self.kv.data if self.kv.scales is None else \
            (self.kv.data, self.kv.scales)
        logits, cache = verify_chunk_g(
            self.params, cache, jnp.asarray(tokens), ctx - 1,
            jnp.asarray(self._block_table(seq, mb)), true_len,
            policy=self.policy, cfg=self.model_config,
            block_size=self.kv.cfg.block_size,
            attn_impl=self.config.attn_impl)
        if self.kv.scales is None:
            self.kv.data = cache
        else:
            self.kv.data, self.kv.scales = cache
        preds = np.asarray(jnp.argmax(logits[:true_len], axis=-1))
        emitted = []
        for i, p in enumerate(proposed):
            if int(preds[i]) == p:
                emitted.append(p)               # accepted proposal token
            else:
                break
        emitted.append(int(preds[len(emitted)]))  # bonus / corrected token
        appended = 0
        for tok in emitted:
            seq.generated.append(tok)
            appended += 1
            if self.config.eos_token_id is not None and \
                    tok == self.config.eos_token_id:
                seq.done = True
                break
        # count what actually landed (EOS may truncate the step); the last
        # entry of `emitted` is the bonus token, the rest were proposals
        self._spec_proposed += len(proposed)
        self._spec_accepted += min(appended, len(emitted) - 1)
        self._spec_steps += 1
        seq.seen_tokens = seq.total_tokens - 1    # last emitted has no KV yet

    def speculative_stats(self) -> Dict[str, float]:
        """{steps, proposed, accepted, tokens_per_step} over this engine's
        speculative steps (acceptance rate drives the speedup)."""
        return {"steps": self._spec_steps, "proposed": self._spec_proposed,
                "accepted": self._spec_accepted,
                "tokens_per_step": (self._spec_accepted + self._spec_steps)
                / max(self._spec_steps, 1)}
