"""Inference v2 module system: per-arch decode policies + registry + heuristics.

Reference analog: ``deepspeed/inference/v2/modules/`` (pluggable layer
implementations behind interfaces + ``module_registry.py`` + ``heuristics.py:36``)
and ``model_implementations/{llama_v2,mistral,mixtral,opt,phi3,qwen_v2,falcon}``.

TPU shape: a *policy* is a small class of pure static methods over the training
model's param pytree — no module surgery, no containers. The generic paged
serving loop (``generic_decode.py``) owns the KV cache, block tables, and the
Pallas paged-attention call; the policy contributes exactly the three
arch-specific pieces:

- ``embed(params, tokens, positions, cfg)``          -> [N, D] hidden states
- ``block(params, i, x, attend, positions, cfg)``    -> [N, D] (one layer;
  calls ``attend(q, k, v)`` for cache write + paged attention)
- ``unembed(params, x, cfg)``                        -> [N, V] fp32 logits

plus ``cache_spec(cfg)`` so the engine can size the paged KV pool. Policies are
keyed both by name and by config dataclass type; ``policy_for`` is the
heuristic (reference heuristics.py) that picks the implementation for a model
config. mistral/qwen2/phi3 are LlamaConfig variants and route to LlamaPolicy.
"""

import dataclasses
import functools
from typing import Any, Callable, Dict, Type

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.v2.llama_decode import _mlp, _qkv, _rms
from deepspeed_tpu.models.llama import LlamaConfig, rope_freqs

DECODE_POLICIES: Dict[str, type] = {}
_CONFIG_TO_POLICY: Dict[type, type] = {}


@dataclasses.dataclass(frozen=True)
class KVCacheSpec:
    num_layers: int
    num_kv_heads: int
    head_dim: int
    max_seq_len: int
    dtype: Any
    window: Any = None       # sliding-window width or None


def register_policy(name: str, config_type: type):
    """Register a decode policy under ``name`` and for ``config_type``
    (reference: module_registry.py)."""
    def deco(cls):
        DECODE_POLICIES[name] = cls
        _CONFIG_TO_POLICY[config_type] = cls
        cls.arch = name
        return cls
    return deco


def policy_for(model_config) -> type:
    """Heuristic: map a model config to its decode policy (reference:
    heuristics.py:36). LlamaConfig covers llama/mistral/qwen2/phi3."""
    cls = _CONFIG_TO_POLICY.get(type(model_config))
    if cls is None:
        raise ValueError(
            f"no decode policy registered for {type(model_config).__name__}; "
            f"known: {sorted(DECODE_POLICIES)}")
    return cls


def _layernorm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), -1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


def _rope_tables(head_dim, max_seq_len, theta):
    """Rope tables as trace-local jnp constants. The numpy compute is cached in
    ``rope_freqs`` (identical ndarray objects across layers → XLA CSEs the
    constants); the jnp conversion must NOT be cached — a jnp array created
    under one jit trace is a tracer and may not leak into the next trace."""
    cos, sin = rope_freqs(head_dim, max_seq_len, theta)
    return jnp.asarray(cos), jnp.asarray(sin)


def _rope_rows(x, cos, sin, positions):
    """x: [N, H, d]; positions: [N] — rotary on per-row absolute positions."""
    cos_p = cos[positions][:, None, :]
    sin_p = sin[positions][:, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos_p - x2 * sin_p, x2 * cos_p + x1 * sin_p], -1)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# Llama family (llama / mistral / qwen2 / phi3)
# ---------------------------------------------------------------------------
@register_policy("llama", LlamaConfig)
class LlamaPolicy:
    """reference: model_implementations/llama_v2 (+ mistral/qwen_v2/phi3 —
    LlamaConfig knobs: sliding_window, attention_bias, fused mappers)."""

    @staticmethod
    def cache_spec(cfg: LlamaConfig) -> KVCacheSpec:
        return KVCacheSpec(cfg.num_layers, cfg.num_kv_heads, cfg.head_dim_,
                           cfg.max_seq_len, cfg.dtype, cfg.sliding_window)

    @staticmethod
    def _norm_scale(scale, cfg):
        # gemma stores norm weights as an offset from 1 (rms_scale_offset)
        return scale + 1.0 if cfg.rms_scale_offset else scale

    @staticmethod
    def embed(params, tokens, positions, cfg):
        x = params["model"]["embed"]["embedding"].astype(cfg.dtype)[tokens]
        if cfg.scale_embeddings:   # gemma normalizer
            x = x * jnp.sqrt(jnp.asarray(cfg.hidden_size,
                                         jnp.float32)).astype(x.dtype)
        return x

    @staticmethod
    def block(params, i, x, attend, positions, cfg):
        lp = params["model"][f"layer_{i}"]
        dtype = cfg.dtype
        ns = LlamaPolicy._norm_scale
        cos, sin = _rope_tables(cfg.head_dim_, cfg.max_seq_len, cfg.rope_theta)
        h = _rms(x, ns(lp["attn_norm"]["scale"], cfg), cfg.rms_norm_eps)
        q, k, v = _qkv(lp, h, dtype)
        q = _rope_rows(q, cos, sin, positions)
        k = _rope_rows(k, cos, sin, positions)
        attn = attend(q, k, v)
        x = x + jnp.einsum("thk,hkd->td", attn,
                           lp["attn"]["wo"]["kernel"].astype(dtype))
        h2 = _rms(x, ns(lp["mlp_norm"]["scale"], cfg), cfg.rms_norm_eps)
        return x + _mlp(lp, h2, dtype, act=cfg.hidden_act)

    @staticmethod
    def unembed(params, x, cfg):
        x = _rms(x, LlamaPolicy._norm_scale(
            params["model"]["final_norm"]["scale"], cfg), cfg.rms_norm_eps)
        if cfg.tie_embeddings:
            logits = x.astype(jnp.float32) @ \
                params["model"]["embed"]["embedding"].astype(jnp.float32).T
        else:
            logits = x.astype(jnp.float32) @ \
                params["model"]["lm_head"]["kernel"].astype(jnp.float32)
        from deepspeed_tpu.models.llama import softcap_logits
        return softcap_logits(logits, cfg.logits_soft_cap)


# ---------------------------------------------------------------------------
# Falcon (parallel attn+mlp, LayerNorm, MQA/GQA)
# ---------------------------------------------------------------------------
from deepspeed_tpu.models.falcon import FalconConfig  # noqa: E402


@register_policy("falcon", FalconConfig)
class FalconPolicy:
    """reference: model_implementations/falcon."""

    @staticmethod
    def cache_spec(cfg: FalconConfig) -> KVCacheSpec:
        return KVCacheSpec(cfg.num_layers, cfg.num_kv_heads, cfg.head_dim_,
                           cfg.max_seq_len, cfg.dtype, None)

    @staticmethod
    def embed(params, tokens, positions, cfg):
        return params["model"]["embed"]["embedding"].astype(cfg.dtype)[tokens]

    @staticmethod
    def block(params, i, x, attend, positions, cfg):
        lp = params["model"][f"layer_{i}"]
        dtype = cfg.dtype
        eps = cfg.layer_norm_eps
        if cfg.new_decoder_architecture:
            h = _layernorm(x, lp["ln_attn"]["scale"], lp["ln_attn"]["bias"], eps)
            h_mlp = _layernorm(x, lp["ln_mlp"]["scale"], lp["ln_mlp"]["bias"], eps)
        else:
            h = _layernorm(x, lp["input_ln"]["scale"], lp["input_ln"]["bias"], eps)
            h_mlp = h
        q = jnp.einsum("td,dhk->thk", h, lp["wq"]["kernel"].astype(dtype))
        k = jnp.einsum("td,dhk->thk", h, lp["wk"]["kernel"].astype(dtype))
        v = jnp.einsum("td,dhk->thk", h, lp["wv"]["kernel"].astype(dtype))
        cos, sin = _rope_tables(cfg.head_dim_, cfg.max_seq_len, cfg.rope_theta)
        q = _rope_rows(q, cos, sin, positions)
        k = _rope_rows(k, cos, sin, positions)
        attn = attend(q, k, v)
        attn_out = jnp.einsum("thk,hkd->td", attn,
                              lp["wo"]["kernel"].astype(dtype))
        mlp = jax.nn.gelu(h_mlp @ lp["mlp_up"]["kernel"].astype(dtype))
        mlp_out = mlp @ lp["mlp_down"]["kernel"].astype(dtype)
        return x + attn_out + mlp_out        # parallel residual

    @staticmethod
    def unembed(params, x, cfg):
        m = params["model"]
        x = _layernorm(x, m["final_ln"]["scale"], m["final_ln"]["bias"],
                       cfg.layer_norm_eps)
        return x.astype(jnp.float32) @ \
            m["embed"]["embedding"].astype(jnp.float32).T   # tied


# ---------------------------------------------------------------------------
# OPT (learned positions, LayerNorm, ReLU MLP, biases everywhere, no rope)
# ---------------------------------------------------------------------------
from deepspeed_tpu.models.opt import OPT_POSITION_OFFSET, OPTConfig  # noqa: E402


@register_policy("opt", OPTConfig)
class OPTPolicy:
    """reference: model_implementations/opt."""

    @staticmethod
    def cache_spec(cfg: OPTConfig) -> KVCacheSpec:
        return KVCacheSpec(cfg.num_layers, cfg.num_heads, cfg.head_dim_,
                           cfg.max_seq_len, cfg.dtype, None)

    @staticmethod
    def embed(params, tokens, positions, cfg):
        m = params["model"]
        x = m["embed"]["embedding"].astype(cfg.dtype)[tokens]
        pos = m["pos_embed"][positions + OPT_POSITION_OFFSET].astype(cfg.dtype)
        return x + pos

    @staticmethod
    def block(params, i, x, attend, positions, cfg):
        lp = params["model"][f"layer_{i}"]
        dtype = cfg.dtype
        eps = cfg.layer_norm_eps
        h = _layernorm(x, lp["attn_ln"]["scale"], lp["attn_ln"]["bias"], eps)
        q = jnp.einsum("td,dhk->thk", h, lp["wq"]["kernel"].astype(dtype)) + \
            lp["wq"]["bias"].astype(dtype)
        k = jnp.einsum("td,dhk->thk", h, lp["wk"]["kernel"].astype(dtype)) + \
            lp["wk"]["bias"].astype(dtype)
        v = jnp.einsum("td,dhk->thk", h, lp["wv"]["kernel"].astype(dtype)) + \
            lp["wv"]["bias"].astype(dtype)
        attn = attend(q, k, v)               # no rope
        x = x + jnp.einsum("thk,hkd->td", attn,
                           lp["wo"]["kernel"].astype(dtype)) + \
            lp["wo"]["bias"].astype(dtype)
        h2 = _layernorm(x, lp["mlp_ln"]["scale"], lp["mlp_ln"]["bias"], eps)
        m = jax.nn.relu(h2 @ lp["fc1"]["kernel"].astype(dtype) +
                        lp["fc1"]["bias"].astype(dtype))
        return x + m @ lp["fc2"]["kernel"].astype(dtype) + \
            lp["fc2"]["bias"].astype(dtype)

    @staticmethod
    def unembed(params, x, cfg):
        m = params["model"]
        x = _layernorm(x, m["final_ln"]["scale"], m["final_ln"]["bias"],
                       cfg.layer_norm_eps)
        return x.astype(jnp.float32) @ \
            m["embed"]["embedding"].astype(jnp.float32).T   # tied


def _dense_moe_combine(moe, h2, top_k, dtype, norm_topk_prob=True):
    """Dense all-expert compute + top-k combine (serving-side MoE;
    equivalent to the training dispatch when no token drops). With
    ``norm_topk_prob`` the kept probs are renormalized to sum to 1
    (GShard/Mixtral); HF Qwen2-MoE runs with it off."""
    gate_logits = h2.astype(jnp.float32) @ moe["gate"]["wg"]["kernel"]
    probs = jax.nn.softmax(gate_logits, axis=-1)              # [T, E]
    topv, topi = jax.lax.top_k(probs, top_k)                  # [T, K]
    if norm_topk_prob:
        w = topv / jnp.maximum(jnp.sum(topv, -1, keepdims=True), 1e-9)
    else:
        w = topv
    ex = moe["experts"]
    g = jnp.einsum("td,edf->etf", h2, ex["w_gate"].astype(dtype))
    u = jnp.einsum("td,edf->etf", h2, ex["w_up"].astype(dtype))
    eo = jnp.einsum("etf,efd->etd", jax.nn.silu(g) * u,
                    ex["w_down"].astype(dtype))               # [E, T, D]
    t_idx = jnp.arange(h2.shape[0])[:, None]                  # [T, 1]
    picked = eo[topi, t_idx]                                  # [T, K, D]
    return jnp.einsum("tk,tkd->td", w.astype(dtype), picked)


# ---------------------------------------------------------------------------
# Mixtral (llama attention + top-k MoE MLP)
# ---------------------------------------------------------------------------
from deepspeed_tpu.models.mixtral import MixtralConfig  # noqa: E402


@register_policy("mixtral", MixtralConfig)
class MixtralPolicy:
    """reference: model_implementations/mixtral (+ qwen_v2_moe shape). Serving
    MoE runs all experts densely on the (small) token batch and combines the
    renormalized top-k gate weights — equivalent to the training dispatch when
    no token is dropped (eval capacity factor keeps that true at decode sizes).
    """

    @staticmethod
    def cache_spec(cfg: MixtralConfig) -> KVCacheSpec:
        b = cfg.base
        return KVCacheSpec(b.num_layers, b.num_kv_heads, b.head_dim_,
                           b.max_seq_len, b.dtype, b.sliding_window)

    @staticmethod
    def embed(params, tokens, positions, cfg):
        return params["embed"]["embedding"].astype(cfg.base.dtype)[tokens]

    @staticmethod
    def block(params, i, x, attend, positions, cfg):
        base = cfg.base
        dtype = base.dtype
        lp = params[f"layer_{i}"]
        cos, sin = _rope_tables(base.head_dim_, base.max_seq_len, base.rope_theta)
        h = _rms(x, lp["attn_norm"]["scale"], base.rms_norm_eps)
        q, k, v = _qkv({"attn": lp["attn"]}, h, dtype)
        q = _rope_rows(q, cos, sin, positions)
        k = _rope_rows(k, cos, sin, positions)
        attn = attend(q, k, v)
        x = x + jnp.einsum("thk,hkd->td", attn,
                           lp["attn"]["wo"]["kernel"].astype(dtype))
        h2 = _rms(x, lp["mlp_norm"]["scale"], base.rms_norm_eps)
        return x + _dense_moe_combine(lp["moe"], h2, cfg.moe.top_k, dtype,
                                      cfg.moe.norm_topk_prob)

    @staticmethod
    def unembed(params, x, cfg):
        x = _rms(x, params["final_norm"]["scale"], cfg.base.rms_norm_eps)
        return x.astype(jnp.float32) @ \
            params["lm_head"]["kernel"].astype(jnp.float32)


# ---------------------------------------------------------------------------
# BLOOM (ALiBi attention via head-dim augmentation, fused-qkv arch, LayerNorm)
# ---------------------------------------------------------------------------
from deepspeed_tpu.models.bloom import (  # noqa: E402
    BloomConfig, alibi_augment, alibi_slopes)


@register_policy("bloom", BloomConfig)
class BloomPolicy:
    """reference: the BLOOM container + alibi softmax kernel
    (``module_inject/containers/bloom.py``,
    ``csrc/transformer/inference/csrc/softmax.cu`` alibi variant). ALiBi rides
    in one augmented head-dim column (``models/bloom.py:alibi_augment``), so
    the KV cache stores head_dim+2 and the paged kernel runs unchanged."""

    @staticmethod
    def cache_spec(cfg: BloomConfig) -> KVCacheSpec:
        return KVCacheSpec(cfg.num_layers, cfg.num_heads, cfg.head_dim_ + 2,
                           cfg.max_seq_len, cfg.dtype, None)

    @staticmethod
    def embed(params, tokens, positions, cfg):
        m = params["model"]
        x = m["embed"]["embedding"].astype(cfg.dtype)[tokens]
        return _layernorm(x, m["embed_ln"]["scale"], m["embed_ln"]["bias"],
                          cfg.layer_norm_eps)

    @staticmethod
    def block(params, i, x, attend, positions, cfg):
        lp = params["model"][f"layer_{i}"]
        dtype = cfg.dtype
        eps = cfg.layer_norm_eps
        d = cfg.head_dim_
        h = _layernorm(x, lp["input_ln"]["scale"], lp["input_ln"]["bias"], eps)
        q = jnp.einsum("td,dhk->thk", h, lp["wq"]["kernel"].astype(dtype)) + \
            lp["wq"]["bias"].astype(dtype)
        k = jnp.einsum("td,dhk->thk", h, lp["wk"]["kernel"].astype(dtype)) + \
            lp["wk"]["bias"].astype(dtype)
        v = jnp.einsum("td,dhk->thk", h, lp["wv"]["kernel"].astype(dtype)) + \
            lp["wv"]["bias"].astype(dtype)
        slopes = jnp.asarray(alibi_slopes(cfg.num_heads))
        q, k, v = alibi_augment(q, k, v, slopes, positions)
        attn = attend(q, k, v)[..., :d]
        x = x + jnp.einsum("thk,hkd->td", attn,
                           lp["wo"]["kernel"].astype(dtype)) + \
            lp["wo"]["bias"].astype(dtype)
        h2 = _layernorm(x, lp["post_ln"]["scale"], lp["post_ln"]["bias"], eps)
        m = jax.nn.gelu(h2 @ lp["mlp_up"]["kernel"].astype(dtype) +
                        lp["mlp_up"]["bias"].astype(dtype))
        return x + m @ lp["mlp_down"]["kernel"].astype(dtype) + \
            lp["mlp_down"]["bias"].astype(dtype)

    @staticmethod
    def unembed(params, x, cfg):
        m = params["model"]
        x = _layernorm(x, m["final_ln"]["scale"], m["final_ln"]["bias"],
                       cfg.layer_norm_eps)
        return x.astype(jnp.float32) @ \
            m["embed"]["embedding"].astype(jnp.float32).T   # tied


# ---------------------------------------------------------------------------
# GPT-NeoX / GPT-J (partial rotary, parallel residual, untied embed_out head)
# ---------------------------------------------------------------------------
from deepspeed_tpu.models.gpt_neox import (  # noqa: E402
    GPTNeoXConfig, apply_partial_rotary)


@register_policy("gpt_neox", GPTNeoXConfig)
class GPTNeoXPolicy:
    """reference: gptneox/gptj containers (module_inject/containers)."""

    @staticmethod
    def cache_spec(cfg: GPTNeoXConfig) -> KVCacheSpec:
        return KVCacheSpec(cfg.num_layers, cfg.num_heads, cfg.head_dim_,
                           cfg.max_seq_len, cfg.dtype, None)

    @staticmethod
    def embed(params, tokens, positions, cfg):
        return params["model"]["embed"]["embedding"].astype(cfg.dtype)[tokens]

    @staticmethod
    def block(params, i, x, attend, positions, cfg):
        lp = params["model"][f"layer_{i}"]
        dtype = cfg.dtype
        eps = cfg.layer_norm_eps
        h = _layernorm(x, lp["input_ln"]["scale"], lp["input_ln"]["bias"], eps)
        q = jnp.einsum("td,dhk->thk", h, lp["wq"]["kernel"].astype(dtype)) + \
            lp["wq"]["bias"].astype(dtype)
        k = jnp.einsum("td,dhk->thk", h, lp["wk"]["kernel"].astype(dtype)) + \
            lp["wk"]["bias"].astype(dtype)
        v = jnp.einsum("td,dhk->thk", h, lp["wv"]["kernel"].astype(dtype)) + \
            lp["wv"]["bias"].astype(dtype)
        q = apply_partial_rotary(q, positions, cfg.rotary_dim_, cfg.rope_theta,
                                 cfg.max_seq_len)
        k = apply_partial_rotary(k, positions, cfg.rotary_dim_, cfg.rope_theta,
                                 cfg.max_seq_len)
        attn = attend(q, k, v)
        attn_out = jnp.einsum("thk,hkd->td", attn,
                              lp["wo"]["kernel"].astype(dtype)) + \
            lp["wo"]["bias"].astype(dtype)
        h2_src = x if cfg.parallel_residual else x + attn_out
        h2 = _layernorm(h2_src, lp["post_ln"]["scale"], lp["post_ln"]["bias"],
                        eps)
        m = jax.nn.gelu(h2 @ lp["mlp_up"]["kernel"].astype(dtype) +
                        lp["mlp_up"]["bias"].astype(dtype))
        mlp_out = m @ lp["mlp_down"]["kernel"].astype(dtype) + \
            lp["mlp_down"]["bias"].astype(dtype)
        return (x + attn_out + mlp_out) if cfg.parallel_residual \
            else h2_src + mlp_out

    @staticmethod
    def unembed(params, x, cfg):
        m = params["model"]
        x = _layernorm(x, m["final_ln"]["scale"], m["final_ln"]["bias"],
                       cfg.layer_norm_eps)
        return x.astype(jnp.float32) @ m["embed_out"].astype(jnp.float32)


# ---------------------------------------------------------------------------
# GPT-2 (learned positions, pre-LN, tied wte head)
# ---------------------------------------------------------------------------
from deepspeed_tpu.models.gpt2 import GPT2Config  # noqa: E402


@register_policy("gpt2", GPT2Config)
class GPT2Policy:
    """reference: HFGPT2LayerPolicy / megatron-gpt container."""

    @staticmethod
    def cache_spec(cfg: GPT2Config) -> KVCacheSpec:
        return KVCacheSpec(cfg.num_layers, cfg.num_heads, cfg.head_dim_,
                           cfg.max_seq_len, cfg.dtype, None)

    @staticmethod
    def embed(params, tokens, positions, cfg):
        m = params["model"]
        return m["embed"]["embedding"].astype(cfg.dtype)[tokens] + \
            m["pos_embed"][positions].astype(cfg.dtype)

    @staticmethod
    def block(params, i, x, attend, positions, cfg):
        lp = params["model"][f"layer_{i}"]
        dtype = cfg.dtype
        eps = cfg.layer_norm_eps
        h = _layernorm(x, lp["ln_1"]["scale"], lp["ln_1"]["bias"], eps)
        q = jnp.einsum("td,dhk->thk", h, lp["wq"]["kernel"].astype(dtype)) + \
            lp["wq"]["bias"].astype(dtype)
        k = jnp.einsum("td,dhk->thk", h, lp["wk"]["kernel"].astype(dtype)) + \
            lp["wk"]["bias"].astype(dtype)
        v = jnp.einsum("td,dhk->thk", h, lp["wv"]["kernel"].astype(dtype)) + \
            lp["wv"]["bias"].astype(dtype)
        attn = attend(q, k, v)               # no rope: positions are learned
        x = x + jnp.einsum("thk,hkd->td", attn,
                           lp["wo"]["kernel"].astype(dtype)) + \
            lp["wo"]["bias"].astype(dtype)
        h2 = _layernorm(x, lp["ln_2"]["scale"], lp["ln_2"]["bias"], eps)
        m = jax.nn.gelu(h2 @ lp["mlp_up"]["kernel"].astype(dtype) +
                        lp["mlp_up"]["bias"].astype(dtype))
        return x + m @ lp["mlp_down"]["kernel"].astype(dtype) + \
            lp["mlp_down"]["bias"].astype(dtype)

    @staticmethod
    def unembed(params, x, cfg):
        m = params["model"]
        x = _layernorm(x, m["final_ln"]["scale"], m["final_ln"]["bias"],
                       cfg.layer_norm_eps)
        return x.astype(jnp.float32) @ \
            m["embed"]["embedding"].astype(jnp.float32).T   # tied


# ---------------------------------------------------------------------------
# Qwen2-MoE (mixtral experts + gated shared expert, qwen2 attention bias)
# ---------------------------------------------------------------------------
from deepspeed_tpu.models.qwen2_moe import Qwen2MoEConfig  # noqa: E402


@register_policy("qwen2_moe", Qwen2MoEConfig)
class Qwen2MoEPolicy:
    """reference: model_implementations/qwen_v2_moe — Mixtral serving plus a
    dense shared expert whose output is scaled by a per-token sigmoid gate."""

    @staticmethod
    def cache_spec(cfg: Qwen2MoEConfig) -> KVCacheSpec:
        b = cfg.base
        return KVCacheSpec(b.num_layers, b.num_kv_heads, b.head_dim_,
                           b.max_seq_len, b.dtype, b.sliding_window)

    @staticmethod
    def embed(params, tokens, positions, cfg):
        return params["embed"]["embedding"].astype(cfg.base.dtype)[tokens]

    @staticmethod
    def block(params, i, x, attend, positions, cfg):
        base = cfg.base
        dtype = base.dtype
        lp = params[f"layer_{i}"]
        cos, sin = _rope_tables(base.head_dim_, base.max_seq_len,
                                base.rope_theta)
        h = _rms(x, lp["attn_norm"]["scale"], base.rms_norm_eps)
        q, k, v = _qkv({"attn": lp["attn"]}, h, dtype)
        q = _rope_rows(q, cos, sin, positions)
        k = _rope_rows(k, cos, sin, positions)
        attn = attend(q, k, v)
        x = x + jnp.einsum("thk,hkd->td", attn,
                           lp["attn"]["wo"]["kernel"].astype(dtype))
        h2 = _rms(x, lp["mlp_norm"]["scale"], base.rms_norm_eps)
        moe_out = _dense_moe_combine(lp["moe"], h2, cfg.moe.top_k, dtype,
                                     cfg.moe.norm_topk_prob)
        se = lp["shared_expert"]
        g = jax.nn.silu(h2 @ se["w_gate"]["kernel"].astype(dtype))
        u = h2 @ se["w_up"]["kernel"].astype(dtype)
        shared = (g * u) @ se["w_down"]["kernel"].astype(dtype)
        gate = jax.nn.sigmoid(
            (h2 @ se["gate"]["kernel"].astype(dtype)).astype(jnp.float32))
        return x + moe_out + shared * gate.astype(dtype)

    @staticmethod
    def unembed(params, x, cfg):
        x = _rms(x, params["final_norm"]["scale"], cfg.base.rms_norm_eps)
        return x.astype(jnp.float32) @ \
            params["lm_head"]["kernel"].astype(jnp.float32)


# ---------------------------------------------------------------------------
# Gemma-2 (sandwich norms, logit softcaps, alternating sliding/full windows)
# ---------------------------------------------------------------------------
from deepspeed_tpu.models.gemma2 import Gemma2Config  # noqa: E402


@register_policy("gemma2", Gemma2Config)
class Gemma2Policy:
    """models/gemma2.py's serving twin. The decoupled attention scale folds
    into q (kernel and gather both divide by sqrt(d)); the attention-logit
    softcap is applied in-kernel on the paged Pallas path
    (ops/pallas/paged_attention.py `softcap`) and mirrored by the gather
    fallback; cache_spec keeps the FULL window since odd layers attend
    globally."""

    @staticmethod
    def cache_spec(cfg: Gemma2Config) -> KVCacheSpec:
        return KVCacheSpec(cfg.num_layers, cfg.num_kv_heads, cfg.head_dim,
                           cfg.max_seq_len, cfg.dtype, None)

    @staticmethod
    def embed(params, tokens, positions, cfg):
        x = params["embed"]["embedding"].astype(cfg.dtype)[tokens]
        return x * jnp.sqrt(jnp.asarray(cfg.hidden_size,
                                        jnp.float32)).astype(x.dtype)

    @staticmethod
    def block(params, i, x, attend, positions, cfg):
        lp = params[f"layer_{i}"]
        dtype = cfg.dtype
        eps = cfg.rms_norm_eps
        cos, sin = _rope_tables(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
        h = _rms(x, lp["attn_norm"]["scale"] + 1.0, eps)
        q, k, v = _qkv(lp, h, dtype)
        q = _rope_rows(q, cos, sin, positions)
        k = _rope_rows(k, cos, sin, positions)
        # fold the decoupled scale: attend divides by sqrt(d), so prescale
        # by scale*sqrt(d) for a net query_pre_attn_scalar**-0.5
        q = q * jnp.asarray(cfg.query_pre_attn_scalar ** -0.5 *
                            np.sqrt(cfg.head_dim), dtype)
        attn = attend(q, k, v,
                      window=cfg.sliding_window if cfg.is_sliding(i) else None,
                      softcap=cfg.attn_logit_softcap)
        h = jnp.einsum("thk,hkd->td", attn,
                       lp["attn"]["wo"]["kernel"].astype(dtype))
        x = x + _rms(h, lp["post_attn_norm"]["scale"] + 1.0, eps)
        h2 = _rms(x, lp["pre_ffw_norm"]["scale"] + 1.0, eps)
        m = _mlp(lp, h2, dtype, act="gelu_tanh")
        return x + _rms(m, lp["post_ffw_norm"]["scale"] + 1.0, eps)

    @staticmethod
    def unembed(params, x, cfg):
        x = _rms(x, params["final_norm"]["scale"] + 1.0, cfg.rms_norm_eps)
        from deepspeed_tpu.models.llama import softcap_logits
        logits = x.astype(jnp.float32) @ \
            params["embed"]["embedding"].astype(jnp.float32).T     # tied
        return softcap_logits(logits, cfg.final_logit_softcap)
