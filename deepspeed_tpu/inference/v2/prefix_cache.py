"""Block-granular radix (trie) prefix cache over KV pages.

At internet-scale traffic mixes — shared system prompts, multi-turn
conversation replays — most prefill work is *redundant*: the KV for a
shared prefix is recomputed per request even though identical pages
already sit in the device cache. This module makes those pages reusable:
a trie keyed by full-block token tuples maps a prompt's longest
already-materialized prefix to the device block ids holding its KV, so
admission pins those blocks (refcounted sharing) and only the novel
suffix is prefilled. The same idea as vLLM/SGLang radix-prefix caching,
block-granular because pages are the unit the TPU paged-attention kernel
DMAs and the unit ``BlockedKVCache`` allocates.

Division of labor (mirrors ``kv_offload.py``):

* this module is PURE host bookkeeping — trie walk, refcount pins,
  LRU leaf-first eviction planning. Every method is registered as a
  DS002 hot path: the serve tick consults the trie on every admission
  and rebalance, so nothing here may ever touch a device array;
* page *contents* stay in ``BlockedKVCache``; the engine
  (``InferenceEngineV2``) decides when to consult/insert/evict and owns
  the device-block release that an eviction triggers;
* serving *policy* — when to evict cached blocks vs demote sequences —
  lives in ``serving/kv_tier.py`` (``plan_prefix_evictions``): under
  pressure, unpinned cached blocks are reclaimed FIRST (free capacity
  nobody is using), live sequences demote second, and a pinned shared
  prefix is the last thing to go — and when its last reader demotes,
  the pages travel to the host tier inside that reader's entry instead
  of being discarded.

Sharing-safety invariant: a cached block only ever holds FULL blocks of
already-materialized KV (tokens < ``seen_tokens``). Writes always land
at ``seen_tokens`` and beyond, and admission caps the reused prefix at
``(len(prompt) - 1) // block_size`` full blocks, so the first novel
token starts a fresh private block — no sequence can ever scatter into
a page another reader is attending over.

Pin invariant: a sequence always pins the FULL root path of the blocks
it reuses or registers, so ``child.refs > 0`` implies
``parent.refs > 0`` — which is what makes leaf-first eviction of
``refs == 0`` nodes safe (an evictable node never has a pinned
descendant) and keeps every cached node reachable by a root walk.
"""

import dataclasses
import heapq
from typing import Dict, List, Optional, Sequence, Tuple


class _TrieNode:
    """One full KV block: ``key`` is the block's token tuple, ``block``
    the device block id holding its (fully materialized) pages."""

    __slots__ = ("key", "block", "refs", "children", "parent", "stamp")

    def __init__(self, key: Tuple[int, ...], block: int,
                 parent: Optional["_TrieNode"], stamp: int):
        self.key = key
        self.block = block
        self.refs = 0
        self.children: Dict[Tuple[int, ...], "_TrieNode"] = {}
        self.parent = parent
        self.stamp = stamp


@dataclasses.dataclass
class PrefixCacheStats:
    """Lifetime counters — the deterministic proof surface bench_serve
    reports (conservation: ``hit_tokens`` is exactly the prefill work the
    engine never ran)."""

    lookups: int = 0
    hits: int = 0                 # lookups that matched >= 1 block
    misses: int = 0
    hit_tokens: int = 0           # tokens whose prefill was skipped
    lookup_tokens: int = 0        # tokens offered to the trie
    inserted_blocks: int = 0
    evicted_blocks: int = 0


class PrefixCache:
    """uid-aware radix cache over device KV blocks. All methods are pure
    host bookkeeping (DS002 hot paths); device-block release happens in
    the engine from the block ids ``evict_blocks`` hands back."""

    def __init__(self, block_size: int, max_cached_blocks: int = 0):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = block_size
        # soft cap on UNPINNED cached blocks (0 = unlimited): the tick
        # evicts down to it so an idle cache can't squat on the whole pool
        self.max_cached_blocks = max_cached_blocks
        self._root = _TrieNode((), -1, None, 0)
        self._clock = 0
        self._nodes = 0
        self._unpinned = 0
        # uid -> pinned root path (admission match + life-time inserts)
        self._pins: Dict[int, List[_TrieNode]] = {}
        # device block id -> owning node (the "cache owns this block" set
        # the engine's release paths partition against)
        self._owner: Dict[int, _TrieNode] = {}
        self.stats = PrefixCacheStats()

    # ------------------------------------------------------------------
    # introspection (pure; consumed by the serve tick every iteration)
    # ------------------------------------------------------------------
    def cached_blocks(self) -> int:
        return self._nodes

    def pinned_blocks(self) -> int:
        return self._nodes - self._unpinned

    def evictable_blocks(self) -> int:
        """Blocks reclaimable on demand (refs == 0). By the pin
        invariant an unpinned node's whole subtree is unpinned, so every
        one of these is reachable by leaf-first eviction."""
        return self._unpinned

    def owns(self, block: int) -> bool:
        return block in self._owner

    def pinned_block_ids(self) -> List[int]:
        """Block ids with refcount > 0 — the set ``BlockedKVCache.release``
        must skip (neither freed nor scale-reset) while readers remain."""
        return [b for b, n in self._owner.items() if n.refs > 0]

    # ------------------------------------------------------------------
    # lookup / pin (the admission path)
    # ------------------------------------------------------------------
    def _keys(self, tokens: Sequence[int], nblocks: int):
        bs = self.block_size
        for i in range(nblocks):
            yield tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])

    def lookup(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest cached prefix of ``tokens`` in FULL blocks, capped at
        ``(len(tokens) - 1) // block_size`` so at least the last prompt
        token is always computed (its logits seed the first sample) and
        the first novel write starts a fresh block. Returns (block ids,
        matched token count) WITHOUT pinning — ``admit_match`` pins."""
        self._clock += 1
        self.stats.lookups += 1
        self.stats.lookup_tokens += len(tokens)
        limit = max(len(tokens) - 1, 0) // self.block_size
        node = self._root
        blocks: List[int] = []
        for key in self._keys(tokens, limit):
            child = node.children.get(key)
            if child is None:
                break
            child.stamp = self._clock
            blocks.append(child.block)
            node = child
        matched = len(blocks) * self.block_size
        if blocks:
            self.stats.hits += 1
            self.stats.hit_tokens += matched
        else:
            self.stats.misses += 1
        return blocks, matched

    def admit_match(self, uid: int, tokens: Sequence[int]
                    ) -> Tuple[List[int], int]:
        """``lookup`` + pin the matched root path for ``uid``. The block
        ids come back in prefix order, ready to seed the sequence's block
        table."""
        blocks, matched = self.lookup(tokens)
        if blocks:
            node = self._root
            pins = self._pins.setdefault(uid, [])
            for key in self._keys(tokens, len(blocks)):
                node = node.children[key]
                self._pin(node, pins)
        return blocks, matched

    def _pin(self, node: _TrieNode, pins: List[_TrieNode]) -> None:
        if node.refs == 0:
            self._unpinned -= 1
        node.refs += 1
        pins.append(node)

    # ------------------------------------------------------------------
    # insertion (prefill completion + flush-time absorption)
    # ------------------------------------------------------------------
    def insert_from_seq(self, uid: int, tokens: Sequence[int],
                        seq_blocks: Sequence[int], seen_tokens: int,
                        pin: bool = True) -> int:
        """Register ``uid``'s fully-materialized full blocks (tokens
        ``< seen_tokens``) in the trie. Existing nodes are kept (first
        writer wins; a duplicate private block stays private and is
        released at flush); novel blocks transfer ownership to the
        cache. With ``pin=True`` the whole walked path is pinned for
        ``uid`` (the pin invariant); ``pin=False`` is the flush-time
        absorb, leaving new nodes immediately evictable. Returns the
        number of blocks newly registered."""
        self._clock += 1
        full = min(seen_tokens, len(tokens)) // self.block_size
        full = min(full, len(seq_blocks))
        node = self._root
        pins = self._pins.setdefault(uid, []) if pin else None
        added = 0
        for i, key in enumerate(self._keys(tokens, full)):
            child = node.children.get(key)
            if child is None:
                block = int(seq_blocks[i])
                if block in self._owner:
                    # one physical block cannot back two trie nodes —
                    # this arises only if a caller re-absorbs a path the
                    # cache already owns under different tokens (a
                    # bookkeeping bug upstream); refuse to corrupt
                    break
                child = _TrieNode(key, block, node, self._clock)
                node.children[key] = child
                self._owner[block] = child
                self._nodes += 1
                self._unpinned += 1
                added += 1
                self.stats.inserted_blocks += 1
            child.stamp = self._clock
            # pin each path node once per uid (refcounts are per reader,
            # not per visit — re-walking a path must not double-pin)
            if pins is not None and child not in pins:
                self._pin(child, pins)
            node = child
        return added

    # ------------------------------------------------------------------
    # release (flush / demotion)
    # ------------------------------------------------------------------
    def release_seq(self, uid: int) -> None:
        """Drop every pin ``uid`` holds. Blocks whose refcount reaches 0
        STAY cached (evictable) — that retention is the whole point: the
        next request with the same prefix reuses them."""
        for node in self._pins.pop(uid, ()):
            node.refs -= 1
            if node.refs == 0:
                self._unpinned += 1

    # ------------------------------------------------------------------
    # eviction (LRU, leaf-first; planner pure, release in the engine)
    # ------------------------------------------------------------------
    def plan_evictions(self, want: int) -> List[int]:
        """Up to ``want`` block ids to reclaim, oldest-stamp leaves
        first. Only ``refs == 0`` nodes whose children are all also
        selected qualify, so a selected set is always removable without
        orphaning a reachable node. One tree walk + a priority queue
        (Kahn over the child counts, min ``(stamp, block)`` first) —
        O(M log M) in cached nodes, never O(want x M): this plans on the
        serve tick. Pure planning — call ``evict_blocks`` to commit."""
        if want <= 0 or self._unpinned == 0:
            return []
        # one DFS: count each unpinned node's children (pin invariant:
        # an unpinned node's whole subtree is unpinned, so every child
        # of a candidate is itself a candidate or pinned-free)
        pending: Dict[int, int] = {}
        by_id: Dict[int, _TrieNode] = {}
        heap: List[Tuple[int, int, int]] = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                stack.append(child)
                if child.refs != 0:
                    continue
                pending[id(child)] = len(child.children)
                by_id[id(child)] = child
                if not child.children:
                    heapq.heappush(heap, (child.stamp, child.block,
                                          id(child)))
        out: List[int] = []
        while heap and len(out) < want:
            _stamp, block, nid = heapq.heappop(heap)
            out.append(block)
            parent = by_id[nid].parent
            if parent is None or id(parent) not in pending:
                continue
            pending[id(parent)] -= 1
            if pending[id(parent)] == 0:
                # all children selected: the parent becomes a leaf
                heapq.heappush(heap, (parent.stamp, parent.block,
                                      id(parent)))
        return out

    def evict_blocks(self, blocks: Sequence[int]) -> List[int]:
        """Commit an eviction plan: detach the nodes and forget the
        blocks. Returns the block ids actually evicted (pinned or
        unknown ids are skipped defensively) — the engine releases these
        to the allocator."""
        out: List[int] = []
        for b in blocks:
            node = self._owner.get(b)
            if node is None or node.refs > 0 or node.children:
                continue
            parent = node.parent
            if parent is not None:
                parent.children.pop(node.key, None)
            del self._owner[b]
            self._nodes -= 1
            self._unpinned -= 1
            self.stats.evicted_blocks += 1
            out.append(b)
        return out

    def over_cap_blocks(self) -> int:
        """How many unpinned blocks exceed ``max_cached_blocks`` (0 when
        uncapped) — the per-tick trim the serve policy applies even
        without pressure."""
        if self.max_cached_blocks <= 0:
            return 0
        return max(self._unpinned - self.max_cached_blocks, 0)

    # ------------------------------------------------------------------
    # export (drain-time handoff walk — NOT a hot path)
    # ------------------------------------------------------------------
    def chains(self) -> List[Tuple[Tuple[int, ...], Tuple[int, ...]]]:
        """Every root-to-leaf path as ``(token tuple, block ids)`` — the
        drain-time export surface for fleet prefix handoff. Leaves only:
        an interior node's tokens/blocks are a prefix of each descendant
        leaf's, so leaf chains carry the whole trie without duplication
        (the importer re-splits them block-by-block). Offline by
        contract (retirement), deliberately NOT in the DS002 registry."""
        out: List[Tuple[Tuple[int, ...], Tuple[int, ...]]] = []
        stack: List[Tuple[_TrieNode, Tuple[int, ...], Tuple[int, ...]]] = [
            (self._root, (), ())]
        while stack:
            node, tokens, blocks = stack.pop()
            if node is not self._root:
                tokens = tokens + node.key
                blocks = blocks + (node.block,)
                if not node.children:
                    out.append((tokens, blocks))
            for child in node.children.values():
                stack.append((child, tokens, blocks))
        return out

    def snapshot(self) -> Dict[str, int]:
        """Counters + occupancy in one dict (the /metrics surface)."""
        s = self.stats
        return {
            "lookups": s.lookups,
            "hits": s.hits,
            "misses": s.misses,
            "hit_tokens": s.hit_tokens,
            "lookup_tokens": s.lookup_tokens,
            "inserted_blocks": s.inserted_blocks,
            "evicted_blocks": s.evicted_blocks,
            "cached_blocks": self._nodes,
            "pinned_blocks": self._nodes - self._unpinned,
            "evictable_blocks": self._unpinned,
        }
