"""Host-RAM KV offload store — the demotion tier under the device cache.

ZeRO-Offload (PAPERS.md, arxiv 2101.06840) applied to inference state:
when device KV blocks run hot, a sequence's pages are *demoted* to host
RAM (freeing its device blocks for active decodes) and *promoted* back —
possibly into different block ids, the block table is rebuilt — when the
scheduler has room again. Overload then costs latency (a paused request
waits in host RAM) instead of availability (a 429 at the door).

Quantized host tier: the EQuARX playbook (arxiv 2506.17615 — quantize
the wire, keep error bounded by per-group scales) applied to CACHE state
instead of wire state. Demoted pages can be stored int8 or fp8 with one
fp32 scale per (layer, k/v, head, page) — the exact group shape the
device fp8 path already uses (``kv_cache.py`` per-page scales) — which
roughly 2x (bf16→fp8) to 4x (fp32→int8) the host budget's effective
blocks. Promotion dequantizes back to device width: bit-identical for
full-width (``codec="none"``) entries, tolerance-bounded (one quantize
round-trip, error <= scale/2 per element) for quantized ones. Pages that
are ALREADY fp8 on device are never re-quantized (their scales ride
along as before, bit-identical round-trip preserved).

This module is the storage half only: a uid-keyed container of gathered
page tiles with exact byte accounting (stored AND raw — the compression
ratio is a first-class counter). Page movement lives on the engine
(``InferenceEngineV2.demote_kv`` / ``promote_kv``); *policy* — watermarks,
victim selection, promotion order, the quantize knob — lives in
``serving/kv_tier.py`` + the ``serving`` config group. The split keeps
the inference package free of serving concerns while the serving tick
stays free of device-array handling.

The codec functions are registered DS002 hot paths in the defensive
sense: they are pure numpy over HOST arrays (the gather already
happened) and must never grow a device touch or a ``float()`` coercion.
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

#: host-tier page codecs the serving ``host_kv_quantize`` knob selects
KV_CODECS = ("none", "int8", "fp8")

_INT8_MAX = 127.0
_FP8_MAX = 448.0       # float8_e4m3fn max finite (see kv_cache.FP8_MAX)


def _page_absmax(data: np.ndarray) -> np.ndarray:
    """[L, 2, H, NB, bs, D] -> per-page absmax [L, 2, H, NB] in fp32."""
    return np.max(np.abs(data.astype(np.float32)), axis=(-1, -2))


def quantize_pages(data: np.ndarray, codec: str
                   ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Quantize gathered page tiles ``[L, 2, H, NB, bs, D]`` under the
    per-page group-scale scheme. Returns ``(stored, qscales)``:
    ``codec="none"`` passes through (qscales None); ``"int8"`` stores
    int8 with fp32 scales ``absmax/127``; ``"fp8"`` stores
    float8_e4m3fn (via ml_dtypes) with fp32 scales ``absmax/448``.
    All-zero pages get scale 1.0 so the round-trip stays exact."""
    if codec == "none":
        return data, None
    if codec not in KV_CODECS:
        raise ValueError(f"unknown KV page codec {codec!r}; "
                         f"one of {KV_CODECS}")
    limit = _INT8_MAX if codec == "int8" else _FP8_MAX
    scales = _page_absmax(data) / limit
    scales = np.where(scales > 0.0, scales, 1.0).astype(np.float32)
    scaled = data.astype(np.float32) / scales[..., None, None]
    if codec == "int8":
        stored = np.clip(np.rint(scaled), -_INT8_MAX, _INT8_MAX
                         ).astype(np.int8)
    else:
        import ml_dtypes
        stored = np.clip(scaled, -_FP8_MAX, _FP8_MAX
                         ).astype(ml_dtypes.float8_e4m3fn)
    return stored, scales


def dequantize_pages(stored: np.ndarray, qscales: Optional[np.ndarray],
                     codec: str, out_dtype) -> np.ndarray:
    """Invert ``quantize_pages`` back to the device page dtype. For
    ``codec="none"`` this is the identity (bit-identical promotion)."""
    if codec == "none" or qscales is None:
        return stored
    return (stored.astype(np.float32) * qscales[..., None, None]
            ).astype(out_dtype)


def quantize_error_bound(qscales: Optional[np.ndarray], codec: str) -> float:
    """The per-element absolute error bound of one quantize round-trip:
    half a quantization step (``scale/2``) for int8 round-to-nearest;
    for fp8 e4m3 (3 mantissa bits, half-ULP relative error 2^-4) the
    worst case is on the largest representable scaled value, i.e.
    ``scale * 448 * 2^-4``. The tolerance tests pin against exactly
    this bound."""
    if codec == "none" or qscales is None:
        return 0.0
    s = float(np.max(qscales))
    return s * (0.5 if codec == "int8" else _FP8_MAX * 2.0 ** -4)


@dataclasses.dataclass
class HostKVEntry:
    """One demoted sequence's KV state: the gathered page tiles
    ``[L, 2, H_kv, n_blocks, block_size, D]`` (host ndarray; full width,
    or codec-quantized with per-page ``qscales``) and the bookkeeping
    needed to re-reserve on promotion. fp8 DEVICE pages keep their
    per-(head, page) ``scales`` alongside either way."""

    blocks: int                          # device blocks held at demotion
    data: Optional[np.ndarray]           # None when blocks == 0
    scales: Optional[np.ndarray]         # fp8 device page scales (else None)
    seen_tokens: int                     # KV coverage at demotion
    codec: str = "none"                  # host-tier page codec
    qscales: Optional[np.ndarray] = None  # codec scales (per page, fp32)
    raw_nbytes: int = 0                  # pre-codec bytes (set by put/engine)

    @property
    def nbytes(self) -> int:
        total = 0
        if self.data is not None:
            total += int(self.data.nbytes)
        if self.scales is not None:
            total += int(self.scales.nbytes)
        if self.qscales is not None:
            total += int(self.qscales.nbytes)
        return total


class HostKVStore:
    """uid -> ``HostKVEntry`` with running byte/lifetime accounting — the
    "host" column of the serving layer's two-tier KV ledger. Tracks both
    STORED bytes (post-codec, what counts against the host budget) and
    RAW bytes (what the pages would cost at device width) so the
    host-tier compression ratio is a first-class deterministic counter."""

    def __init__(self):
        self._entries: Dict[int, HostKVEntry] = {}
        self.total_bytes = 0
        self.raw_bytes = 0
        # lifetime counters (monotone; the deterministic proof surface)
        self.demotions = 0
        self.promotions = 0
        self.demoted_bytes = 0
        self.promoted_bytes = 0
        self.demoted_raw_bytes = 0
        self.quantized_entries = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, uid: int) -> bool:
        return uid in self._entries

    def get(self, uid: int) -> Optional[HostKVEntry]:
        return self._entries.get(uid)

    def uids(self) -> List[int]:
        """Insertion (= demotion) order — the FIFO promotion order."""
        return list(self._entries)

    def put(self, uid: int, entry: HostKVEntry) -> int:
        if uid in self._entries:
            raise ValueError(f"uid {uid} already demoted")
        if entry.raw_nbytes == 0:
            entry.raw_nbytes = entry.nbytes
        self._entries[uid] = entry
        self.total_bytes += entry.nbytes
        self.raw_bytes += entry.raw_nbytes
        self.demotions += 1
        self.demoted_bytes += entry.nbytes
        self.demoted_raw_bytes += entry.raw_nbytes
        if entry.codec != "none":
            self.quantized_entries += 1
        return entry.nbytes

    def pop(self, uid: int, promoted: bool = False) -> Optional[HostKVEntry]:
        """Remove an entry (promotion, or flush of a cancelled/expired
        sequence). ``promoted=True`` counts it as a promotion."""
        entry = self._entries.pop(uid, None)
        if entry is None:
            return None
        self.total_bytes -= entry.nbytes
        self.raw_bytes -= entry.raw_nbytes
        if promoted:
            self.promotions += 1
            self.promoted_bytes += entry.nbytes
        return entry

    def compression_ratio(self) -> float:
        """Lifetime demoted raw/stored ratio (1.0 = no quantization) —
        the 'host-tier compression' row on env_report and /metrics."""
        return (self.demoted_raw_bytes / self.demoted_bytes
                if self.demoted_bytes > 0 else 1.0)
