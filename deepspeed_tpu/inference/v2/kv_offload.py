"""Host-RAM KV offload store — the demotion tier under the device cache.

ZeRO-Offload (PAPERS.md, arxiv 2101.06840) applied to inference state:
when device KV blocks run hot, a sequence's pages are *demoted* to host
RAM (freeing its device blocks for active decodes) and *promoted* back —
possibly into different block ids, the block table is rebuilt — when the
scheduler has room again. Overload then costs latency (a paused request
waits in host RAM) instead of availability (a 429 at the door).

This module is the storage half only: a uid-keyed container of gathered
page tiles with exact byte accounting. Page movement lives on the engine
(``InferenceEngineV2.demote_kv`` / ``promote_kv``); *policy* — watermarks,
victim selection, promotion order — lives in ``serving/kv_tier.py``. The
split keeps the inference package free of serving concerns while the
serving tick stays free of device-array handling.
"""

import dataclasses
from typing import Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class HostKVEntry:
    """One demoted sequence's KV state: the gathered page tiles
    ``[L, 2, H_kv, n_blocks, block_size, D]`` (host ndarray, page dtype
    preserved — fp8 pages stay fp8 with their per-(head, page) scales) and
    the bookkeeping needed to re-reserve on promotion."""

    blocks: int                          # device blocks held at demotion
    data: Optional[np.ndarray]           # None when blocks == 0
    scales: Optional[np.ndarray]         # fp8 page scales (else None)
    seen_tokens: int                     # KV coverage at demotion

    @property
    def nbytes(self) -> int:
        total = 0
        if self.data is not None:
            total += int(self.data.nbytes)
        if self.scales is not None:
            total += int(self.scales.nbytes)
        return total


class HostKVStore:
    """uid -> ``HostKVEntry`` with running byte/lifetime accounting — the
    "host" column of the serving layer's two-tier KV ledger."""

    def __init__(self):
        self._entries: Dict[int, HostKVEntry] = {}
        self.total_bytes = 0
        # lifetime counters (monotone; the deterministic proof surface)
        self.demotions = 0
        self.promotions = 0
        self.demoted_bytes = 0
        self.promoted_bytes = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, uid: int) -> bool:
        return uid in self._entries

    def get(self, uid: int) -> Optional[HostKVEntry]:
        return self._entries.get(uid)

    def uids(self) -> List[int]:
        """Insertion (= demotion) order — the FIFO promotion order."""
        return list(self._entries)

    def put(self, uid: int, entry: HostKVEntry) -> int:
        if uid in self._entries:
            raise ValueError(f"uid {uid} already demoted")
        self._entries[uid] = entry
        self.total_bytes += entry.nbytes
        self.demotions += 1
        self.demoted_bytes += entry.nbytes
        return entry.nbytes

    def pop(self, uid: int, promoted: bool = False) -> Optional[HostKVEntry]:
        """Remove an entry (promotion, or flush of a cancelled/expired
        sequence). ``promoted=True`` counts it as a promotion."""
        entry = self._entries.pop(uid, None)
        if entry is None:
            return None
        self.total_bytes -= entry.nbytes
        if promoted:
            self.promotions += 1
            self.promoted_bytes += entry.nbytes
        return entry
