"""Free-list allocator for paged KV-cache blocks.

Reference analog: ``deepspeed/inference/v2/ragged/blocked_allocator.py:11``
(``BlockedAllocator`` — a linked free list over a fixed block pool). Host-side
bookkeeping; the blocks themselves are rows of device KV arrays.
"""

from typing import List

import numpy as np


class BlockedAllocator:
    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"need at least 1 block, got {num_blocks}")
        self._num_blocks = num_blocks
        # linked free list: _next[i] = next free block after i
        self._next = np.arange(1, num_blocks + 1, dtype=np.int64)
        self._head = 0
        self._free = num_blocks

    @property
    def free_blocks(self) -> int:
        return self._free

    @property
    def total_blocks(self) -> int:
        return self._num_blocks

    def allocate(self, num_blocks: int) -> List[int]:
        if num_blocks > self._free:
            raise ValueError(
                f"cannot allocate {num_blocks} blocks ({self._free} free)")
        out = []
        for _ in range(num_blocks):
            out.append(self._head)
            self._head = int(self._next[self._head])
            self._free -= 1
        return out

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if not 0 <= b < self._num_blocks:
                raise ValueError(f"invalid block id {b}")
            self._next[b] = self._head
            self._head = b
            self._free += 1
