"""Cache-aware Llama forward for serving: chunked prefill + batched paged decode.

Reference analog: the inference v2 kernel pipeline (``linear_blocked_kv_rotary``,
``blocked_flash``, ``logits_gather`` in ``inference/v2/kernels/ragged_ops/``) and
the per-arch model implementations (``inference/v2/model_implementations/llama_v2``).

TPU redesign: pure functions over the *training* model's param pytree
(``LlamaForCausalLM`` — same weights serve and train, no module surgery), with
static bucketed shapes so each (bucket, batch) pair compiles once:

- ``prefill_chunk``: one sequence, a [bucket]-padded token chunk; writes K/V into
  its cache blocks, runs flash attention against the gathered context, returns the
  last real token's logits (SplitFuse chunks: q_offset = chunk start).
- ``decode_step``: a [B]-padded batch of sequences, one token each; scatter-writes
  K/V, attends over gathered paged context.

Padding tokens write into a reserved trash block (the pool's last block), so no
masking is needed on the write path. Causal masking doubles as padding masking on
the read path: gathered positions >= context length can never satisfy
qpos >= kpos.
"""

from functools import partial
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import LlamaConfig, rope_freqs
from deepspeed_tpu.ops.flash_attention import flash_attention

NEG_INF = -1e30


def _rms(x, scale, eps):
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(jnp.square(x32), -1, keepdims=True) + eps)
    return (y * scale).astype(x.dtype)


def _rope_1d(x, cos, sin, positions):
    """x: [..., T, H, D]; positions broadcastable to [..., T]."""
    cos_p = cos[positions][..., None, :]
    sin_p = sin[positions][..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos_p - x2 * sin_p, x2 * cos_p + x1 * sin_p], -1)
    return out.astype(x.dtype)


def _layer_params(params, i):
    return params["model"][f"layer_{i}"]


def _windowed_context_attention(q, ctx_k, ctx_v, qpos, window, num_heads):
    """Sliding-window prefill attention over gathered paged context.
    q: [T,H,d]; ctx_k/v: [K,Hkv,d]; qpos: [T] absolute positions."""
    rep = num_heads // ctx_k.shape[1]
    if rep > 1:
        ctx_k = jnp.repeat(ctx_k, rep, axis=1)
        ctx_v = jnp.repeat(ctx_v, rep, axis=1)
    d = q.shape[-1]
    scores = jnp.einsum("thd,khd->htk", q, ctx_k,
                        preferred_element_type=jnp.float32) / np.sqrt(d)
    kpos = jnp.arange(ctx_k.shape[0])[None, :]
    mask = (kpos <= qpos[:, None]) & (kpos > qpos[:, None] - window)
    scores = jnp.where(mask[None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("htk,khd->thd", probs, ctx_v)


def _qkv(lp, x, dtype):
    """x: [T, D] -> q [T,H,d], k/v [T,Hkv,d] via DenseGeneral kernels (+ bias
    when present — qwen2-style attention_bias)."""
    q = jnp.einsum("td,dhk->thk", x, lp["attn"]["wq"]["kernel"].astype(dtype))
    k = jnp.einsum("td,dhk->thk", x, lp["attn"]["wk"]["kernel"].astype(dtype))
    v = jnp.einsum("td,dhk->thk", x, lp["attn"]["wv"]["kernel"].astype(dtype))
    if "bias" in lp["attn"]["wq"]:
        q = q + lp["attn"]["wq"]["bias"].astype(dtype)
        k = k + lp["attn"]["wk"]["bias"].astype(dtype)
        v = v + lp["attn"]["wv"]["bias"].astype(dtype)
    return q, k, v


def _mlp(lp, x, dtype):
    g = x @ lp["mlp"]["w_gate"]["kernel"].astype(dtype)
    u = x @ lp["mlp"]["w_up"]["kernel"].astype(dtype)
    return (jax.nn.silu(g) * u) @ lp["mlp"]["w_down"]["kernel"].astype(dtype)


@partial(jax.jit, static_argnames=("cfg", "block_size"))
def prefill_chunk(params, cache_data, tokens, start, block_table, true_len,
                  cfg: LlamaConfig, block_size: int):
    """One sequence, one chunk. tokens: [Tb] (bucket-padded); start: chunk offset;
    block_table: [MB] block ids (trash-padded); true_len: real chunk tokens.
    Returns (last-token logits [V], updated cache_data)."""
    dtype = cfg.dtype
    tb = tokens.shape[0]
    mb = block_table.shape[0]
    d_head = cfg.head_dim_
    cos, sin = rope_freqs(d_head, cfg.max_seq_len, cfg.rope_theta)
    cos, sin = jnp.asarray(cos), jnp.asarray(sin)

    positions = start + jnp.arange(tb)
    safe_pos = jnp.minimum(positions, cfg.max_seq_len - 1)
    # padding tokens (t >= true_len) route to the trash block
    tok_block = jnp.where(jnp.arange(tb) < true_len,
                          block_table[jnp.minimum(safe_pos // block_size, mb - 1)],
                          cache_data.shape[2] - 1)
    tok_off = safe_pos % block_size

    x = params["model"]["embed"]["embedding"].astype(dtype)[tokens]
    for i in range(cfg.num_layers):
        lp = _layer_params(params, i)
        h = _rms(x, lp["attn_norm"]["scale"], cfg.rms_norm_eps)
        q, k, v = _qkv(lp, h, dtype)
        q = _rope_1d(q, cos, sin, safe_pos)
        k = _rope_1d(k, cos, sin, safe_pos)
        cache_data = cache_data.at[i, 0, tok_block, tok_off].set(k)
        cache_data = cache_data.at[i, 1, tok_block, tok_off].set(v)
        # gather full context (includes this chunk's freshly written K/V)
        ctx_k = cache_data[i, 0, block_table].reshape(mb * block_size,
                                                     cfg.num_kv_heads, d_head)
        ctx_v = cache_data[i, 1, block_table].reshape(mb * block_size,
                                                     cfg.num_kv_heads, d_head)
        if cfg.sliding_window is not None:
            attn = _windowed_context_attention(
                q, ctx_k, ctx_v, positions, cfg.sliding_window, cfg.num_heads)
        else:
            attn = flash_attention(q[None], ctx_k[None], ctx_v[None], causal=True,
                                   q_offset=start)[0]
        attn_out = jnp.einsum("thk,hkd->td", attn,
                              lp["attn"]["wo"]["kernel"].astype(dtype))
        x = x + attn_out
        h2 = _rms(x, lp["mlp_norm"]["scale"], cfg.rms_norm_eps)
        x = x + _mlp(lp, h2, dtype)

    x = _rms(x, params["model"]["final_norm"]["scale"], cfg.rms_norm_eps)
    last = x[jnp.maximum(true_len - 1, 0)]
    if cfg.tie_embeddings:
        logits = params["model"]["embed"]["embedding"].astype(jnp.float32) @ \
            last.astype(jnp.float32)
    else:
        logits = last.astype(jnp.float32) @ \
            params["model"]["lm_head"]["kernel"].astype(jnp.float32)
    return logits, cache_data


@partial(jax.jit, static_argnames=("cfg", "block_size"))
def decode_step(params, cache_data, tokens, positions, block_tables, valid,
                cfg: LlamaConfig, block_size: int):
    """Batched single-token decode. tokens/positions/valid: [B];
    block_tables: [B, MB]. Returns (logits [B, V], updated cache_data)."""
    dtype = cfg.dtype
    b = tokens.shape[0]
    mb = block_tables.shape[1]
    d_head = cfg.head_dim_
    cos, sin = rope_freqs(d_head, cfg.max_seq_len, cfg.rope_theta)
    cos, sin = jnp.asarray(cos), jnp.asarray(sin)

    safe_pos = jnp.minimum(positions, cfg.max_seq_len - 1)
    blk = jnp.where(valid,
                    jnp.take_along_axis(
                        block_tables,
                        jnp.minimum(safe_pos // block_size, mb - 1)[:, None],
                        axis=1)[:, 0],
                    cache_data.shape[2] - 1)
    off = safe_pos % block_size

    x = params["model"]["embed"]["embedding"].astype(dtype)[tokens]  # [B, D]
    for i in range(cfg.num_layers):
        lp = _layer_params(params, i)
        h = _rms(x, lp["attn_norm"]["scale"], cfg.rms_norm_eps)
        q, k, v = _qkv(lp, h, dtype)                     # [B, H(kv), d]
        q = _rope_1d(q[:, None], cos, sin, safe_pos[:, None])[:, 0]
        k = _rope_1d(k[:, None], cos, sin, safe_pos[:, None])[:, 0]
        cache_data = cache_data.at[i, 0, blk, off].set(k)
        cache_data = cache_data.at[i, 1, blk, off].set(v)
        # paged context gather: [B, MB*bs, Hkv, d]
        ctx_k = cache_data[i, 0][block_tables].reshape(b, mb * block_size,
                                                       cfg.num_kv_heads, d_head)
        ctx_v = cache_data[i, 1][block_tables].reshape(b, mb * block_size,
                                                       cfg.num_kv_heads, d_head)
        rep = cfg.num_heads // cfg.num_kv_heads
        if rep > 1:
            ctx_k = jnp.repeat(ctx_k, rep, axis=2)
            ctx_v = jnp.repeat(ctx_v, rep, axis=2)
        scores = jnp.einsum("bhd,bkhd->bhk", q, ctx_k,
                            preferred_element_type=jnp.float32) / np.sqrt(d_head)
        kpos = jnp.arange(mb * block_size)[None, :]
        mask = kpos <= safe_pos[:, None]
        if cfg.sliding_window is not None:
            mask &= kpos > (safe_pos[:, None] - cfg.sliding_window)
        scores = jnp.where(mask[:, None, :], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
        attn = jnp.einsum("bhk,bkhd->bhd", probs, ctx_v)
        attn_out = jnp.einsum("bhk,hkd->bd", attn,
                              lp["attn"]["wo"]["kernel"].astype(dtype))
        x = x + attn_out
        h2 = _rms(x, lp["mlp_norm"]["scale"], cfg.rms_norm_eps)
        x = x + _mlp(lp, h2, dtype)

    x = _rms(x, params["model"]["final_norm"]["scale"], cfg.rms_norm_eps)
    if cfg.tie_embeddings:
        logits = x.astype(jnp.float32) @ \
            params["model"]["embed"]["embedding"].astype(jnp.float32).T
    else:
        logits = x.astype(jnp.float32) @ \
            params["model"]["lm_head"]["kernel"].astype(jnp.float32)
    return logits, cache_data
