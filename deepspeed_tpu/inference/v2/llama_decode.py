"""Cache-aware Llama forward for serving: chunked prefill + batched paged decode.

Reference analog: the inference v2 kernel pipeline (``linear_blocked_kv_rotary``,
``blocked_flash``, ``logits_gather`` in ``inference/v2/kernels/ragged_ops/``) and
the per-arch model implementations (``inference/v2/model_implementations/llama_v2``).

Attention runs through the Pallas paged kernel on TPU (block tables in scalar
prefetch — pages stream from the paged pool with no context re-materialization,
``ops/pallas/paged_attention.py``); elsewhere the gather-based reference path
with identical semantics runs (``attn_impl`` static arg: auto|kernel|
kernel_interpret|gather).

TPU redesign: pure functions over the *training* model's param pytree
(``LlamaForCausalLM`` — same weights serve and train, no module surgery), with
static bucketed shapes so each (bucket, batch) pair compiles once:

- ``prefill_chunk``: one sequence, a [bucket]-padded token chunk; writes K/V into
  its cache blocks, runs flash attention against the gathered context, returns the
  last real token's logits (SplitFuse chunks: q_offset = chunk start).
- ``decode_step``: a [B]-padded batch of sequences, one token each; scatter-writes
  K/V, attends over gathered paged context.

Padding tokens write into a reserved trash block (the pool's last block), so no
masking is needed on the write path. Causal masking doubles as padding masking on
the read path: gathered positions >= context length can never satisfy
qpos >= kpos.
"""

from functools import partial
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models.llama import LlamaConfig
from deepspeed_tpu.ops.pallas.paged_attention import (
    paged_attention, paged_attention_reference)

ATTN_IMPLS = ("auto", "kernel", "kernel_interpret", "gather")


def _paged_attn(q, cache_data, layer, block_tables, start_pos, window,
                attn_impl: str, softcap=None, scales=None):
    """q: [B, T, H, d]; dispatch kernel vs gather reference over the head-major
    cache [L, 2, Hkv, NB, bs, d]. ``softcap`` (gemma2) is supported by both
    the kernel and the gather path; ``scales`` ([L, 2, Hkv, NB] fp32, fp8
    pages) dequantizes per (head, page) on load in both paths."""
    if attn_impl not in ATTN_IMPLS:
        raise ValueError(f"unknown attn_impl {attn_impl!r}; one of {ATTN_IMPLS}")
    k_pages, v_pages = cache_data[layer, 0], cache_data[layer, 1]
    ks, vs = (scales[layer, 0], scales[layer, 1]) if scales is not None \
        else (None, None)
    impl = attn_impl
    if impl == "auto":
        impl = "kernel" if jax.default_backend() == "tpu" else "gather"
    if impl == "gather":
        return paged_attention_reference(q, k_pages, v_pages, block_tables,
                                         start_pos, window=window,
                                         softcap=softcap, k_scales=ks,
                                         v_scales=vs)
    return paged_attention(q, k_pages, v_pages, block_tables, start_pos,
                           window=window, softcap=softcap, k_scales=ks,
                           v_scales=vs, interpret=impl == "kernel_interpret")


def _rms(x, scale, eps):
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(jnp.square(x32), -1, keepdims=True) + eps)
    return (y * scale).astype(x.dtype)


def _qkv(lp, x, dtype):
    """x: [T, D] -> q [T,H,d], k/v [T,Hkv,d] via DenseGeneral kernels (+ bias
    when present — qwen2-style attention_bias)."""
    q = jnp.einsum("td,dhk->thk", x, lp["attn"]["wq"]["kernel"].astype(dtype))
    k = jnp.einsum("td,dhk->thk", x, lp["attn"]["wk"]["kernel"].astype(dtype))
    v = jnp.einsum("td,dhk->thk", x, lp["attn"]["wv"]["kernel"].astype(dtype))
    if "bias" in lp["attn"]["wq"]:
        q = q + lp["attn"]["wq"]["bias"].astype(dtype)
        k = k + lp["attn"]["wk"]["bias"].astype(dtype)
        v = v + lp["attn"]["wv"]["bias"].astype(dtype)
    return q, k, v


def _mlp(lp, x, dtype, act: str = "silu"):
    g = x @ lp["mlp"]["w_gate"]["kernel"].astype(dtype)
    u = x @ lp["mlp"]["w_up"]["kernel"].astype(dtype)
    if act == "silu":
        gated = jax.nn.silu(g)
    elif act == "gelu_tanh":
        gated = jax.nn.gelu(g, approximate=True)
    else:
        raise ValueError(f"unsupported hidden_act {act!r} (silu | gelu_tanh)")
    return (gated * u) @ lp["mlp"]["w_down"]["kernel"].astype(dtype)


def prefill_chunk(params, cache_data, tokens, start, block_table, true_len,
                  cfg: LlamaConfig, block_size: int, attn_impl: str = "auto"):
    """One sequence, one chunk. tokens: [Tb] (bucket-padded); start: chunk offset;
    block_table: [MB] block ids (trash-padded); true_len: real chunk tokens.
    Returns (last-token logits [V], updated cache_data).

    Thin llama-specialized wrapper over the arch-generic loop
    (``generic_decode.prefill_chunk_g`` + ``modules.LlamaPolicy``)."""
    from deepspeed_tpu.inference.v2.generic_decode import prefill_chunk_g
    from deepspeed_tpu.inference.v2.modules import LlamaPolicy
    return prefill_chunk_g(params, cache_data, tokens, start, block_table,
                           true_len, policy=LlamaPolicy, cfg=cfg,
                           block_size=block_size, attn_impl=attn_impl)


def decode_step(params, cache_data, tokens, positions, block_tables, valid,
                cfg: LlamaConfig, block_size: int, attn_impl: str = "auto"):
    """Batched single-token decode. tokens/positions/valid: [B];
    block_tables: [B, MB]. Returns (logits [B, V], updated cache_data).

    Thin llama-specialized wrapper over the arch-generic loop."""
    from deepspeed_tpu.inference.v2.generic_decode import decode_step_g
    from deepspeed_tpu.inference.v2.modules import LlamaPolicy
    return decode_step_g(params, cache_data, tokens, positions, block_tables,
                         valid, policy=LlamaPolicy, cfg=cfg,
                         block_size=block_size, attn_impl=attn_impl)
