"""Sequence state tracking for continuous batching.

Reference analogs: ``deepspeed/inference/v2/ragged/sequence_descriptor.py``
(``DSSequenceDescriptor``) and ``ragged_manager.py:19`` (``DSStateManager``) —
uid-keyed sequence records holding seen-token counts and KV block tables.
"""

import dataclasses
from typing import Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class SequenceDescriptor:
    uid: int
    prompt_tokens: np.ndarray                 # full prompt (host)
    blocks: List[int] = dataclasses.field(default_factory=list)
    seen_tokens: int = 0                      # tokens whose KV is in cache
    generated: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    # demoted to the host KV tier: holds no device blocks, invisible to the
    # step planner until promoted back (engine_v2.demote_kv/promote_kv)
    paused: bool = False

    @property
    def total_tokens(self) -> int:
        return len(self.prompt_tokens) + len(self.generated)

    @property
    def in_prefill(self) -> bool:
        return self.seen_tokens < len(self.prompt_tokens)

    def remaining_prompt(self) -> np.ndarray:
        return self.prompt_tokens[self.seen_tokens:]


class StateManager:
    """uid -> SequenceDescriptor (reference: DSStateManager ragged_manager.py:19)."""

    def __init__(self, max_tracked_sequences: int = 256,
                 max_context_length: int = 8192):
        self.max_tracked_sequences = max_tracked_sequences
        self.max_context_length = max_context_length
        self._seqs: Dict[int, SequenceDescriptor] = {}

    def __contains__(self, uid: int) -> bool:
        return uid in self._seqs

    def __len__(self) -> int:
        return len(self._seqs)

    def get(self, uid: int) -> Optional[SequenceDescriptor]:
        return self._seqs.get(uid)

    def create(self, uid: int, prompt_tokens) -> SequenceDescriptor:
        if uid in self._seqs:
            raise ValueError(f"uid {uid} already tracked")
        if len(self._seqs) >= self.max_tracked_sequences:
            raise RuntimeError("max_tracked_sequences exceeded")
        prompt = np.asarray(prompt_tokens, dtype=np.int32)
        if len(prompt) > self.max_context_length:
            raise ValueError(f"prompt length {len(prompt)} > max context "
                             f"{self.max_context_length}")
        seq = SequenceDescriptor(uid=uid, prompt_tokens=prompt)
        self._seqs[uid] = seq
        return seq

    def pop(self, uid: int) -> SequenceDescriptor:
        return self._seqs.pop(uid)

    def all(self) -> List[SequenceDescriptor]:
        return list(self._seqs.values())

    def running(self) -> List[SequenceDescriptor]:
        return [s for s in self._seqs.values() if not s.done]

    def decoding(self) -> List[SequenceDescriptor]:
        return [s for s in self._seqs.values()
                if not s.done and not s.paused and not s.in_prefill]

    def prefilling(self) -> List[SequenceDescriptor]:
        return [s for s in self._seqs.values()
                if not s.done and not s.paused and s.in_prefill]
