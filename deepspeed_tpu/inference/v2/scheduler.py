"""Dynamic SplitFuse scheduling.

Reference analog: ``deepspeed/inference/v2/scheduling_utils.py`` + the admission
logic in ``engine_v2.py:158,184`` (``query``/``can_schedule``): each engine step
carries a fixed token budget; running decodes get 1 token each, remaining budget is
filled by *chunks* of pending prefills (long prompts split across steps — SplitFuse).

TPU adaptation: chunk sizes snap to a bucket ladder so every distinct compiled
shape is reused (XLA static shapes); decodes batch into a padded [max_batch] call.
"""

import dataclasses
from typing import List, Optional, Sequence, Tuple

from deepspeed_tpu.inference.v2.ragged_manager import SequenceDescriptor


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    max_tokens_per_step: int = 2048      # SplitFuse token budget
    max_decode_batch: int = 64
    prefill_buckets: Tuple[int, ...] = (128, 256, 512, 1024, 2048)


@dataclasses.dataclass
class PrefillChunk:
    seq: SequenceDescriptor
    start: int           # token offset into the sequence
    length: int          # real tokens this chunk
    bucket: int          # padded compile shape


@dataclasses.dataclass
class StepPlan:
    decode_seqs: List[SequenceDescriptor]
    prefill_chunks: List[PrefillChunk]

    @property
    def empty(self) -> bool:
        return not self.decode_seqs and not self.prefill_chunks


def snap_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def plan_step(decoding: List[SequenceDescriptor],
              prefilling: List[SequenceDescriptor],
              cfg: SchedulerConfig) -> StepPlan:
    """Build one step's work: decodes first (latency), then prefill chunks up to
    the token budget (reference: SplitFuse composition in engine_v2.put)."""
    decodes = decoding[:cfg.max_decode_batch]
    budget = cfg.max_tokens_per_step - len(decodes)
    chunks: List[PrefillChunk] = []
    for seq in prefilling:
        if budget < cfg.prefill_buckets[0] // 2 and chunks:
            break
        remaining = len(seq.prompt_tokens) - seq.seen_tokens
        take = min(remaining, budget, cfg.prefill_buckets[-1])
        if take <= 0:
            break
        bucket = snap_bucket(take, cfg.prefill_buckets)
        chunks.append(PrefillChunk(seq=seq, start=seq.seen_tokens,
                                   length=take, bucket=bucket))
        budget -= take
    return StepPlan(decode_seqs=decodes, prefill_chunks=chunks)
