"""Dynamic SplitFuse scheduling.

Reference analog: ``deepspeed/inference/v2/scheduling_utils.py`` + the admission
logic in ``engine_v2.py:158,184`` (``query``/``can_schedule``): each engine step
carries a fixed token budget; running decodes get 1 token each, remaining budget is
filled by *chunks* of pending prefills (long prompts split across steps — SplitFuse).

TPU adaptation: chunk sizes snap to a bucket ladder so every distinct compiled
shape is reused (XLA static shapes); decodes batch into a padded [max_batch] call.
"""

import dataclasses
from typing import List, Optional, Sequence, Tuple

from deepspeed_tpu.inference.v2.ragged_manager import SequenceDescriptor


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    max_tokens_per_step: int = 2048      # SplitFuse token budget
    max_decode_batch: int = 64
    prefill_buckets: Tuple[int, ...] = (128, 256, 512, 1024, 2048)
    # decode-first chunk cap: at most this many prefill tokens per step, so
    # chunked prefill interleaves with decode and TPOT never spikes behind a
    # long prompt. 0 (default) = uncapped, bit-identical pre-cap planning.
    prefill_chunk_tokens: int = 0


@dataclasses.dataclass
class PrefillChunk:
    seq: SequenceDescriptor
    start: int           # token offset into the sequence
    length: int          # real tokens this chunk
    bucket: int          # padded compile shape


@dataclasses.dataclass
class StepPlan:
    decode_seqs: List[SequenceDescriptor]
    prefill_chunks: List[PrefillChunk]

    @property
    def empty(self) -> bool:
        return not self.decode_seqs and not self.prefill_chunks


def snap_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def plan_step(decoding: List[SequenceDescriptor],
              prefilling: List[SequenceDescriptor],
              cfg: SchedulerConfig,
              block_tokens: int = 0) -> StepPlan:
    """Build one step's work: decodes first (latency), then prefill chunks up to
    the token budget (reference: SplitFuse composition in engine_v2.put).

    With ``cfg.prefill_chunk_tokens > 0`` the decode-first cap applies: total
    prefill tokens this step never exceed the cap, and mid-prompt chunk
    boundaries are rounded DOWN to ``block_tokens`` multiples (KV-block /
    PrefixCache granularity — a chunk ending mid-block would strand a
    partial page no later hit or handoff could adopt). Buckets are unchanged,
    so capped chunks reuse the warm compile ladder. Cap off (0, default) is
    bit-identical to pre-cap planning."""
    cap = int(cfg.prefill_chunk_tokens)
    decodes = decoding[:cfg.max_decode_batch]
    budget = cfg.max_tokens_per_step - len(decodes)
    if cap > 0:
        budget = min(budget, cap)
    chunks: List[PrefillChunk] = []
    for seq in prefilling:
        if budget < cfg.prefill_buckets[0] // 2 and chunks:
            break
        remaining = len(seq.prompt_tokens) - seq.seen_tokens
        take = min(remaining, budget, cfg.prefill_buckets[-1])
        if cap > 0 and block_tokens > 0 and take < remaining:
            # a capped mid-prompt boundary snaps to KV-block granularity;
            # when the leftover budget can't cover one block, the prompt
            # waits a tick (decodes keep the step — that's the point)
            take -= take % block_tokens
        if take <= 0:
            break
        bucket = snap_bucket(take, cfg.prefill_buckets)
        chunks.append(PrefillChunk(seq=seq, start=seq.seen_tokens,
                                   length=take, bucket=bucket))
        budget -= take
    return StepPlan(decode_seqs=decodes, prefill_chunks=chunks)
