"""Paged (blocked) KV cache on device.

Reference analog: ``deepspeed/inference/v2/ragged/kv_cache.py:40``
(``BlockedKVCache``) — a pool of fixed-size KV blocks per layer, reserved through a
``BlockedAllocator``. TPU layout is **head-major**
[kv_heads, num_blocks, block_size, head_dim], so one page of one KV head is a
contiguous (block_size, head_dim) tile — the shape the Pallas paged-attention
kernel DMAs per grid step (``ops/pallas/paged_attention.py``); shard over
``tensor`` on the leading heads dim. Block writes are ``.at[].set`` scatters
inside the jitted step; reads either go through the kernel (block table in
scalar prefetch) or gather a contiguous context window (CPU fallback).
"""

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.v2.blocked_allocator import BlockedAllocator


@dataclasses.dataclass
class KVCacheConfig:
    num_layers: int
    num_kv_heads: int
    head_dim: int
    block_size: int = 64
    num_blocks: int = 256
    dtype: any = jnp.bfloat16


class BlockedKVCache:
    def __init__(self, cfg: KVCacheConfig):
        self.cfg = cfg
        # last block reserved as the trash target for padding-token writes
        # (see llama_decode.py); never handed out by the allocator
        self.allocator = BlockedAllocator(cfg.num_blocks - 1)
        # [L, 2(kv), H_kv, num_blocks, block_size, D] (head-major pages)
        self.data = jnp.zeros(
            (cfg.num_layers, 2, cfg.num_kv_heads, cfg.num_blocks,
             cfg.block_size, cfg.head_dim), cfg.dtype)
        # fp8 pages carry a per-(layer, k/v, head, page) fp32 scale: stored
        # value = real / scale, grown monotonically as outliers arrive (the
        # whole page is requantized under the new scale on growth). The
        # reference fp quantizer is group-scaled the same way
        # (csrc/fp_quantizer/fp_quantize.cu, group absmax).
        self.scales = (jnp.ones(
            (cfg.num_layers, 2, cfg.num_kv_heads, cfg.num_blocks),
            jnp.float32) if cfg.dtype == jnp.float8_e4m3fn else None)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    def blocks_needed(self, num_tokens: int) -> int:
        return int(np.ceil(num_tokens / self.cfg.block_size))

    def reserve(self, num_blocks: int) -> List[int]:
        """reference: kv_cache.py:144 reserve."""
        return self.allocator.allocate(num_blocks)

    def release(self, blocks: List[int],
                pinned: Optional[Sequence[int]] = None) -> None:
        """Free blocks back to the allocator. ``pinned`` names pages the
        prefix cache still holds readers on (refcount > 0): those are
        skipped ENTIRELY — not freed and, critically, not scale-reset.
        One reader of a shared fp8 page releasing its block list must
        not clobber the surviving readers' scales (a reset would silently
        re-interpret their stored values under the wrong scale)."""
        if pinned:
            keep = set(pinned)
            blocks = [b for b in blocks if b not in keep]
        self.allocator.free(blocks)
        if self.scales is not None and blocks:
            # reset released pages' scales: a page freed by a sequence with
            # outlier K/V must not impose its grown scale (= lost precision)
            # on the next sequence the allocator hands it to
            self.scales = self.scales.at[
                :, :, :, jnp.asarray(blocks)].set(1.0)

    # ------------------------------------------------------------------
    # host offload tier (serving demotion/promotion; see kv_offload.py)
    # ------------------------------------------------------------------
    def gather_blocks(self, blocks: List[int]
                      ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Copy the listed blocks' pages (and, for fp8, their scales) to
        host ndarrays ``[L, 2, H_kv, len(blocks), bs, D]``. A deliberate
        device->host transfer — demotion runs OFF the per-tick fast path,
        only when the serving tier policy decides to spill."""
        idx = np.asarray(blocks, np.int32)
        data = np.asarray(self.data[:, :, :, idx])
        scales = (np.asarray(self.scales[:, :, :, idx])
                  if self.scales is not None else None)
        return data, scales

    def scatter_blocks(self, blocks: List[int], data: np.ndarray,
                       scales: Optional[np.ndarray] = None) -> None:
        """Write gathered pages back into (possibly different) blocks —
        the promotion path. fp8 scales are restored alongside the pages,
        so a promoted sequence's quantization state is bit-identical to
        what it was at demotion."""
        idx = jnp.asarray(np.asarray(blocks, np.int32))
        self.data = self.data.at[:, :, :, idx].set(
            jnp.asarray(data, self.cfg.dtype))
        if self.scales is not None and scales is not None:
            self.scales = self.scales.at[:, :, :, idx].set(
                jnp.asarray(scales, jnp.float32))


FP8_MAX = 448.0     # float8_e4m3fn max finite; overflow casts become NaN


def cast_to_page_dtype(x, dtype):
    """Cast K/V to the page dtype; fp8 pages clamp to the finite e4m3 range
    first (e4m3 has no inf — out-of-range casts would write NaN and poison
    the page for the rest of the sequence)."""
    if dtype == jnp.float8_e4m3fn:
        x = jnp.clip(x, -FP8_MAX, FP8_MAX)
    return x.astype(dtype)


def write_kv_scaled(cache_data, scales, layer: int, kv: int, vals,
                    block_ids, offsets, touched_pages):
    """Scatter new tokens into fp8 pages under per-(head, page) scales.

    cache_data: [L, 2, H, NB, bs, D] fp8; scales: [L, 2, H, NB] fp32;
    vals: [T, H, D] compute dtype; block_ids/offsets: [T] target slot per
    token; touched_pages: [P] page ids covering ``set(block_ids)`` —
    duplicates are allowed only if they carry identical updates (trash-padded
    table slots / clamped slices satisfy this), because the requantize
    scatter writes them all.

    A new token whose |value| exceeds the page's committed range GROWS the
    page scale (``new = max(old, absmax/448)``) and the whole page is
    requantized under it (one small gather-scale-scatter — pages are
    (bs, D) tiles); pages without outliers keep ratio 1.0 and the fp8→fp32→
    fp8 round-trip is exact. Scales never shrink while a page is live; the
    allocator resets them to 1.0 on release (``BlockedKVCache.release``).
    """
    f32 = jnp.float32
    old_s = scales[layer, kv]                                   # [H, NB]
    absmax = jnp.max(jnp.abs(vals.astype(f32)), axis=-1)        # [T, H]
    page_max = jnp.zeros_like(old_s).at[:, block_ids].max(absmax.T)
    # the trash page (num_blocks-1, where bucket-padding rows land) is never
    # allocated or released, so letting it join the scatter-max would grow
    # its scale monotonically for the cache's lifetime — silent state drift
    # with no output effect (trash slots are always causally masked)
    page_max = page_max.at[:, -1].set(0.0)
    new_s = jnp.maximum(old_s, page_max / FP8_MAX)              # [H, NB]

    # requantize touched pages under the grown scale — predicated: in
    # steady-state decode no scale grows and the full-page read-modify-write
    # would be pure wasted HBM bandwidth in the hot path
    def requant(data):
        old_tile = data[layer, kv, :, touched_pages]            # [P, H, bs, D]
        ratio = (old_s / new_s)[:, touched_pages].T             # [P, H]
        tile = old_tile.astype(f32) * ratio[..., None, None]
        return data.at[layer, kv, :, touched_pages].set(
            tile.astype(data.dtype))

    cache_data = jax.lax.cond(jnp.any(new_s > old_s), requant,
                              lambda data: data, cache_data)
    # write the new tokens under the new scale
    tok_scale = new_s[:, block_ids].T                           # [T, H]
    cache_data = cache_data.at[layer, kv, :, block_ids, offsets].set(
        cast_to_page_dtype(vals.astype(f32) / tok_scale[..., None],
                           cache_data.dtype))
    return cache_data, scales.at[layer, kv].set(new_s)


def write_kv_block_tokens(cache_data, layer: int, k_new, v_new, block_ids,
                          start_pos: int, block_size: int):
    """Scatter new K/V tokens into their blocks (jit-friendly building block).

    k_new/v_new: [T, H, D]; block_ids: [T] target block per token;
    offsets derived from positions. Used by the engine's compiled step via
    flat (block, offset) indices.
    """
    t = k_new.shape[0]
    positions = start_pos + jnp.arange(t)
    offsets = positions % block_size
    # head-major pages: advanced (block, offset) dims land first, so the
    # indexed view is [T, H, D] — matching k_new directly
    cache_data = cache_data.at[layer, 0, :, block_ids, offsets].set(
        cast_to_page_dtype(k_new, cache_data.dtype))
    cache_data = cache_data.at[layer, 1, :, block_ids, offsets].set(
        cast_to_page_dtype(v_new, cache_data.dtype))
    return cache_data
