"""Paged (blocked) KV cache on device.

Reference analog: ``deepspeed/inference/v2/ragged/kv_cache.py:40``
(``BlockedKVCache``) — a pool of fixed-size KV blocks per layer, reserved through a
``BlockedAllocator``. TPU layout is **head-major**
[kv_heads, num_blocks, block_size, head_dim], so one page of one KV head is a
contiguous (block_size, head_dim) tile — the shape the Pallas paged-attention
kernel DMAs per grid step (``ops/pallas/paged_attention.py``); shard over
``tensor`` on the leading heads dim. Block writes are ``.at[].set`` scatters
inside the jitted step; reads either go through the kernel (block table in
scalar prefetch) or gather a contiguous context window (CPU fallback).
"""

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.v2.blocked_allocator import BlockedAllocator


@dataclasses.dataclass
class KVCacheConfig:
    num_layers: int
    num_kv_heads: int
    head_dim: int
    block_size: int = 64
    num_blocks: int = 256
    dtype: any = jnp.bfloat16


class BlockedKVCache:
    def __init__(self, cfg: KVCacheConfig):
        self.cfg = cfg
        # last block reserved as the trash target for padding-token writes
        # (see llama_decode.py); never handed out by the allocator
        self.allocator = BlockedAllocator(cfg.num_blocks - 1)
        # [L, 2(kv), H_kv, num_blocks, block_size, D] (head-major pages)
        self.data = jnp.zeros(
            (cfg.num_layers, 2, cfg.num_kv_heads, cfg.num_blocks,
             cfg.block_size, cfg.head_dim), cfg.dtype)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    def blocks_needed(self, num_tokens: int) -> int:
        return int(np.ceil(num_tokens / self.cfg.block_size))

    def reserve(self, num_blocks: int) -> List[int]:
        """reference: kv_cache.py:144 reserve."""
        return self.allocator.allocate(num_blocks)

    def release(self, blocks: List[int]) -> None:
        self.allocator.free(blocks)


FP8_MAX = 448.0     # float8_e4m3fn max finite; overflow casts become NaN


def cast_to_page_dtype(x, dtype):
    """Cast K/V to the page dtype; fp8 pages clamp to the finite e4m3 range
    first (e4m3 has no inf — out-of-range casts would write NaN and poison
    the page for the rest of the sequence)."""
    if dtype == jnp.float8_e4m3fn:
        x = jnp.clip(x, -FP8_MAX, FP8_MAX)
    return x.astype(dtype)


def write_kv_block_tokens(cache_data, layer: int, k_new, v_new, block_ids,
                          start_pos: int, block_size: int):
    """Scatter new K/V tokens into their blocks (jit-friendly building block).

    k_new/v_new: [T, H, D]; block_ids: [T] target block per token;
    offsets derived from positions. Used by the engine's compiled step via
    flat (block, offset) indices.
    """
    t = k_new.shape[0]
    positions = start_pos + jnp.arange(t)
    offsets = positions % block_size
    # head-major pages: advanced (block, offset) dims land first, so the
    # indexed view is [T, H, D] — matching k_new directly
    cache_data = cache_data.at[layer, 0, :, block_ids, offsets].set(
        cast_to_page_dtype(k_new, cache_data.dtype))
    cache_data = cache_data.at[layer, 1, :, block_ids, offsets].set(
        cast_to_page_dtype(v_new, cache_data.dtype))
    return cache_data
