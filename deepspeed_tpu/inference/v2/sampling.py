"""Token sampling for serving: greedy / temperature / top-k / top-p.

Reference analog: the reference's FastGen pipeline samples in MII; the engine
itself shipped argmax. Here sampling is a first-class jitted device-side op so
the serving loop fetches only the sampled token ids ([B] int32, a few bytes)
instead of the full [B, vocab] logits every step — on a tunneled or multi-host
topology the logits D2H round trip is the decode bottleneck, not compute.
"""

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    temperature: float = 0.0     # 0 -> greedy argmax
    top_k: int = 0               # 0 -> disabled
    top_p: float = 1.0           # 1 -> disabled
    seed: int = 0


@partial(jax.jit, static_argnames=("cfg",))
def sample_tokens(logits, key, cfg: SamplingConfig):
    """logits: [B, V] fp32 -> [B] int32 sampled token ids (device-side)."""
    if cfg.temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) / cfg.temperature
    if cfg.top_k and cfg.top_k > 0:
        kth = jax.lax.top_k(scaled, cfg.top_k)[0][:, -1:]
        scaled = jnp.where(scaled < kth, NEG_INF, scaled)
    if cfg.top_p < 1.0:
        sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass >= top_p; the top-1
        # token is kept unconditionally so top_p <= 0 degrades to greedy
        # instead of masking every token
        keep = cum - probs < cfg.top_p
        keep = keep.at[:, 0].set(True)
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf),
                         axis=-1, keepdims=True)
        scaled = jnp.where(scaled < cutoff, NEG_INF, scaled)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


# compile-event ledger: sampler recompiles (a new [B, V] bucket or a new
# SamplingConfig) are real serve-tick stalls too — watched like the step fns
from deepspeed_tpu.telemetry.compiles import watch_jit  # noqa: E402

sample_tokens = watch_jit(sample_tokens, "sampling.sample_tokens")
