"""Generic paged serving loop: policy-parameterized prefill + decode.

Reference analog: ``inference/v2/model_implementations/inference_transformer_base.py``
— the shared ragged forward skeleton that per-arch containers plug into. Here the
skeleton is jitted pure functions over (policy, config) static args; the
policy (``modules.py``) contributes embed/block/unembed and the loop owns KV
cache writes + the Pallas paged attention (``llama_decode._paged_attn``).
"""

from functools import partial

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.kv_cache import (cast_to_page_dtype,
                                                 write_kv_scaled)
from deepspeed_tpu.inference.v2.llama_decode import _paged_attn


def _chunk_states(params, cache_data, tokens, start, block_table, true_len,
                  policy, cfg, block_size: int, attn_impl: str):
    """Shared chunk forward: embeds a bucket-padded token chunk, scatters
    each layer's K/V into the pages, attends over the paged context, and
    returns (per-row hidden states [Tb, D], updated cache). ``cache_data``
    may be the plain page pool [L, 2, H, NB, bs, D] or a ``(pages, scales)``
    tuple for scaled fp8 pages (``BlockedKVCache.scales``)."""
    spec = policy.cache_spec(cfg)
    tb = tokens.shape[0]
    mb = block_table.shape[0]
    scaled = isinstance(cache_data, tuple)
    pool = cache_data[0] if scaled else cache_data

    positions = start + jnp.arange(tb)
    safe_pos = jnp.minimum(positions, spec.max_seq_len - 1)
    tok_block = jnp.where(jnp.arange(tb) < true_len,
                          block_table[jnp.minimum(safe_pos // block_size, mb - 1)],
                          pool.shape[3] - 1)
    tok_off = safe_pos % block_size
    touched = None
    if scaled:
        # pages the chunk's valid tokens can land on: a contiguous table
        # slice (clamp duplicates repeat the same slot — identical updates,
        # safe for write_kv_scaled's requantize scatter). Static worst-case
        # page count: offsets start%bs .. start%bs+tb-1 span up to
        # (tb + bs - 2)//bs + 1 pages — a chunk smaller than a page that
        # crosses a boundary still touches TWO pages (tb//bs+1 missed that)
        touch_idx = jnp.minimum(
            start // block_size +
            jnp.arange((tb + block_size - 2) // block_size + 1), mb - 1)
        touched = block_table[touch_idx]

    x = policy.embed(params, tokens, safe_pos, cfg)

    cache = cache_data
    for i in range(spec.num_layers):
        def attend(q, k, v, i=i, window="spec", softcap=None):
            nonlocal cache
            win = spec.window if window == "spec" else window
            if scaled:
                data, scales = cache
                data, scales = write_kv_scaled(data, scales, i, 0, k,
                                               tok_block, tok_off, touched)
                data, scales = write_kv_scaled(data, scales, i, 1, v,
                                               tok_block, tok_off, touched)
                cache = (data, scales)
                return _paged_attn(q[None], data, i, block_table[None],
                                   jnp.asarray(start).reshape(1), win,
                                   attn_impl, softcap=softcap,
                                   scales=scales)[0]
            cache = cache.at[i, 0, :, tok_block, tok_off].set(
                cast_to_page_dtype(k, cache.dtype))
            cache = cache.at[i, 1, :, tok_block, tok_off].set(
                cast_to_page_dtype(v, cache.dtype))
            return _paged_attn(q[None], cache, i, block_table[None],
                               jnp.asarray(start).reshape(1), win,
                               attn_impl, softcap=softcap)[0]
        x = policy.block(params, i, x, attend, safe_pos, cfg)
    return x, cache


@partial(jax.jit, static_argnames=("policy", "cfg", "block_size", "attn_impl"))
def prefill_chunk_g(params, cache_data, tokens, start, block_table, true_len,
                    policy, cfg, block_size: int, attn_impl: str = "auto"):
    """One sequence, one bucket-padded chunk; returns (last-token logits [V],
    updated cache_data). See llama_decode.prefill_chunk for the argument
    contract — this is the arch-generic version; cache structure in ==
    structure out (plain pool or (pages, scales))."""
    x, cache = _chunk_states(params, cache_data, tokens, start, block_table,
                             true_len, policy, cfg, block_size, attn_impl)
    last = x[jnp.maximum(true_len - 1, 0)]
    logits = policy.unembed(params, last[None], cfg)[0]
    return logits, cache


@partial(jax.jit, static_argnames=("policy", "cfg", "block_size", "attn_impl"))
def verify_chunk_g(params, cache_data, tokens, start, block_table, true_len,
                   policy, cfg, block_size: int, attn_impl: str = "auto"):
    """Speculative-decoding verifier: the same cache-writing chunk forward
    as ``prefill_chunk_g`` but returns logits for EVERY row ([Tb, V]) — row
    i holds the model's prediction for position ``start + i + 1``, so the
    host accepts the longest proposal prefix whose tokens match the argmax
    chain (draft-free prompt-lookup speculation; no reference analog —
    FastGen has no speculative decoding). Rejected rows' K/V writes land at
    positions beyond the accepted context and are invisible (causal masking
    doubles as the context-length mask) until a later step overwrites them."""
    x, cache = _chunk_states(params, cache_data, tokens, start, block_table,
                             true_len, policy, cfg, block_size, attn_impl)
    return policy.unembed(params, x, cfg), cache


@partial(jax.jit, static_argnames=("policy", "cfg", "block_size", "attn_impl"))
def decode_step_g(params, cache_data, tokens, positions, block_tables, valid,
                  policy, cfg, block_size: int, attn_impl: str = "auto"):
    """Batched single-token decode; returns (logits [B, V], updated
    cache_data). See llama_decode.decode_step for the argument contract.
    ``cache_data``: plain pool or ``(pages, scales)`` like prefill_chunk_g."""
    spec = policy.cache_spec(cfg)
    mb = block_tables.shape[1]
    scaled = isinstance(cache_data, tuple)
    pool = cache_data[0] if scaled else cache_data

    safe_pos = jnp.minimum(positions, spec.max_seq_len - 1)
    blk = jnp.where(valid,
                    jnp.take_along_axis(
                        block_tables,
                        jnp.minimum(safe_pos // block_size, mb - 1)[:, None],
                        axis=1)[:, 0],
                    pool.shape[3] - 1)
    off = safe_pos % block_size

    x = policy.embed(params, tokens, safe_pos, cfg)

    cache = cache_data
    for i in range(spec.num_layers):
        def attend(q, k, v, i=i, window="spec", softcap=None):
            nonlocal cache
            win = spec.window if window == "spec" else window
            if scaled:
                # each token touches exactly its own page (invalid rows all
                # write the trash page with identical per-page updates)
                data, scales = cache
                data, scales = write_kv_scaled(data, scales, i, 0, k,
                                               blk, off, blk)
                data, scales = write_kv_scaled(data, scales, i, 1, v,
                                               blk, off, blk)
                cache = (data, scales)
                return _paged_attn(q[:, None], data, i, block_tables,
                                   safe_pos, win, attn_impl,
                                   softcap=softcap, scales=scales)[:, 0]
            cache = cache.at[i, 0, :, blk, off].set(
                cast_to_page_dtype(k, cache.dtype))
            cache = cache.at[i, 1, :, blk, off].set(
                cast_to_page_dtype(v, cache.dtype))
            return _paged_attn(q[:, None], cache, i, block_tables, safe_pos,
                               win, attn_impl, softcap=softcap)[:, 0]
        x = policy.block(params, i, x, attend, safe_pos, cfg)

    logits = policy.unembed(params, x, cfg)
    return logits, cache


# compile-event ledger: every XLA compile of the serving step fns emits an
# ``xla/compile`` instant (fn + shape signature + wall ms) and bumps the
# process compile counter — bench_serve asserts ZERO compiles inside the
# measured window after warmup (telemetry/compiles.py)
from deepspeed_tpu.telemetry.compiles import watch_jit  # noqa: E402

prefill_chunk_g = watch_jit(prefill_chunk_g, "generic_decode.prefill_chunk_g")
verify_chunk_g = watch_jit(verify_chunk_g, "generic_decode.verify_chunk_g")
decode_step_g = watch_jit(decode_step_g, "generic_decode.decode_step_g")
