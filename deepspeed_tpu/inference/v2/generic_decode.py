"""Generic paged serving loop: policy-parameterized prefill + decode.

Reference analog: ``inference/v2/model_implementations/inference_transformer_base.py``
— the shared ragged forward skeleton that per-arch containers plug into. Here the
skeleton is two jitted pure functions over (policy, config) static args; the
policy (``modules.py``) contributes embed/block/unembed and the loop owns KV
cache writes + the Pallas paged attention (``llama_decode._paged_attn``).
"""

from functools import partial

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.kv_cache import cast_to_page_dtype
from deepspeed_tpu.inference.v2.llama_decode import _paged_attn


@partial(jax.jit, static_argnames=("policy", "cfg", "block_size", "attn_impl"))
def prefill_chunk_g(params, cache_data, tokens, start, block_table, true_len,
                    policy, cfg, block_size: int, attn_impl: str = "auto"):
    """One sequence, one bucket-padded chunk; returns (last-token logits [V],
    updated cache_data). See llama_decode.prefill_chunk for the argument
    contract — this is the arch-generic version."""
    spec = policy.cache_spec(cfg)
    tb = tokens.shape[0]
    mb = block_table.shape[0]

    positions = start + jnp.arange(tb)
    safe_pos = jnp.minimum(positions, spec.max_seq_len - 1)
    tok_block = jnp.where(jnp.arange(tb) < true_len,
                          block_table[jnp.minimum(safe_pos // block_size, mb - 1)],
                          cache_data.shape[3] - 1)
    tok_off = safe_pos % block_size

    x = policy.embed(params, tokens, safe_pos, cfg)

    cache = cache_data
    for i in range(spec.num_layers):
        def attend(q, k, v, i=i, window="spec", softcap=None):
            nonlocal cache
            cache = cache.at[i, 0, :, tok_block, tok_off].set(
                cast_to_page_dtype(k, cache.dtype))
            cache = cache.at[i, 1, :, tok_block, tok_off].set(
                cast_to_page_dtype(v, cache.dtype))
            return _paged_attn(q[None], cache, i, block_table[None],
                               jnp.asarray(start).reshape(1),
                               spec.window if window == "spec" else window,
                               attn_impl, softcap=softcap)[0]
        x = policy.block(params, i, x, attend, safe_pos, cfg)

    last = x[jnp.maximum(true_len - 1, 0)]
    logits = policy.unembed(params, last[None], cfg)[0]
    return logits, cache


@partial(jax.jit, static_argnames=("policy", "cfg", "block_size", "attn_impl"))
def decode_step_g(params, cache_data, tokens, positions, block_tables, valid,
                  policy, cfg, block_size: int, attn_impl: str = "auto"):
    """Batched single-token decode; returns (logits [B, V], updated
    cache_data). See llama_decode.decode_step for the argument contract."""
    spec = policy.cache_spec(cfg)
    mb = block_tables.shape[1]

    safe_pos = jnp.minimum(positions, spec.max_seq_len - 1)
    blk = jnp.where(valid,
                    jnp.take_along_axis(
                        block_tables,
                        jnp.minimum(safe_pos // block_size, mb - 1)[:, None],
                        axis=1)[:, 0],
                    cache_data.shape[3] - 1)
    off = safe_pos % block_size

    x = policy.embed(params, tokens, safe_pos, cfg)

    cache = cache_data
    for i in range(spec.num_layers):
        def attend(q, k, v, i=i, window="spec", softcap=None):
            nonlocal cache
            cache = cache.at[i, 0, :, blk, off].set(
                cast_to_page_dtype(k, cache.dtype))
            cache = cache.at[i, 1, :, blk, off].set(
                cast_to_page_dtype(v, cache.dtype))
            return _paged_attn(q[:, None], cache, i, block_tables, safe_pos,
                               spec.window if window == "spec" else window,
                               attn_impl, softcap=softcap)[:, 0]
        x = policy.block(params, i, x, attend, safe_pos, cfg)

    logits = policy.unembed(params, x, cfg)
    return logits, cache
