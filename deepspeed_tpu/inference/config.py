"""Inference config.

Reference analog: ``deepspeed/inference/config.py`` (``DeepSpeedInferenceConfig``).
TP degree maps to the mesh ``tensor`` axis; dtype to the compute dtype.
"""

from typing import Any, Dict, Optional

from pydantic import Field

from deepspeed_tpu.config.config_utils import DeepSpeedTPUConfigModel


class QuantizationConfig(DeepSpeedTPUConfigModel):
    enabled: bool = False
    bits: int = 8


class InferenceConfig(DeepSpeedTPUConfigModel):
    dtype: str = "bfloat16"
    tensor_parallel: Dict[str, Any] = Field(default_factory=lambda: {"tp_size": 1})
    max_out_tokens: int = 1024
    min_out_tokens: int = 1
    replace_with_kernel_inject: bool = True   # accepted for parity; kernels are XLA/Pallas
    quant: QuantizationConfig = Field(default_factory=QuantizationConfig)
    checkpoint: Optional[str] = None
    enable_cuda_graph: bool = False            # parity no-op: XLA compiles everything

    @property
    def tp_size(self) -> int:
        return int(self.tensor_parallel.get("tp_size", 1))
