"""LoRA / quantization configs (reference: ``deepspeed/linear/config.py``)."""

from dataclasses import dataclass, field
from typing import List


@dataclass
class LoRAConfig:
    """reference: linear/config.py:11. ``base_weight_sharding`` on TPU maps to
    sharding the frozen base over the ZeRO ``fsdp`` mesh axes (the reference
    manually flattens and narrows per rank); ``offload`` maps to the engine's
    host-offload tier."""
    lora_r: int = 64
    lora_alpha: float = 16.0
    base_weight_sharding: int = 1
    offload: bool = False
    offload_ratio: float = 0.0
    delay_lora_init: bool = False
    target_mods: List[str] = field(default_factory=lambda: [
        "q_proj", "k_proj", "v_proj", "o_proj", "gate_proj", "up_proj", "down_proj"])


@dataclass
class QuantizationConfig:
    """reference: linear/config.py:37. ``q_bits`` 8 or 4 (grouped symmetric int);
    ``mantissa_bits`` kept for config parity (the reference's FP6/FP12 formats
    map to int quantization grain here — TPU has no FP6 datapath; fp8 lives in
    the Pallas quant kernels)."""
    q_bits: int = 8
    mantissa_bits: int = 3
    group_size: int = 512
