"""Optimized linear layers: LoRA + quantized frozen base.

Reference analog: ``deepspeed/linear/`` (``optimized_linear.py:18,76``
OptimizedLinear / LoRAOptimizedLinear, ``config.py`` LoRAConfig /
QuantizationConfig, ``quantization.py`` QuantizedParameter).
"""

from deepspeed_tpu.linear.config import LoRAConfig, QuantizationConfig
from deepspeed_tpu.linear.optimized_linear import (
    LoRAOptimizedLinear, OptimizedLinear, QuantizedLinear, lora_trainable_mask,
    make_lora_optimizer)

__all__ = ["LoRAConfig", "QuantizationConfig", "OptimizedLinear",
           "QuantizedLinear", "LoRAOptimizedLinear", "lora_trainable_mask",
           "make_lora_optimizer"]
