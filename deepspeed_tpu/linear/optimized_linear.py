"""Optimized / LoRA / quantized linear layers (flax).

Reference analog: ``deepspeed/linear/optimized_linear.py`` —
``OptimizedLinear.__new__`` (:18) dispatches to nn.Linear / QuantizedLinear /
LoRAOptimizedLinear (:76); LoRA A initialized kaiming-uniform, B zeros, scale
``alpha/r``; the base weight is frozen (``requires_grad=False``) and optionally
stored quantized (``quantization.py QuantizedParameter``) and/or sharded across
ranks (``base_weight_sharding``).

TPU-native differences:
- the frozen base is a flax variable in the ``frozen_params`` collection —
  excluded from ``params`` so gradients are never computed for it (JAX's
  equivalent of requires_grad=False, enforced by structure instead of flags);
- quantized storage is grouped symmetric int8/int4 values + fp32 scales, both in
  ``frozen_params``; dequantize fuses into the matmul under XLA;
- ``base_weight_sharding`` is a PartitionSpec annotation over the ``fsdp`` mesh
  axes (XLA shards/gathers; no manual flatten-narrow);
- for trainers that keep everything in one ``params`` tree (HF-style LoRA),
  ``lora_trainable_mask`` + ``make_lora_optimizer`` mask non-LoRA leaves out of
  the update (optax.masked) — update-freezing equivalent to the reference.
"""

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.linear.config import LoRAConfig, QuantizationConfig
from deepspeed_tpu.utils.logging import warning_once

LORA_A = "lora_a"
LORA_B = "lora_b"


def _quantize_grouped(w: jnp.ndarray, q_bits: int, group_size: int):
    """Grouped symmetric int quantization: returns (int8 codes, fp32 scales).
    Codes use the int8 container even for q_bits<8 (XLA has no int4 storage on
    all backends; the value range is what matters for accuracy)."""
    qmax = 2.0 ** (q_bits - 1) - 1
    flat = w.astype(jnp.float32).ravel()
    pad = (-flat.size) % group_size
    flat = jnp.pad(flat, (0, pad))
    g = flat.reshape(-1, group_size)
    scale = jnp.maximum(jnp.max(jnp.abs(g), axis=1, keepdims=True) / qmax, 1e-12)
    codes = jnp.clip(jnp.round(g / scale), -qmax - 1, qmax).astype(jnp.int8)
    return codes, scale


def _dequantize_grouped(codes: jnp.ndarray, scale: jnp.ndarray, shape,
                        dtype=jnp.bfloat16) -> jnp.ndarray:
    flat = (codes.astype(jnp.float32) * scale).ravel()
    n = 1
    for d in shape:
        n *= d
    return flat[:n].reshape(shape).astype(dtype)


class QuantizedLinear(nn.Module):
    """Frozen quantized-weight linear (reference: QuantizedLinear,
    optimized_linear.py:66 dispatch; quantization.py QuantizedParameter)."""
    input_dim: int
    output_dim: int
    use_bias: bool = False
    quantization_config: Optional[QuantizationConfig] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        qc = self.quantization_config or QuantizationConfig()

        def init_quantized(key):
            w = jax.nn.initializers.xavier_uniform()(
                key, (self.input_dim, self.output_dim), jnp.float32)
            return _quantize_grouped(w, qc.q_bits, qc.group_size)

        key = self.make_rng("params") if self.has_rng("params") else jax.random.PRNGKey(0)
        quant = self.variable("frozen_params", "weight_q",
                              lambda: init_quantized(key))
        codes, scale = quant.value
        w = _dequantize_grouped(codes, scale,
                                (self.input_dim, self.output_dim), self.dtype)
        y = x.astype(self.dtype) @ w
        if self.use_bias:
            b = self.param("bias", jax.nn.initializers.zeros, (self.output_dim,),
                           self.dtype)
            y = y + b
        return y


class LoRAOptimizedLinear(nn.Module):
    """Frozen (optionally quantized) base + trainable LoRA adapters
    (reference: LoRAOptimizedLinear, optimized_linear.py:76; A kaiming, B zeros,
    scale alpha/r per init_lora :125-160)."""
    input_dim: int
    output_dim: int
    use_bias: bool = False
    lora_config: Optional[LoRAConfig] = None
    quantization_config: Optional[QuantizationConfig] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        lc = self.lora_config or LoRAConfig()
        if self.use_bias:
            raise ValueError("bias=True is not supported by LoRAOptimizedLinear "
                             "(reference parity)")
        x = x.astype(self.dtype)

        key = self.make_rng("params") if self.has_rng("params") else jax.random.PRNGKey(0)
        if self.quantization_config is not None:
            qc = self.quantization_config

            def init_q():
                w = jax.nn.initializers.xavier_uniform()(
                    key, (self.input_dim, self.output_dim), jnp.float32)
                return _quantize_grouped(w, qc.q_bits, qc.group_size)
            quant = self.variable("frozen_params", "weight_q", init_q)
            base = _dequantize_grouped(quant.value[0], quant.value[1],
                                       (self.input_dim, self.output_dim), self.dtype)
        else:
            frozen = self.variable(
                "frozen_params", "weight",
                lambda: jax.nn.initializers.xavier_uniform()(
                    key, (self.input_dim, self.output_dim), jnp.float32))
            base = frozen.value.astype(self.dtype)

        # base_weight_sharding: annotate for the fsdp axes present in the
        # active mesh; XLA shards storage and gathers at use (the reference
        # narrows a flattened weight per rank)
        if lc.base_weight_sharding > 1:
            am = jax.sharding.get_abstract_mesh()
            mesh_axes = [n for n, _ in getattr(am, "shape_tuple", ())]
            axes = tuple(a for a in ("fsdp_out", "fsdp") if a in mesh_axes)
            if axes:
                base = jax.lax.with_sharding_constraint(
                    base, jax.sharding.PartitionSpec(axes, None))
            else:
                warning_once(
                    "base_weight_sharding>1 requires running under a mesh with "
                    "fsdp axes (jax.sharding.use_mesh / engine mesh); ignored")

        # LoRA adapters (trainable, in the regular params collection)
        a = self.param(LORA_A,
                       jax.nn.initializers.variance_scaling(
                           1.0 / 3.0, "fan_in", "uniform"),  # kaiming a=sqrt(5)
                       (self.input_dim, lc.lora_r), self.dtype)
        b = self.param(LORA_B, jax.nn.initializers.zeros,
                       (lc.lora_r, self.output_dim), self.dtype)
        scaling = lc.lora_alpha / lc.lora_r
        return x @ base + (x @ a) @ b * scaling


def OptimizedLinear(input_dim: int,
                    output_dim: int,
                    bias: bool = False,
                    lora_config: Optional[LoRAConfig] = None,
                    quantization_config: Optional[QuantizationConfig] = None,
                    dtype: Any = jnp.bfloat16) -> nn.Module:
    """Factory matching the reference dispatch (optimized_linear.py:18):
    plain Dense / QuantizedLinear / LoRAOptimizedLinear."""
    if lora_config is None and quantization_config is None:
        return nn.Dense(features=output_dim, use_bias=bias, dtype=dtype,
                        param_dtype=dtype)
    if lora_config is not None:
        return LoRAOptimizedLinear(input_dim=input_dim, output_dim=output_dim,
                                   use_bias=bias, lora_config=lora_config,
                                   quantization_config=quantization_config,
                                   dtype=dtype)
    return QuantizedLinear(input_dim=input_dim, output_dim=output_dim,
                           use_bias=bias, quantization_config=quantization_config,
                           dtype=dtype)


def lora_trainable_mask(params, target_mods=None):
    """Bool pytree: True for LoRA adapter leaves (and nothing else).

    Without ``target_mods``, a leaf is an adapter iff its key is exactly
    ``lora_a``/``lora_b``. With ``target_mods`` (reference
    LoRAConfig.target_mods), HF-style trees are supported: any leaf whose key
    contains "lora" AND whose path contains one of the target module names is
    trainable (e.g. ``.../q_proj/lora_A/kernel``)."""
    def mask(path, leaf):
        names = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
        if LORA_A in names or LORA_B in names:
            return True
        if target_mods:
            has_lora = any("lora" in n.lower() for n in names)
            in_target = any(any(t in n for n in names) for t in target_mods)
            return has_lora and in_target
        return False
    return jax.tree_util.tree_map_with_path(mask, params)


def make_lora_optimizer(tx: optax.GradientTransformation, params
                        ) -> optax.GradientTransformation:
    """Freeze every non-LoRA leaf (reference: requires_grad=False on base):
    masked updates so frozen leaves get zero deltas and no optimizer state."""
    mask = lora_trainable_mask(params)
    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()},
        jax.tree.map(lambda m: "train" if m else "freeze", mask))
