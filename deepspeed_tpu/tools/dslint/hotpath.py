"""The hot-path registry DS002 enforces.

One place — shared by the rule, the CLI, and ``tests/test_no_hot_sync.py``
(now a thin wrapper over this registry) — naming every function that runs
on the per-step/per-tick fast path and therefore must never host-sync.
Growing a registry entry is a conscious, reviewed decision; a registered
function disappearing (renamed without updating the registry) is itself a
DS002 finding so the tripwire can't silently rot.

Spec fields:

  path            repo-relative file the spec applies to
  cls             class whose methods are listed (None = module functions)
  hot_functions   fully forbidden: any host sync inside is a finding
  guard_branches  (function, guard_attr): only ``if ...<guard_attr>``
                  branches of that function are checked (async fan-in
                  points whose synchronous fallback MAY sync)
  confine         attr call -> functions allowed to use it anywhere in the
                  file (e.g. ``device_get`` confined to the designated
                  drain); any other function using it is a finding
  forbidden       call names treated as host syncs for this spec
"""

import dataclasses
from typing import Dict, Optional, Tuple

#: calls that force (or can force) a device->host sync. ``float``/``int``/
#: ``bool`` on a jax.Array block on the value; ``.item()``/``np.asarray``/
#: ``np.array`` copy to host; device_get / block_until_ready are explicit.
DEFAULT_FORBIDDEN: Tuple[str, ...] = (
    "float", ".item", ".device_get", ".block_until_ready",
    ".copy_to_host_async", "np.asarray", "np.array",
)

#: the engine hot path legitimately touches numpy on HOST batches before
#: they are staged (stack_microbatches/_shard_batch) — np.* stays allowed
#: there; device syncs stay forbidden.
ENGINE_FORBIDDEN: Tuple[str, ...] = (
    "float", ".item", ".device_get", ".block_until_ready",
    ".copy_to_host_async",
)

#: for the engine spec itself `.device_get` is enforced by the file-wide
#: confine entry (which covers the hot functions too) — listing it here as
#: well would double-report one violation under two baseline anchors
ENGINE_HOT_FORBIDDEN: Tuple[str, ...] = (
    "float", ".item", ".block_until_ready", ".copy_to_host_async",
)


@dataclasses.dataclass(frozen=True)
class HotPathSpec:
    path: str
    cls: Optional[str]
    hot_functions: Tuple[str, ...] = ()
    guard_branches: Tuple[Tuple[str, str], ...] = ()
    confine: Optional[Dict[str, Tuple[str, ...]]] = None
    forbidden: Tuple[str, ...] = DEFAULT_FORBIDDEN


HOT_PATHS: Tuple[HotPathSpec, ...] = (
    # the training engine's per-step fused path: everything that runs on
    # EVERY train_batch call. Readback belongs ONLY in _drain_metric_ring
    # (the designated drain) and the explicitly host-synchronous paths.
    HotPathSpec(
        path="deepspeed_tpu/runtime/engine.py",
        cls="DeepSpeedTPUEngine",
        hot_functions=(
            "train_batch",
            "stack_microbatches",
            "_shard_batch",
            "_advance_data_schedules",
            "_ensure_prefetcher",
            # per-step comm/overlap retro-span emission (comm_compression):
            # append-only analytic schedule spans, never a device touch
            "_emit_overlap_spans",
        ),
        # the async push branch of _record_metrics queues device arrays
        # verbatim — any transfer there re-serializes every step; the
        # synchronous fallback branch MAY sync (it is the designed sync path)
        guard_branches=(("_record_metrics", "_async_enabled"),),
        confine={
            ".device_get": (
                "_drain_metric_ring",           # THE drain
                "_offload_host_update",         # host optimizer: sync by design
                "_train_batch_param_offload",   # ditto (streamed host step)
                "_host_init_params",            # init-time, not per-step
                "__init__",                     # offload master construction
                "get_lr", "get_global_grad_norm", "cur_scale",
                "skipped_steps",                # accessors: sync on request
                "module_state_dict",
            ),
        },
        forbidden=ENGINE_HOT_FORBIDDEN,
    ),
    # the extracted host-orchestration core (runtime/sched.py) BOTH loops
    # now consume: the dispatch ring's producer/consumer surface runs on
    # every train step AND every serve tick, and ``drain`` is THE
    # designated batched readback — the file-wide confine proves nothing
    # else in the shared core ever grows a ``device_get``
    HotPathSpec(
        path="deepspeed_tpu/runtime/sched.py",
        cls="DispatchRing",
        hot_functions=("push", "rearm_if_idle", "store", "take",
                       "requeue", "__len__"),
        confine={".device_get": ("drain",)},
        forbidden=ENGINE_HOT_FORBIDDEN,
    ),
    HotPathSpec(
        path="deepspeed_tpu/runtime/sched.py",
        cls="StagedPrefetcher",
        hot_functions=("ensure",),
    ),
    # the serve scheduler's tick ledger: ``observe_tick`` runs once per
    # engine step — pure host int arithmetic (``snapshot`` is report-time
    # and deliberately NOT hot)
    HotPathSpec(
        path="deepspeed_tpu/runtime/sched.py",
        cls="TickLedger",
        hot_functions=("observe_tick", "reset_window"),
    ),
    # the serve tick planner + chunk splitter: decode-first batch
    # composition and cap/bucket/block-snapped prefill chunking, run on
    # EVERY engine step — pure int planning over the sequence tables
    HotPathSpec(
        path="deepspeed_tpu/inference/v2/scheduler.py",
        cls=None,
        hot_functions=("snap_bucket", "plan_step"),
    ),
    # disaggregation: the role-pair step + the block-granular KV handoff
    # run every tick of a role-split server; the only device touches are
    # the engine demote/adopt calls the handoff *decides* to issue
    HotPathSpec(
        path="deepspeed_tpu/serving/disagg.py",
        cls="DisaggregatedEngine",
        hot_functions=("step", "_handoff", "can_schedule", "has_work"),
    ),
    # the adoption half of the handoff: host-side table/codec work plus
    # the deliberate scatter of already-dequantized pages (numpy over
    # HOST arrays — device syncs stay forbidden)
    HotPathSpec(
        path="deepspeed_tpu/inference/v2/engine_v2.py",
        cls="InferenceEngineV2",
        hot_functions=("adopt_kv_handoff",),
        forbidden=ENGINE_FORBIDDEN,
    ),
    # the serving tick: one thread drives admit/step/fan-out for every live
    # request — a sync here stalls every stream at once. The PR 10 siege
    # helpers (KV tier rebalance, ladder observation, drift reconcile,
    # fault-window bookkeeping) run EVERY tick and are registered to PROVE
    # the ladder and KV-tier bookkeeping never host-sync the tick: the
    # only device touches are the engine demote/promote calls the
    # rebalance *decides* to issue, which are deliberate off-path copies
    HotPathSpec(
        path="deepspeed_tpu/serving/server.py",
        cls="InferenceServer",
        hot_functions=("_serve_once", "_admit_from_queue", "_fan_out",
                       "_reap", "_settle_reaped", "_rebalance_kv_tiers",
                       "_observe_ladder", "_reconcile_kv",
                       "_active_worstcase", "_active_uids",
                       "_note_clean_step", "_trim_prefix_cache",
                       "_prefix_gauges", "_cache_evictable_blocks",
                       # the serve-plan tick clocks: per-tick stage marks,
                       # the batched retro-span emission, and the
                       # tick-stage share gauges all run every working
                       # tick — registering them PROVES the serving-tick
                       # attribution substrate never host-syncs the tick
                       "_mark", "_emit_tick_spans", "_tick_stage_gauges"),
        forbidden=ENGINE_FORBIDDEN,
    ),
    # the degradation ladder's per-tick observation + edge transition:
    # pure host arithmetic feeding edge-triggered trace instants
    HotPathSpec(
        path="deepspeed_tpu/serving/degradation.py",
        cls="DegradationLadder",
        hot_functions=("observe", "_transition"),
    ),
    # the KV tier planners: the decision half of the offload tier is pure
    # int arithmetic over the request tables (page movement lives in the
    # engine, invoked off these plans)
    HotPathSpec(
        path="deepspeed_tpu/serving/kv_tier.py",
        cls=None,
        hot_functions=("effective_usable_blocks", "plan_demotions",
                       "plan_prefix_evictions", "plan_promotions",
                       "tier_pressure"),
    ),
    # the fleet router's per-request decision helpers: pure stdlib
    # int/dict work over healthz snapshots, run on EVERY routed request
    # and EVERY poll tick — registering them proves routing never grows a
    # numpy materialization or host sync (the router host may not even
    # have an accelerator runtime)
    HotPathSpec(
        path="deepspeed_tpu/serving/fleet.py",
        cls=None,
        hot_functions=("affinity_key", "pick_replica", "plan_scale"),
    ),
    HotPathSpec(
        path="deepspeed_tpu/serving/fleet.py",
        cls="ReplicaHandle",
        hot_functions=("in_rotation", "snapshot"),
    ),
    # the radix prefix cache: the serve tick walks/pins/plans against the
    # trie on EVERY admission and rebalance — registering the whole
    # bookkeeping surface PROVES the trie never host-syncs the tick (the
    # only device op a cache decision triggers is the engine-side block
    # release an eviction plan commits, off these functions)
    HotPathSpec(
        path="deepspeed_tpu/inference/v2/prefix_cache.py",
        cls="PrefixCache",
        hot_functions=("lookup", "admit_match", "_pin", "_keys",
                       "insert_from_seq", "release_seq", "plan_evictions",
                       "evict_blocks", "evictable_blocks", "over_cap_blocks",
                       "cached_blocks", "pinned_blocks", "pinned_block_ids",
                       "owns", "snapshot"),
    ),
    # the host-tier page codec: pure numpy over ALREADY-GATHERED host
    # arrays (the device->host copy happened in gather_blocks, off-tick);
    # registering it proves quantization never grows a device touch or a
    # float() coercion of its own
    HotPathSpec(
        path="deepspeed_tpu/inference/v2/kv_offload.py",
        cls=None,
        hot_functions=("quantize_pages", "dequantize_pages",
                       "_page_absmax"),
        forbidden=ENGINE_FORBIDDEN,
    ),
    # the prefetch worker exists to overlap H2D with compute; a host sync in
    # the worker body (outside stage_fn, which the engine owns) re-serializes
    HotPathSpec(
        path="deepspeed_tpu/runtime/dataloader.py",
        cls="PrefetchLoader",
        hot_functions=("_worker", "__next__"),
        forbidden=ENGINE_FORBIDDEN,
    ),
    # the dstrace emit helpers run INSIDE every registered hot path above
    # (train_batch dispatch, serve tick, prefetch worker) — registering them
    # here is what PROVES "always-on tracing never adds a host sync": any
    # device readback, float() coercion, or numpy materialization growing
    # into the emit path is a DS002 finding
    HotPathSpec(
        path="deepspeed_tpu/telemetry/tracer.py",
        cls="Tracer",
        hot_functions=("span", "instant", "complete", "counter", "_emit"),
    ),
    HotPathSpec(
        path="deepspeed_tpu/telemetry/tracer.py",
        cls="_Span",
        hot_functions=("__enter__", "__exit__"),
    ),
    # the comm compression layer: the codec + error-feedback step and the
    # in-shard_map collective impls run at TRACE time inside the compiled
    # step (a host sync there wedges compilation of every traced program),
    # and the bucket scheduler's sync closure runs per traced reduction —
    # registering the whole surface PROVES the per-bucket path never
    # host-syncs (the satellite contract: DS002 green, baseline empty)
    HotPathSpec(
        path="deepspeed_tpu/comm/compress.py",
        cls=None,
        hot_functions=("quantize_wire", "dequantize_wire", "ef_step",
                       "reduce_scatter_impl", "all_reduce_impl",
                       "_exchange", "_regather", "axis_world",
                       "plan_buckets"),
    ),
    HotPathSpec(
        path="deepspeed_tpu/comm/compress.py",
        cls="GradCompressor",
        hot_functions=("make_sync_fn", "bucket_summaries"),
    ),
    # the comm-op listener runs inside the collective facade's _record —
    # trace time for jit collectives, per call when eager. Registering it
    # (and the heartbeat producer it fans into) PROVES the comm guard's
    # membership feed adds no host sync to the per-step path: emission is
    # one attribute read + one locked int/str store, never a device touch
    HotPathSpec(
        path="deepspeed_tpu/comm/guard.py",
        cls=None,
        # next_op_seq allocates the cross-rank comm sequence number inside
        # the collective facade's _record (trace time under jit, per call
        # eager) — registering it PROVES op_seq stamping is one C-level
        # counter increment, never a host sync
        hot_functions=("note_comm_op", "next_op_seq"),
    ),
    HotPathSpec(
        path="deepspeed_tpu/resilience/membership.py",
        cls="Heartbeat",
        hot_functions=("note_op",),
    ),
    # the dsmem sampler's entry points: ``on_drain`` is called from the
    # engine's designated drain / sync print boundary (points that already
    # host-sync by design) and ``sample`` from the background cadence
    # thread — registering collection here PROVES memory observability
    # never adds a device sync of its own: it reads allocator-stat dicts
    # and one /proc line, never a transfer or a float() coercion
    HotPathSpec(
        path="deepspeed_tpu/telemetry/memory.py",
        cls="MemorySampler",
        hot_functions=("on_drain", "sample", "_collect"),
    ),
    # the compile-event ledger's dispatch wrapper rides EVERY watched jit
    # dispatch (train step, serving prefill/decode/sample) — registering
    # it PROVES compile detection is one C-level cache-size probe per
    # call, never a readback; the signature builder runs only on the
    # compile (slow) path and reads .shape/.dtype attributes, never data
    HotPathSpec(
        path="deepspeed_tpu/telemetry/compiles.py",
        cls="CompileWatched",
        hot_functions=("__call__",),
    ),
)

#: the inverse registry: modules that must NEVER run on (or be imported
#: by) a registered hot path. ``dstpu plan``'s trace replay is offline by
#: contract — it re-reads whole dumps, builds interval sweeps, and does
#: unbounded host work, any of which would wreck a per-step path.
#: tests/test_plan.py proves both directions: no HOT_PATHS file references
#: these modules, and the modules themselves never import jax (an offline
#: analyzer has no business touching the device runtime at all).
OFFLINE_ONLY_MODULES: Tuple[str, ...] = (
    "deepspeed_tpu/telemetry/attribution.py",
    # the serving-tick replay (`dstpu plan --serve`) — same contract:
    # stdlib-only, file-loadable on jax-less hosts, never on a hot path
    "deepspeed_tpu/telemetry/serve_attribution.py",
    # the cross-rank merge + skew ledger (`dstpu trace merge` / `dstpu
    # plan --cross-rank`) — replays N whole dumps at once; strictly
    # offline, stdlib-only, jax-less-host loadable
    "deepspeed_tpu/telemetry/crossrank.py",
)
