"""The hot-path declaration DS002 enforces: roots + escape hatches.

Until dslint v2 this file was a 300-line registry enumerating every
function on the per-step/per-tick fast path — every PR had to remember
to extend it, and a helper extracted out of a registered function
silently fell off the tripwire. DS002 is now **taint propagation** over
the project call graph (``tools/dslint/callgraph.py``): host-sync sinks
(``float()`` on arrays, ``.item()``, ``device_get``,
``block_until_ready``, ``np.asarray``) are findings in any function
*reachable from a registered hot root*, so new helpers are covered the
moment a hot path starts calling them. What remains here is the part
that genuinely is a reviewed declaration:

  HOT_ROOTS        the entry points INTO hot code: the training dispatch,
                   the serve tick, the router pick/poll, the planners,
                   and the bench/listener-facing surface whose callers
                   live outside the package (HTTP handlers, installed
                   callbacks, bench harnesses — edges no static call
                   graph can see)
  ESCAPE_HATCHES   the designed synchronous points: THE drain, the host
                   offload path, the guarded async fan-in
  OFFLINE_ONLY_MODULES  the inverse contract, enforced by DS009

``tests/test_dslint.py`` proves the taint closure of HOT_ROOTS covers a
strict superset of the retired registry, and that every root is
load-bearing (deleting any one loses coverage of at least one formerly
registered function).

Root fields:

  path / qualname  repo-relative file + dotted function name
  reason           why this is an entry point (shown in findings)
  forbidden        sink matchers for paths tainted from this root

Hatch fields:

  mode = "sync_ok"   the function's OWN body may sync (it IS the
                     designated sync point) but its callees are still
                     traversed — the drain's bookkeeping helpers stay
                     covered
  mode = "prune"     the whole subtree under the function is exempt and
                     not traversed (explicitly host-synchronous designs:
                     the streamed host optimizer step)
  mode = "guarded"   branch-sensitive: sinks on lines that provably
                     execute only when ``guard_attr`` is false (the
                     designed synchronous fallback) are exempt; the
                     async side and shared code stay covered
"""

import dataclasses
from typing import Tuple

#: calls that force (or can force) a device->host sync. ``float()`` on a
#: jax.Array blocks on the value; ``.item()``/``np.asarray``/``np.array``
#: copy to host; device_get / block_until_ready are explicit.
DEFAULT_FORBIDDEN: Tuple[str, ...] = (
    "float", ".item", ".device_get", ".block_until_ready",
    ".copy_to_host_async", "np.asarray", "np.array",
)

#: files whose hot code legitimately touches numpy on HOST arrays (batch
#: staging before H2D, the already-gathered page codec, healthz int
#: arithmetic) — ``np.asarray``/``np.array`` stay allowed there; device
#: syncs stay forbidden. This mirrors the retired registry's
#: ENGINE_FORBIDDEN profile, keyed by file instead of by spec.
HOST_NUMPY_FILES: Tuple[str, ...] = (
    "deepspeed_tpu/runtime/engine.py",
    "deepspeed_tpu/runtime/dataloader.py",
    "deepspeed_tpu/serving/server.py",
    "deepspeed_tpu/inference/v2/engine_v2.py",
    "deepspeed_tpu/inference/v2/kv_offload.py",
    # host token tables: prompt ids arrive as python lists and are staged
    # into numpy before the single H2D
    "deepspeed_tpu/inference/v2/ragged_manager.py",
    # fault injection poisons the HOST batch before dispatch — that is
    # the drill (corrupting on device would change what the guard sees)
    "deepspeed_tpu/resilience/chaos.py",
)

#: the fleet router runs on a deviceless host by design (its roots'
#: ``reason`` says so): ``float()`` there parses JSON bodies and healthz
#: snapshots, never a device array. Explicit syncs stay forbidden — a
#: router importing jax readback APIs is wrong no matter the host.
ROUTER_FORBIDDEN: Tuple[str, ...] = tuple(
    m for m in DEFAULT_FORBIDDEN if m != "float")


@dataclasses.dataclass(frozen=True)
class HotRoot:
    path: str
    qualname: str
    reason: str
    forbidden: Tuple[str, ...] = DEFAULT_FORBIDDEN


@dataclasses.dataclass(frozen=True)
class EscapeHatch:
    path: str
    qualname: str
    mode: str                   # "sync_ok" | "prune" | "guarded"
    reason: str
    guard_attr: str = ""        # mode == "guarded" only


HOT_ROOTS: Tuple[HotRoot, ...] = (
    # -- dispatch roots: the loops themselves -------------------------------
    HotRoot(
        path="deepspeed_tpu/runtime/engine.py",
        qualname="DeepSpeedTPUEngine.train_batch",
        reason="the training dispatch: everything it reaches runs every "
               "step — one sync re-serializes the pipeline while every "
               "timing test keeps passing"),
    HotRoot(
        path="deepspeed_tpu/resilience/runner.py",
        qualname="FaultTolerantRunner.step",
        reason="the fault-tolerant step wrapper: drained-metric reconcile "
               "and chaos/guard bookkeeping ride every training step"),
    HotRoot(
        path="deepspeed_tpu/serving/server.py",
        qualname="InferenceServer._serve_once",
        reason="the serving tick: one thread drives admit/step/fan-out "
               "for every live request — a sync stalls every stream"),
    HotRoot(
        path="deepspeed_tpu/serving/server.py",
        qualname="InferenceServer.health",
        reason="the /healthz payload: polled by the fleet router every "
               "poll tick, so its gauge reads must never touch the device"),
    HotRoot(
        path="deepspeed_tpu/serving/disagg.py",
        qualname="DisaggregatedEngine.step",
        reason="the role-split tick: prefill/decode pair step + "
               "block-granular KV handoff run every tick"),
    HotRoot(
        path="deepspeed_tpu/inference/v2/engine_v2.py",
        qualname="InferenceEngineV2.step",
        reason="the v2 engine dispatch: scheduler planning, KV/prefix "
               "bookkeeping and decode fan-in run every engine step"),
    HotRoot(
        path="deepspeed_tpu/serving/fleet.py",
        qualname="FleetRouter.route_generate",
        reason="the per-request routing pick: pure stdlib work over "
               "healthz snapshots — the router host may not even have an "
               "accelerator runtime",
        forbidden=ROUTER_FORBIDDEN),
    HotRoot(
        path="deepspeed_tpu/serving/fleet.py",
        qualname="FleetRouter._poll_once",
        reason="the router poll tick: snapshot/scale-plan every interval",
        forbidden=ROUTER_FORBIDDEN),
    # -- planner/facade roots ----------------------------------------------
    HotRoot(
        path="deepspeed_tpu/comm/compress.py",
        qualname="GradCompressor.build",
        reason="bucket/wire-schedule planning (PR 14): constructed at "
               "engine init but part of the registered comm surface"),
    HotRoot(
        path="deepspeed_tpu/comm/compress.py",
        qualname="GradCompressor.bucket_summaries",
        reason="the overlap-schedule summaries dstpu plan attributes "
               "comm overlap from"),
    # -- callback/surface roots: callers outside the package ---------------
    # (installed listeners, bench harnesses, HTTP dispatch — entry edges a
    # static call graph cannot see; declaring them roots keeps their
    # bodies, and everything they call, inside the taint)
    HotRoot(
        path="deepspeed_tpu/resilience/membership.py",
        qualname="Heartbeat.note_op",
        reason="installed as the comm-op listener: invoked from the "
               "collective facade's _record through listener indirection"),
    HotRoot(
        path="deepspeed_tpu/inference/v2/engine_v2.py",
        qualname="InferenceEngineV2.sched_mark",
        reason="the bench measured-window mark: called between ticks by "
               "bench_serve at the compile boundary"),
    HotRoot(
        path="deepspeed_tpu/runtime/sched.py",
        qualname="DispatchRing.rearm_if_idle",
        reason="public ring surface armed by harnesses between steps"),
    HotRoot(
        path="deepspeed_tpu/runtime/sched.py",
        qualname="DispatchRing.__len__",
        reason="public ring surface: pending-depth probes from benches "
               "and tests ride the hot loop cadence"),
    HotRoot(
        path="deepspeed_tpu/inference/v2/prefix_cache.py",
        qualname="PrefixCache.pinned_blocks",
        reason="cache gauge surface read at tick cadence by harnesses"),
    HotRoot(
        path="deepspeed_tpu/inference/v2/prefix_cache.py",
        qualname="PrefixCache.pinned_block_ids",
        reason="cache pin-set surface consumed by eviction planners and "
               "harnesses at tick cadence"),
)


ESCAPE_HATCHES: Tuple[EscapeHatch, ...] = (
    EscapeHatch(
        path="deepspeed_tpu/runtime/sched.py",
        qualname="DispatchRing.drain",
        mode="sync_ok",
        reason="THE designated readback: one batched device_get over "
               "every pending payload — its bookkeeping callees stay "
               "covered"),
    EscapeHatch(
        path="deepspeed_tpu/runtime/engine.py",
        qualname="DeepSpeedTPUEngine._drain_metric_ring",
        mode="sync_ok",
        reason="the engine-side drain wrapper: reconciles host copies at "
               "the designated sync point"),
    EscapeHatch(
        path="deepspeed_tpu/runtime/engine.py",
        qualname="DeepSpeedTPUEngine._record_metrics",
        mode="guarded", guard_attr="_async_enabled",
        reason="async fan-in point: the push branch queues device arrays "
               "verbatim and must stay sync-free; the synchronous "
               "fallback branch IS the designed sync path"),
    EscapeHatch(
        path="deepspeed_tpu/runtime/engine.py",
        qualname="DeepSpeedTPUEngine._offload_host_update",
        mode="prune",
        reason="host optimizer step: synchronous by design (streamed "
               "D2H/H2D is the whole point of the offload ladder)"),
    EscapeHatch(
        path="deepspeed_tpu/runtime/engine.py",
        qualname="DeepSpeedTPUEngine._train_batch_param_offload",
        mode="prune",
        reason="the streamed host-offload train step: ditto"),
    EscapeHatch(
        path="deepspeed_tpu/runtime/engine.py",
        qualname="DeepSpeedTPUEngine._host_init_params",
        mode="prune",
        reason="init-time host materialization, not per-step"),
    EscapeHatch(
        path="deepspeed_tpu/runtime/engine.py",
        qualname="DeepSpeedTPUEngine._monitor_step_events",
        mode="sync_ok",
        reason="the single monitor-event formatter: both callers hand it "
               "host copies (the guarded sync record path and the drain "
               "consumer) — its float() normalizes, never blocks"),
    EscapeHatch(
        path="deepspeed_tpu/runtime/engine.py",
        qualname="DeepSpeedTPUEngine._note_oom",
        mode="prune",
        reason="OOM forensics: runs once on a RESOURCE_EXHAUSTED raise, "
               "after the step already died — sync is the point"),
    EscapeHatch(
        path="deepspeed_tpu/resilience/runner.py",
        qualname="FaultTolerantRunner.step",
        mode="guarded", guard_attr="_async_enabled",
        reason="the runner's readback fan-in: the async branch replays "
               "drained host copies; the fallback branch owns ONE "
               "batched device_get and is the designed sync path"),
    EscapeHatch(
        path="deepspeed_tpu/resilience/runner.py",
        qualname="FaultTolerantRunner._maybe_save",
        mode="prune",
        reason="checkpoint save: a deliberate synchronous D2H barrier at "
               "the save boundary (snapshot consistency requires it)"),
    EscapeHatch(
        path="deepspeed_tpu/resilience/runner.py",
        qualname="FaultTolerantRunner._export_monitor_events",
        mode="sync_ok",
        reason="exports already-drained host metric dicts to the monitor "
               "backends — float() normalizes host values"),
    EscapeHatch(
        path="deepspeed_tpu/resilience/guards.py",
        qualname="_finite_report",
        mode="prune",
        reason="non-finite forensics: runs only after the guard trips; "
               "the whole point is to pull the offending values to host"),
    EscapeHatch(
        path="deepspeed_tpu/resilience/membership.py",
        qualname="StragglerDetector.ingest_spans",
        mode="sync_ok",
        reason="consumes host span dicts from the tracer ring snapshot"),
    EscapeHatch(
        path="deepspeed_tpu/runtime/eigenvalue.py",
        qualname="Eigenvalue.compute_eigenvalue",
        mode="prune",
        reason="periodic power-iteration probe on its own schedule "
               "(eigenvalue_every): synchronous convergence loop by "
               "design, never on the steady-state step"),
    EscapeHatch(
        path="deepspeed_tpu/compression/compress.py",
        qualname="Compressor.maybe_freeze_masks",
        mode="prune",
        reason="one-shot sparse-mask freeze at the scheduled boundary "
               "step: a single deliberate readback, then never again"),
    EscapeHatch(
        path="deepspeed_tpu/inference/v2/kv_cache.py",
        qualname="BlockedKVCache.gather_blocks",
        mode="sync_ok",
        reason="THE designated page D2H: the tier planner decided to "
               "demote these blocks; the copy is the operation"),
    EscapeHatch(
        path="deepspeed_tpu/inference/v2/kv_cache.py",
        qualname="BlockedKVCache.scatter_blocks",
        mode="sync_ok",
        reason="THE designated page H2D staging (promotion/handoff "
               "adopt): ditto"),
    EscapeHatch(
        path="deepspeed_tpu/monitor/monitor.py",
        qualname="MonitorMaster.write_events",
        mode="sync_ok",
        reason="normalizes host event values once for every backend; "
               "producers only hand it host copies (drain output)"),
    EscapeHatch(
        path="deepspeed_tpu/serving/metrics.py",
        qualname="ServingMetrics.set_prefix_gauges",
        mode="sync_ok",
        reason="coerces host bookkeeping counters from the prefix-cache "
               "stats dict into gauges"),
    EscapeHatch(
        path="deepspeed_tpu/serving/metrics.py",
        qualname="ServingMetrics.events",
        mode="sync_ok",
        reason="flattens the host counter/gauge snapshot for export"),
    EscapeHatch(
        path="deepspeed_tpu/telemetry/hist.py",
        qualname="LogHistogram.observe",
        mode="sync_ok",
        reason="float() normalizes a host monotonic-stamp difference "
               "into a bucket counter — the SLO histograms are fed "
               "stdlib floats only, never device arrays"),
    EscapeHatch(
        path="deepspeed_tpu/telemetry/hist.py",
        qualname="LogHistogram.bucket_index",
        mode="sync_ok",
        reason="the le-inclusive bucket scan over the same host float "
               "(observe's callee; covered separately because sync_ok "
               "does not exempt callees)"),
    EscapeHatch(
        path="deepspeed_tpu/telemetry/tracer.py",
        qualname="Tracer.tail",
        mode="sync_ok",
        reason="diagnostic slice over the host event ring (the 'last 30s "
               "before quarantine' bundle) — host tuples only"),
    EscapeHatch(
        path="deepspeed_tpu/utils/timer.py",
        qualname="_device_sync",
        mode="sync_ok",
        reason="the timer's opt-in synchronize mode: a deliberate "
               "dispatch-queue flush, off on the hot path by default"),
    EscapeHatch(
        path="deepspeed_tpu/utils/timer.py",
        qualname="Timer.record_external",
        mode="sync_ok",
        reason="records host wall-clock seconds handed in by the caller"),
)


#: the inverse contract: modules that must NEVER run on (or be imported
#: by) a hot path, enforced as lint by DS009 in both directions — an
#: OFFLINE_ONLY module reaching ``jax`` through its module-level import
#: graph is a finding, and a hot-path file importing an OFFLINE_ONLY
#: module is a finding. ``dstpu plan``'s trace replay is offline by
#: contract: it re-reads whole dumps, builds interval sweeps, and does
#: unbounded host work, any of which would wreck a per-step path.
OFFLINE_ONLY_MODULES: Tuple[str, ...] = (
    "deepspeed_tpu/telemetry/attribution.py",
    # the serving-tick replay (`dstpu plan --serve`) — same contract:
    # stdlib-only, file-loadable on jax-less hosts, never on a hot path
    "deepspeed_tpu/telemetry/serve_attribution.py",
    # the cross-rank merge + skew ledger (`dstpu trace merge` / `dstpu
    # plan --cross-rank`) — replays N whole dumps at once; strictly
    # offline, stdlib-only, jax-less-host loadable
    "deepspeed_tpu/telemetry/crossrank.py",
    # the per-request fleet-timeline stitcher (`dstpu reqtrace`): joins
    # router + replica + flight-recorder dumps on the trace id — whole-
    # dump replay, interval sweeps, strictly offline. (telemetry/hist.py
    # is deliberately NOT here: serving/metrics.py feeds its histograms
    # on the serve path, so it lives under DS002 taint instead.)
    "deepspeed_tpu/telemetry/reqtrace.py",
)
