"""``dslint`` CLI — lint the tree against the repo's TPU bug classes.

    dslint deepspeed_tpu/                     # text report, auto baseline
    dslint --format json deepspeed_tpu/      # machine-readable
    dslint --write-baseline deepspeed_tpu/   # grandfather current findings
    dslint --select DS002 path/to/file.py    # one rule only
    dslint --changed origin/main             # changed files + reverse deps
    dslint --list-rules

Exit codes: 0 clean (vs baseline); 1 findings — including DS000 parse
errors — or stale baseline entries; 2 usage / baseline-load problems.
"""

import argparse
import ast
import collections
import json
import os
import subprocess
import sys

from deepspeed_tpu.tools.dslint import baseline as baseline_mod
from deepspeed_tpu.tools.dslint.engine import LintEngine, iter_python_files
from deepspeed_tpu.tools.dslint.rules import get_rules


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dslint",
        description="JAX/TPU-aware static analysis (rules DS001-DS009)")
    p.add_argument("paths", nargs="*", default=["."],
                   help="files/directories to lint (default: .)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default="auto",
                   help="baseline json path; 'auto' walks up from the first "
                        "path looking for dslint_baseline.json; 'none' "
                        "disables the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current (unsuppressed) findings as the new "
                        "baseline and exit 0")
    p.add_argument("--select", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--ignore", default=None,
                   help="comma-separated rule ids to skip")
    p.add_argument("--root", default=None,
                   help="directory findings paths are relative to "
                        "(default: the baseline file's directory, else cwd)")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="BASE",
                   help="lint only python files changed vs BASE (default "
                        "HEAD; staged, unstaged and untracked all count) "
                        "PLUS their reverse dependencies — files whose "
                        "call or import edges reach a changed file, so "
                        "taint/purity findings that depend on the change "
                        "are still seen. Fast pre-push subset of the "
                        "full run.")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="findings only, no summary")
    return p


def _git_lines(cwd, *args):
    try:
        proc = subprocess.run(["git", *args], cwd=cwd, capture_output=True,
                              text=True, check=True)
    except (OSError, subprocess.CalledProcessError):
        return None
    return [ln.strip() for ln in proc.stdout.splitlines() if ln.strip()]


def changed_python_files(top, base="HEAD"):
    """Repo-relative .py files that differ from ``base``: committed-but-
    diverged, staged, unstaged, and untracked all count (the lint should
    see exactly what a push would)."""
    diffed = _git_lines(top, "diff", "--name-only", base, "--")
    untracked = _git_lines(top, "ls-files", "--others",
                           "--exclude-standard")
    if diffed is None and untracked is None:
        return None
    out = []
    for rel in (diffed or []) + (untracked or []):
        if rel.endswith(".py") and os.path.exists(os.path.join(top, rel)):
            out.append(rel.replace(os.sep, "/"))
    return sorted(set(out))


def expand_with_reverse_deps(top, changed):
    """The changed files plus every file that can REACH one of them
    through a call or module-level import edge (transitively). Those
    dependents' findings can flip without their own text changing — a
    hot root two files away may now taint a new sink, an offline module
    may newly reach jax — so a subset run must re-lint them too."""
    from deepspeed_tpu.tools.dslint.callgraph import build_graph
    files = []
    for p in iter_python_files([top]):
        rel = os.path.relpath(p, top).replace(os.sep, "/")
        try:
            with open(p, encoding="utf-8") as f:
                files.append((rel, ast.parse(f.read())))
        except (OSError, SyntaxError):
            continue        # unparseable: the engine reports DS000 if it
                            # is in the changed set itself
    g = build_graph(files)
    rev = {}                # file -> files that call/import into it
    for caller, callees in g.edges.items():
        cf = g.functions[caller].relpath
        for callee in callees:
            tf = g.functions[callee].relpath
            if tf != cf:
                rev.setdefault(tf, set()).add(cf)
    for rel, mod in g.modules.items():
        for tgt in mod.internal_imports:
            if tgt != rel:
                rev.setdefault(tgt, set()).add(rel)
    out, queue = set(), list(changed)
    while queue:
        cur = queue.pop()
        if cur in out:
            continue
        out.add(cur)
        queue.extend(rev.get(cur, ()))
    return sorted(out)


def _resolve_baseline(args) -> str:
    if args.baseline == "none":
        return ""
    if args.baseline != "auto":
        return args.baseline
    found = baseline_mod.find_default_baseline(
        args.paths[0] if args.paths else ".")
    return found or ""


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    rules = get_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.name:<24} {r.description}")
        return 0

    if args.changed is not None:
        lines = _git_lines(os.getcwd(), "rev-parse", "--show-toplevel")
        if not lines:
            print("dslint: --changed requires a git checkout",
                  file=sys.stderr)
            return 2
        top = lines[0]
        changed = changed_python_files(top, args.changed)
        if changed is None:
            print(f"dslint: cannot diff against {args.changed!r}",
                  file=sys.stderr)
            return 2
        if not changed:
            if not args.quiet:
                print(f"dslint: no python files changed vs {args.changed}")
            return 0
        # scope: the positional paths (relative to the repo top), else the
        # package the checked-in baseline governs — a changed test file is
        # not part of the self-lint surface, matching the full-run recipe
        # `dslint deepspeed_tpu/`
        scopes = [os.path.relpath(os.path.abspath(p), top).replace(
            os.sep, "/") for p in args.paths if p != "."]
        if not scopes:
            scopes = ["deepspeed_tpu"] if os.path.isdir(
                os.path.join(top, "deepspeed_tpu")) else ["."]
        in_scope = lambda rel: any(
            s == "." or rel == s or rel.startswith(s + "/") for s in scopes)
        changed = [rel for rel in changed if in_scope(rel)]
        if not changed:
            if not args.quiet:
                print(f"dslint: no in-scope python files changed vs "
                      f"{args.changed}")
            return 0
        subset = [rel for rel in expand_with_reverse_deps(top, changed)
                  if in_scope(rel)]
        if not args.quiet:
            print(f"dslint: --changed vs {args.changed}: {len(changed)} "
                  f"changed file(s) + {len(subset) - len(changed)} "
                  f"reverse dep(s)")
        args.paths = [os.path.join(top, rel) for rel in subset]

    split = lambda s: [x.strip() for x in s.split(",") if x.strip()] \
        if s else None
    baseline_path = _resolve_baseline(args)
    root = args.root or (os.path.dirname(os.path.abspath(baseline_path))
                         if baseline_path else None)
    engine = LintEngine(rules, root=root, select=split(args.select),
                        ignore=split(args.ignore))
    if not engine.rules:
        print("dslint: no rules selected", file=sys.stderr)
        return 2

    baseline = None
    if baseline_path and not args.write_baseline:
        try:
            baseline = baseline_mod.load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"dslint: cannot load baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2

    result = engine.run(args.paths, baseline=baseline)

    if args.write_baseline:
        out = baseline_path or baseline_mod.DEFAULT_BASELINE_NAME
        prior = None
        if os.path.exists(out):
            try:
                # partial runs (path subset, --select) must not truncate
                # the baseline for everything they did not re-evaluate
                prior = baseline_mod.load_baseline(out)
            except (OSError, ValueError, json.JSONDecodeError) as e:
                print(f"dslint: cannot merge existing baseline {out}: {e}",
                      file=sys.stderr)
                return 2
        baseline_mod.write_baseline(
            out, result.findings, prior=prior,
            covered_paths=set(result.linted_paths),
            active_rules=set(result.active_rules))
        grandfathered = [f for f in result.findings if f.rule != "DS000"]
        if not args.quiet:
            print(f"dslint: baseline written -> {out} "
                  f"({len(grandfathered)} findings grandfathered)")
        if result.parse_errors:
            # an unparseable file cannot be linted, so it cannot be
            # grandfathered — it keeps failing until it parses
            for f in result.parse_errors:
                print(f"dslint: NOT grandfathered: {f.render()}",
                      file=sys.stderr)
            return 1
        return 0

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in result.findings],
            "suppressed": len(result.suppressed),
            "baselined": len(result.baselined),
            "stale_baseline": result.stale_baseline,
            "files_checked": result.files_checked,
            "exit_code": result.exit_code,
        }, indent=2))
        return result.exit_code

    for f in result.findings:
        print(f.render())
    if not args.quiet:
        by_rule = collections.Counter(f.rule for f in result.findings)
        summary = ", ".join(f"{r}:{n}" for r, n in sorted(by_rule.items())) \
            or "clean"
        print(f"dslint: {result.files_checked} files, "
              f"{len(result.findings)} findings ({summary}), "
              f"{len(result.suppressed)} suppressed inline, "
              f"{len(result.baselined)} baselined"
              + (f" [{os.path.basename(baseline_path)}]"
                 if baseline_path else ""))
        if result.stale_baseline:
            print(f"dslint: {len(result.stale_baseline)} stale baseline "
                  f"entries (violation fixed — expire with "
                  f"--write-baseline):")
            for e in result.stale_baseline:
                print(f"  {e['rule']} {e['path']} :: {e['anchor']}")
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
