"""``dslint`` CLI — lint the tree against the repo's TPU bug classes.

    dslint deepspeed_tpu/                     # text report, auto baseline
    dslint --format json deepspeed_tpu/      # machine-readable
    dslint --write-baseline deepspeed_tpu/   # grandfather current findings
    dslint --select DS002 path/to/file.py    # one rule only
    dslint --list-rules

Exit codes: 0 clean (vs baseline); 1 findings — including DS000 parse
errors — or stale baseline entries; 2 usage / baseline-load problems.
"""

import argparse
import collections
import json
import os
import sys

from deepspeed_tpu.tools.dslint import baseline as baseline_mod
from deepspeed_tpu.tools.dslint.engine import LintEngine
from deepspeed_tpu.tools.dslint.rules import get_rules


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dslint",
        description="JAX/TPU-aware static analysis (rules DS001-DS006)")
    p.add_argument("paths", nargs="*", default=["."],
                   help="files/directories to lint (default: .)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default="auto",
                   help="baseline json path; 'auto' walks up from the first "
                        "path looking for dslint_baseline.json; 'none' "
                        "disables the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current (unsuppressed) findings as the new "
                        "baseline and exit 0")
    p.add_argument("--select", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--ignore", default=None,
                   help="comma-separated rule ids to skip")
    p.add_argument("--root", default=None,
                   help="directory findings paths are relative to "
                        "(default: the baseline file's directory, else cwd)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="findings only, no summary")
    return p


def _resolve_baseline(args) -> str:
    if args.baseline == "none":
        return ""
    if args.baseline != "auto":
        return args.baseline
    found = baseline_mod.find_default_baseline(
        args.paths[0] if args.paths else ".")
    return found or ""


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    rules = get_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.name:<24} {r.description}")
        return 0

    split = lambda s: [x.strip() for x in s.split(",") if x.strip()] \
        if s else None
    baseline_path = _resolve_baseline(args)
    root = args.root or (os.path.dirname(os.path.abspath(baseline_path))
                         if baseline_path else None)
    engine = LintEngine(rules, root=root, select=split(args.select),
                        ignore=split(args.ignore))
    if not engine.rules:
        print("dslint: no rules selected", file=sys.stderr)
        return 2

    baseline = None
    if baseline_path and not args.write_baseline:
        try:
            baseline = baseline_mod.load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"dslint: cannot load baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2

    result = engine.run(args.paths, baseline=baseline)

    if args.write_baseline:
        out = baseline_path or baseline_mod.DEFAULT_BASELINE_NAME
        prior = None
        if os.path.exists(out):
            try:
                # partial runs (path subset, --select) must not truncate
                # the baseline for everything they did not re-evaluate
                prior = baseline_mod.load_baseline(out)
            except (OSError, ValueError, json.JSONDecodeError) as e:
                print(f"dslint: cannot merge existing baseline {out}: {e}",
                      file=sys.stderr)
                return 2
        baseline_mod.write_baseline(
            out, result.findings, prior=prior,
            covered_paths=set(result.linted_paths),
            active_rules=set(result.active_rules))
        grandfathered = [f for f in result.findings if f.rule != "DS000"]
        if not args.quiet:
            print(f"dslint: baseline written -> {out} "
                  f"({len(grandfathered)} findings grandfathered)")
        if result.parse_errors:
            # an unparseable file cannot be linted, so it cannot be
            # grandfathered — it keeps failing until it parses
            for f in result.parse_errors:
                print(f"dslint: NOT grandfathered: {f.render()}",
                      file=sys.stderr)
            return 1
        return 0

    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in result.findings],
            "suppressed": len(result.suppressed),
            "baselined": len(result.baselined),
            "stale_baseline": result.stale_baseline,
            "files_checked": result.files_checked,
            "exit_code": result.exit_code,
        }, indent=2))
        return result.exit_code

    for f in result.findings:
        print(f.render())
    if not args.quiet:
        by_rule = collections.Counter(f.rule for f in result.findings)
        summary = ", ".join(f"{r}:{n}" for r, n in sorted(by_rule.items())) \
            or "clean"
        print(f"dslint: {result.files_checked} files, "
              f"{len(result.findings)} findings ({summary}), "
              f"{len(result.suppressed)} suppressed inline, "
              f"{len(result.baselined)} baselined"
              + (f" [{os.path.basename(baseline_path)}]"
                 if baseline_path else ""))
        if result.stale_baseline:
            print(f"dslint: {len(result.stale_baseline)} stale baseline "
                  f"entries (violation fixed — expire with "
                  f"--write-baseline):")
            for e in result.stale_baseline:
                print(f"  {e['rule']} {e['path']} :: {e['anchor']}")
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
