"""dslint rule engine — AST-based static analysis for the JAX/TPU bug
classes this repo keeps fixing by hand.

The engine is deliberately dependency-free (stdlib ``ast`` only, no jax
import) so it runs anywhere the source does — pre-commit, CI collection
phase, the tier-1 self-lint test — in well under a second for the whole
tree.

Pipeline per run:

  collect .py files -> parse once -> per-file rules (``Rule.check``)
                                  -> project rules (``Rule.finalize``)
          -> inline ``# dslint: disable=RULE`` suppressions
          -> checked-in baseline (grandfathered findings)
          -> text/JSON report + exit code

Findings are keyed for the baseline by ``(rule, path, anchor)`` where the
anchor is a line-number-free symbol (enclosing qualname + offending token),
so unrelated edits above a grandfathered finding never churn the baseline.
"""

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Finding", "FileContext", "ProjectContext", "Rule", "LintResult",
    "LintEngine", "iter_python_files", "parse_suppressions",
]

_DISABLE_RE = re.compile(
    r"#\s*dslint:\s*disable=([A-Za-z0-9_,\s]+?)(?:\s+--.*)?$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation.

    ``anchor`` is the stable baseline key (qualname + token, no line
    number); ``line``/``col`` locate it for humans.
    """
    rule: str
    path: str           # repo-relative, posix separators
    line: int
    col: int
    message: str
    anchor: str

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.anchor)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def parse_suppressions(source: str) -> Dict[int, set]:
    """Map line number -> set of rule ids disabled on that line.

    Two comment forms (1-indexed lines, matching ``ast`` node linenos):

      x = risky()            # dslint: disable=DS001 -- reason
      # dslint: disable=DS004 -- reason
      x = risky()            (standalone comment applies to the NEXT line)

    ``disable=all`` disables every rule.
    """
    out: Dict[int, set] = {}
    pending: set = set()      # standalone comments bind to the NEXT code
    for i, text in enumerate(source.splitlines(), start=1):
        stripped = text.strip()
        m = _DISABLE_RE.search(text)
        rules = ({r.strip().upper() for r in m.group(1).split(",")
                  if r.strip()} if m else set())
        if stripped.startswith("#"):
            pending.update(rules)     # (continuation comment lines pass by)
            continue
        if not stripped:
            continue
        if pending:
            out.setdefault(i, set()).update(pending)
            pending = set()
        if rules:                     # trailing comment: applies here
            out.setdefault(i, set()).update(rules)
    return out


class FileContext:
    """One parsed source file plus the lookups rules share."""

    def __init__(self, abspath: str, relpath: str, source: str,
                 tree: ast.Module):
        self.abspath = abspath
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.suppressions = parse_suppressions(source)
        self._scope_spans: Optional[List[Tuple[int, int, str]]] = None
        self._stmt_spans: Optional[List[Tuple[int, int]]] = None
        self._decorator_spans: Optional[List[Tuple[int, int]]] = None

    # ------------------------------------------------------------------
    def qualname(self, node: ast.AST) -> str:
        """Dotted class/function path of the scope *containing* ``node``
        (``""`` at module level) — the stable half of a baseline anchor."""
        if self._scope_spans is None:
            self._scope_spans = []
            self._index_scopes(self.tree, prefix="")
        lineno = getattr(node, "lineno", None)
        if lineno is None:
            return ""
        containing = [(lo, hi, name) for lo, hi, name in self._scope_spans
                      if lo <= lineno <= hi]
        if not containing:
            return ""
        # innermost scope = the latest-starting span that contains the node
        return max(containing, key=lambda s: s[0])[2]

    def _index_scopes(self, node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                name = f"{prefix}.{child.name}" if prefix else child.name
                hi = max((getattr(n, "end_lineno", None) or child.lineno)
                         for n in ast.walk(child))
                self._scope_spans.append((child.lineno, hi, name))
                self._index_scopes(child, name)
            else:
                self._index_scopes(child, prefix)

    def is_suppressed(self, rule: str, line: int) -> bool:
        """A finding is suppressed by a disable comment on its own line OR
        on the first line of any statement enclosing it — so the documented
        standalone form works for findings anchored on a continuation line
        of a multi-line statement. A decorator stack counts as one such
        region (first decorator line through the ``def``/``async def``
        line): a standalone comment above the stack lexically binds to the
        FIRST decorator line, and must still reach findings anchored on a
        later decorator or the def line itself."""
        for cand in (line, *self._stmt_starts_covering(line),
                     *self._decorator_starts_covering(line)):
            disabled = self.suppressions.get(cand, set())
            if rule in disabled or "ALL" in disabled:
                return True
        return False

    _COMPOUND = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                 ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
                 ast.AsyncWith, ast.Try)

    def _stmt_starts_covering(self, line: int):
        # SIMPLE statements only: a disable on a `def`/`if`/`with` line
        # must not silence the whole block under it
        if getattr(self, "_stmt_spans", None) is None:
            self._stmt_spans = []
            for node in ast.walk(self.tree):
                if isinstance(node, ast.stmt) \
                        and not isinstance(node, self._COMPOUND):
                    hi = max((getattr(n, "end_lineno", None) or node.lineno)
                             for n in ast.walk(node))
                    self._stmt_spans.append((node.lineno, hi))
        return [lo for lo, hi in self._stmt_spans if lo <= line <= hi]

    def _decorator_starts_covering(self, line: int):
        if getattr(self, "_decorator_spans", None) is None:
            self._decorator_spans = []
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)) and node.decorator_list:
                    self._decorator_spans.append(
                        (node.decorator_list[0].lineno, node.lineno))
        return [lo for lo, hi in self._decorator_spans if lo <= line <= hi]

    def finding(self, rule: str, node: ast.AST, message: str,
                token: str) -> Finding:
        """Build a Finding anchored at ``node`` with a line-free anchor."""
        qn = self.qualname(node)
        anchor = f"{qn}:{token}" if qn else token
        return Finding(rule=rule, path=self.relpath,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       message=message, anchor=anchor)


class ProjectContext:
    """Every parsed file of one run (project-wide rules finalize over it)."""

    def __init__(self, root: str, files: List[FileContext]):
        self.root = root
        self.files = files

    def get(self, relpath: str) -> Optional[FileContext]:
        for f in self.files:
            if f.relpath == relpath:
                return f
        return None


class Rule:
    """Base class: subclasses set ``id``/``name``/``description`` and
    implement ``check`` (per file) and/or ``finalize`` (project-wide).
    Rules that accumulate cross-file state override ``begin_run`` to clear
    it — one rule instance may serve several ``LintEngine.run`` calls."""

    id: str = "DS000"
    name: str = "base"
    description: str = ""

    def begin_run(self) -> None:
        pass

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finalize(self, project: ProjectContext) -> Iterable[Finding]:
        return ()


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]               # unsuppressed, not in baseline
    suppressed: List[Finding]             # killed by inline disables
    baselined: List[Finding]              # matched a baseline entry
    stale_baseline: List[dict]            # covered entries nothing matched
    files_checked: int = 0
    parse_errors: List[Finding] = dataclasses.field(default_factory=list)
    linted_paths: List[str] = dataclasses.field(default_factory=list)
    active_rules: List[str] = dataclasses.field(default_factory=list)

    @property
    def exit_code(self) -> int:
        # stale entries fail too: an expired-but-unexpunged baseline entry
        # would silently absorb one future regression at the same anchor
        return 1 if self.findings or self.stale_baseline else 0


_SKIP_DIRS = {"__pycache__", ".git", ".hg", "build", "dist", "node_modules",
              "csrc",
              # seeded-violation fixtures: linted only when targeted
              # explicitly by tests, never by a directory sweep
              "dslint_fixtures"}


def iter_python_files(paths: Iterable[str]) -> List[str]:
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS
                                     and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
    # overlapping inputs (a directory plus a file inside it) must not lint
    # a file twice — duplicates double findings and blow per-anchor
    # baseline count budgets
    return list(dict.fromkeys(out))


#: (abspath, mtime_ns, size) -> (source, tree). The test suite lints the
#: whole package several times in one process (self-lint, the hot-sync
#: proof, offline purity); the trees are immutable to rules, so re-parsing
#: ~200 unchanged files each run is pure waste. Keyed on stat so edited
#: fixtures (tmp-path copies, --changed scratch repos) never hit stale.
_PARSE_CACHE: dict = {}
_PARSE_CACHE_MAX = 1024


def _load_parsed(abspath):
    try:
        st = os.stat(abspath)
        key = (abspath, st.st_mtime_ns, st.st_size)
    except OSError:
        key = None
    if key is not None and key in _PARSE_CACHE:
        return _PARSE_CACHE[key]
    with open(abspath, "r", encoding="utf-8") as f:
        source = f.read()
    tree = ast.parse(source, filename=abspath)
    if key is not None:
        if len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
            _PARSE_CACHE.clear()
        _PARSE_CACHE[key] = (source, tree)
    return source, tree


class LintEngine:
    def __init__(self, rules: List[Rule], root: Optional[str] = None,
                 select: Optional[Iterable[str]] = None,
                 ignore: Optional[Iterable[str]] = None):
        selected = {r.upper() for r in select} if select else None
        ignored = {r.upper() for r in ignore} if ignore else set()
        self.rules = [r for r in rules
                      if (selected is None or r.id in selected)
                      and r.id not in ignored]
        self.root = os.path.abspath(root) if root else None

    # ------------------------------------------------------------------
    def _relpath(self, abspath: str) -> str:
        root = self.root or os.getcwd()
        try:
            rel = os.path.relpath(abspath, root)
        except ValueError:            # different drive (windows)
            rel = abspath
        return rel.replace(os.sep, "/")

    def run(self, paths: Iterable[str],
            baseline: Optional[dict] = None) -> LintResult:
        files: List[FileContext] = []
        parse_errors: List[Finding] = []
        for abspath in iter_python_files(paths):
            relpath = self._relpath(abspath)
            try:
                source, tree = _load_parsed(abspath)
            except (SyntaxError, UnicodeDecodeError) as e:
                parse_errors.append(Finding(
                    rule="DS000", path=relpath,
                    line=getattr(e, "lineno", 0) or 0, col=0,
                    message=f"file does not parse: {e.__class__.__name__}: {e}",
                    anchor="parse-error"))
                continue
            files.append(FileContext(abspath, relpath, source, tree))

        project = ProjectContext(self.root or os.getcwd(), files)
        raw: List[Finding] = list(parse_errors)
        for rule in self.rules:
            rule.begin_run()
            for ctx in files:
                raw.extend(rule.check(ctx))
            raw.extend(rule.finalize(project))
        raw.sort(key=lambda f: (f.path, f.line, f.rule))

        # inline suppressions
        kept, suppressed = [], []
        by_path = {f.relpath: f for f in files}
        for f in raw:
            ctx = by_path.get(f.path)
            if ctx is not None and ctx.is_suppressed(f.rule, f.line):
                suppressed.append(f)
            else:
                kept.append(f)

        # baseline (stale judgment only over what this run re-evaluated)
        from deepspeed_tpu.tools.dslint.baseline import match_baseline
        covered = {f.relpath for f in files}
        active = {r.id for r in self.rules}
        findings, baselined, stale = match_baseline(
            kept, baseline, covered_paths=covered, active_rules=active)
        return LintResult(findings=findings, suppressed=suppressed,
                          baselined=baselined, stale_baseline=stale,
                          files_checked=len(files),
                          parse_errors=parse_errors,
                          linted_paths=sorted(covered),
                          active_rules=sorted(active))
