"""Small AST helpers shared by the dslint rules."""

import ast
from typing import Iterator, List, Optional, Sequence, Tuple

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None. ``self.x`` keeps the
    ``self.`` prefix so callers can distinguish methods from locals."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def keyword_arg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def literal_int_tuple(node: ast.expr) -> Optional[Tuple[int, ...]]:
    """Evaluate an int / tuple-of-ints literal (donate_argnums shapes)."""
    try:
        v = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None
    if isinstance(v, int):
        return (v,)
    if isinstance(v, (tuple, list)) and all(isinstance(x, int) for x in v):
        return tuple(v)
    return None


def functions_of(scope: ast.AST) -> Iterator[ast.AST]:
    """Direct function/method children of a module or class body."""
    for node in ast.iter_child_nodes(scope):
        if isinstance(node, FunctionNode):
            yield node


def classes_of(module: ast.Module) -> Iterator[ast.ClassDef]:
    for node in ast.walk(module):
        if isinstance(node, ast.ClassDef):
            yield node


def methods_of(cls: ast.ClassDef) -> dict:
    return {n.name: n for n in cls.body if isinstance(n, FunctionNode)}


def self_attr(node: ast.AST) -> Optional[str]:
    """``x`` when node is the attribute access ``self.x``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def assigned_names(target: ast.expr) -> Iterator[ast.expr]:
    """Flatten tuple/list/starred assignment targets to leaf expressions."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            yield from assigned_names(el)
    elif isinstance(target, ast.Starred):
        yield from assigned_names(target.value)
    else:
        yield target


def statement_targets(stmt: ast.stmt) -> List[ast.expr]:
    """Assignment target leaves of a statement (Assign/AugAssign/AnnAssign/
    with-as/for)."""
    out: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            out.extend(assigned_names(t))
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        out.extend(assigned_names(stmt.target))
    elif isinstance(stmt, ast.For):
        out.extend(assigned_names(stmt.target))
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                out.extend(assigned_names(item.optional_vars))
    return out


_LOCKISH = ("lock", "mutex", "cond", "condition", "sem")


def _lockish_expr(expr: ast.expr) -> bool:
    name = dotted_name(expr)
    if isinstance(expr, ast.Call):
        name = call_name(expr)        # with self._lock_for(x): ...
    if not name:
        return False
    leaf = name.split(".")[-1].lower()
    return any(tok in leaf for tok in _LOCKISH)


def lock_protected_lines(func: ast.AST) -> set:
    """Line numbers inside ``with <lock-ish>`` blocks of ``func``, plus —
    for the explicit ``x.acquire()`` / ``x.release()`` pattern — the span
    from the first acquire to the matching release (to the end of the
    function when no release is visible). Code BEFORE the acquire is not
    protected: treating the whole function as locked would silence real
    unprotected writes."""
    lines: set = set()
    acquire_line = None
    release_line = None
    for node in ast.walk(func):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            if any(_lockish_expr(item.context_expr) for item in node.items):
                hi = max((getattr(n, "end_lineno", None) or node.lineno)
                         for n in ast.walk(node))
                lines.update(range(node.lineno, hi + 1))
        elif isinstance(node, ast.Call):
            nm = call_name(node)
            if nm and nm.endswith(".acquire"):
                acquire_line = min(acquire_line or node.lineno, node.lineno)
            elif nm and nm.endswith(".release"):
                release_line = max(release_line or node.lineno, node.lineno)
    if acquire_line is not None:
        end = release_line if release_line is not None \
            else max((getattr(n, "end_lineno", None) or func.lineno)
                     for n in ast.walk(func))
        lines.update(range(acquire_line, end + 1))
    return lines


def import_aliases(module: ast.Module, targets: Sequence[str]) -> dict:
    """Map local alias -> canonical module name for ``targets`` (e.g.
    ``{"np": "numpy", "jnp": "jax.numpy", "jax": "jax"}``)."""
    out = {}
    for node in ast.walk(module):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in targets:
                    out[a.asname or a.name] = a.name
        elif isinstance(node, ast.ImportFrom):
            full = node.module or ""
            for a in node.names:
                dotted = f"{full}.{a.name}" if full else a.name
                if dotted in targets:
                    out[a.asname or a.name] = dotted
    return out
