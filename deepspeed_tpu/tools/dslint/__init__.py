"""dslint — JAX/TPU-aware static analysis for this repo's recurring bug
classes.

Rule catalog (see ``docs/static_analysis.md``):

  DS001 donation-safety        read of a pytree after donate_argnums dispatch
  DS002 host-sync-in-hot-path  float()/.item()/device_get in a registered hot path
  DS003 0-d-array-truthiness   array reduction used as a Python bool
  DS004 thread-shared-state    unlocked writes across a thread boundary
  DS005 signal-handler-safety  non-reentrant work inside a signal handler
  DS006 config-key-drift       raw keys vs config/constants.py, dead constants
  DS007 trace-name-drift       emitted trace names vs telemetry/names.py registry
  DS008 prom-family-uniqueness at most one '# TYPE' emission site per metric family
  DS009 offline-purity         OFFLINE_ONLY modules never (transitively) import jax

Programmatic entry points::

    from deepspeed_tpu.tools.dslint import lint_paths
    result = lint_paths(["deepspeed_tpu/"], baseline_path="dslint_baseline.json")
    assert not result.findings
"""

from typing import Iterable, Optional

from deepspeed_tpu.tools.dslint.baseline import (find_default_baseline,
                                                 load_baseline,
                                                 write_baseline)
from deepspeed_tpu.tools.dslint.engine import (Finding, LintEngine,
                                               LintResult, Rule)
from deepspeed_tpu.tools.dslint.rules import ALL_RULES, get_rules

__all__ = [
    "Finding", "LintEngine", "LintResult", "Rule", "ALL_RULES", "get_rules",
    "lint_paths", "load_baseline", "write_baseline", "find_default_baseline",
]


def lint_paths(paths: Iterable[str], baseline_path: Optional[str] = None,
               root: Optional[str] = None,
               select: Optional[Iterable[str]] = None,
               ignore: Optional[Iterable[str]] = None,
               rules: Optional[list] = None) -> LintResult:
    """One-call lint: fresh rules, optional baseline, relative to ``root``
    (defaults to the baseline file's directory so baseline paths match)."""
    baseline = load_baseline(baseline_path) if baseline_path else None
    if root is None and baseline_path:
        import os
        root = os.path.dirname(os.path.abspath(baseline_path))
    engine = LintEngine(rules if rules is not None else get_rules(),
                        root=root, select=select, ignore=ignore)
    return engine.run(paths, baseline=baseline)
