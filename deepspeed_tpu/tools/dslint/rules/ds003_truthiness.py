"""DS003 — 0-d array truthiness: wrap array reductions in ``bool(...)``.

``np.all(x)``/``np.isfinite(x).all()`` return 0-d numpy bool ARRAYS, not
Python bools. Used directly as a flag they *appear* to work in an ``if``,
then bite downstream: ``is True`` comparisons fail, ``json.dump`` chokes,
``jnp`` variants raise ``TracerBoolConversionError`` under jit, and a
0-d array stored where a bool is expected silently changes the meaning of
identity checks (PR 3's guards bug: 0-d bool arrays were flag VALUES being
re-interpreted as finiteness reports). The mechanical discipline: convert
at the boundary — ``bool(np.all(x))``.

Flags array-reduction expressions used where Python evaluates truthiness
(``if``/``while``/``assert``/``and``/``or``/``not``/ternary/comprehension
conditions) and in ``return`` position of bool-shaped functions
(``-> bool`` annotation or ``is_``/``has_``/``can_``/``should_`` prefix)
unless wrapped in ``bool(...)``.
"""

import ast

from deepspeed_tpu.tools.dslint import astutil
from deepspeed_tpu.tools.dslint.engine import FileContext, Rule

_NUMPY_MODULES = {"np", "numpy", "jnp", "jax.numpy"}
_REDUCER_FUNCS = {"all", "any", "isfinite", "isnan", "isinf", "isclose",
                  "logical_and", "logical_or", "logical_not", "equal",
                  "greater", "less", "array_equal"}
_REDUCER_METHODS = {"all", "any"}
_BOOL_FN_PREFIXES = ("is_", "has_", "can_", "should_")


def _offending_call(expr: ast.expr):
    """Return (node, description) when ``expr`` is an array-returning
    reduction used bare (module function or ``.all()``/``.any()``)."""
    if not isinstance(expr, ast.Call):
        return None
    name = astutil.call_name(expr)
    if name:
        parts = name.split(".")
        if (len(parts) >= 2 and parts[-1] in _REDUCER_FUNCS
                and ".".join(parts[:-1]) in _NUMPY_MODULES):
            return expr, name
    if (isinstance(expr.func, ast.Attribute)
            and expr.func.attr in _REDUCER_METHODS
            and not expr.args and not expr.keywords):
        # x.all() / jnp.isfinite(x).all() — but not builtins all(...)/any(...)
        return expr, f".{expr.func.attr}()"
    return None


class ArrayTruthinessRule(Rule):
    id = "DS003"
    name = "0-d-array-truthiness"
    description = ("numpy/jax array reduction used as a Python bool "
                   "without bool(...) conversion")

    def check(self, ctx: FileContext):
        findings = []

        def flag(expr: ast.expr, where: str):
            hit = _offending_call(expr)
            if hit is None:
                return
            node, name = hit
            findings.append(ctx.finding(
                self.id, node,
                f"`{name}` used as a Python bool in {where}: it returns a "
                f"0-d array (and a tracer error under jit) — wrap it in "
                f"bool(...) at the boundary", token=name))

        for node in ast.walk(ctx.tree):
            roots = []
            if isinstance(node, (ast.If, ast.While)):
                roots.append((node.test, "a condition"))
            elif isinstance(node, ast.Assert):
                roots.append((node.test, "an assert"))
            elif isinstance(node, ast.IfExp):
                roots.append((node.test, "a ternary condition"))
            elif isinstance(node, ast.comprehension):
                roots.extend((i, "a comprehension filter") for i in node.ifs)
            elif isinstance(node, ast.BoolOp):
                roots.extend((v, "a boolean expression")
                             for v in node.values)
            elif isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                              ast.Not):
                roots.append((node.operand, "a `not` expression"))
            for root, where in roots:
                # only the root needs flagging here: nested BoolOp/Not
                # operands are themselves visited as nodes by the walk
                flag(root, where)

            if isinstance(node, astutil.FunctionNode):
                returns_bool = (
                    (isinstance(node.returns, ast.Name)
                     and node.returns.id == "bool")
                    or node.name.startswith(_BOOL_FN_PREFIXES))
                if returns_bool:
                    for n in ast.walk(node):
                        if isinstance(n, ast.Return) and n.value is not None:
                            flag(n.value,
                                 f"the return of bool-shaped "
                                 f"`{node.name}()`")
        return findings
