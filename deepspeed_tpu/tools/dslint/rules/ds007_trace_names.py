"""DS007 — trace-name drift between emitters and the registry.

Every literal name handed to ``Tracer.span/instant/counter/complete``
must appear in ``deepspeed_tpu/telemetry/names.py`` ``TRACE_NAMES``
(with a matching kind); dynamic f-string names must start with a
registered ``DYNAMIC_PREFIXES`` entry. The offline stage tables
(attribution / serve_attribution / crossrank) derive their constants
from the same registry, so a renamed span is a lint finding instead of a
silent attribution hole (the renamed stage's time quietly becoming
``residual`` was the pre-v2 failure mode).

Resolution is deliberately shallow and sound-by-silence: a first
argument that is a string constant or a same-file module-level string
constant is checked; anything the rule cannot resolve statically
(parameters, dict lookups, attributes) is skipped, never guessed — the
taint rule's discipline of degrading to silence rather than false
positives.
"""

import ast
import os
from typing import Dict, Iterable, List, Optional, Tuple

from deepspeed_tpu.tools.dslint.engine import (FileContext, Finding,
                                               ProjectContext, Rule)

_KINDS = ("span", "instant", "counter", "complete")
_REGISTRY_SUFFIX = "telemetry/names.py"


def _emitter_kind(call: ast.Call) -> Optional[str]:
    """The event kind if this looks like a Tracer emit call: receiver is
    a name/attribute/call whose leaf mentions ``tracer`` (``tracer``,
    ``self.tracer``, ``get_tracer()``, ``self._tracer()``) or is the
    conventional short alias ``tr``."""
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in _KINDS:
        return None
    recv = f.value
    if isinstance(recv, ast.Name):
        leaf = recv.id
    elif isinstance(recv, ast.Attribute):
        leaf = recv.attr
    elif isinstance(recv, ast.Call):
        cf = recv.func
        leaf = (cf.id if isinstance(cf, ast.Name)
                else cf.attr if isinstance(cf, ast.Attribute) else "")
    else:
        return None
    if "tracer" in leaf.lower() or leaf == "tr":
        return f.attr
    return None


def _module_str_constants(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                           ast.Name):
            targets = [node.target]
        if targets and isinstance(getattr(node, "value", None),
                                  ast.Constant) \
                and isinstance(node.value.value, str):
            for t in targets:
                out[t.id] = node.value.value
    return out


def _fstring_head(js: ast.JoinedStr) -> str:
    head = ""
    for part in js.values:
        if isinstance(part, ast.Constant) and isinstance(part.value, str):
            head += part.value
        else:
            break
    return head


def parse_registry(tree: ast.Module
                   ) -> Tuple[Dict[str, Tuple[str, ...]], Tuple[str, ...]]:
    """Extract ``TRACE_NAMES`` / ``DYNAMIC_PREFIXES`` from the registry
    module's AST — dslint never imports the project it lints."""
    names: Dict[str, Tuple[str, ...]] = {}
    prefixes: Tuple[str, ...] = ()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target, value = node.target.id, node.value
        else:
            continue
        if target == "TRACE_NAMES" and isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    continue
                kinds = tuple(
                    e.value for e in getattr(v, "elts", [])
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
                names[k.value] = kinds
        elif target == "DYNAMIC_PREFIXES" and isinstance(value, ast.Tuple):
            prefixes = tuple(e.value for e in value.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, str))
    return names, prefixes


def _find_registry(project: ProjectContext
                   ) -> Optional[Tuple[Dict[str, Tuple[str, ...]],
                                       Tuple[str, ...]]]:
    for ctx in project.files:
        if ctx.relpath.endswith(_REGISTRY_SUFFIX) \
                or ctx.relpath == "names.py":
            return parse_registry(ctx.tree)
    # subset run (--changed): locate the registry on disk from any linted
    # file's absolute path
    for ctx in project.files:
        d = os.path.dirname(ctx.abspath)
        while True:
            for cand in (
                    os.path.join(d, "deepspeed_tpu", "telemetry", "names.py"),
                    os.path.join(d, "telemetry", "names.py")):
                if os.path.isfile(cand):
                    try:
                        return parse_registry(ast.parse(
                            open(cand, encoding="utf-8").read()))
                    except (OSError, SyntaxError):
                        return None
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    return None


class TraceNameRule(Rule):
    id = "DS007"
    name = "trace-name-drift"
    description = ("trace name emitted via Tracer.span/instant/counter/"
                   "complete is not declared in telemetry/names.py "
                   "TRACE_NAMES (or its kind is not registered)")

    def finalize(self, project: ProjectContext) -> Iterable[Finding]:
        reg = _find_registry(project)
        if reg is None:
            return []          # nothing to check against (scratch subset)
        names, prefixes = reg
        findings: List[Finding] = []
        for ctx in project.files:
            if ctx.relpath.endswith(_REGISTRY_SUFFIX) \
                    or ctx.relpath == "names.py" \
                    or ctx.relpath.startswith("tests/") \
                    or "/tests/" in ctx.relpath \
                    or "tools/dslint" in ctx.relpath:
                continue
            consts = _module_str_constants(ctx.tree)
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                kind = _emitter_kind(node)
                if kind is None or not node.args:
                    continue
                findings.extend(self._check_name(ctx, node, kind,
                                                 node.args[0], consts,
                                                 names, prefixes))
        return findings

    def _check_name(self, ctx: FileContext, call: ast.Call, kind: str,
                    arg: ast.expr, consts: Dict[str, str],
                    names: Dict[str, Tuple[str, ...]],
                    prefixes: Tuple[str, ...]):
        name: Optional[str] = None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
        elif isinstance(arg, ast.Name):
            name = consts.get(arg.id)
        elif isinstance(arg, ast.JoinedStr):
            head = _fstring_head(arg)
            if not any(head.startswith(p) and p for p in prefixes):
                yield ctx.finding(
                    self.id, call,
                    f"dynamic trace name with unregistered head "
                    f"{head!r} — literal-prefix f-strings must start "
                    f"with a telemetry/names.py DYNAMIC_PREFIXES entry",
                    token=f"prefix:{head}")
            return
        if name is None:
            return                      # unresolvable: skip, never guess
        if name not in names:
            yield ctx.finding(
                self.id, call,
                f"trace name {name!r} is not registered in telemetry/"
                f"names.py TRACE_NAMES — register it (and extend the "
                f"stage tables if an offline sweep should attribute it)",
                token=f"name:{name}")
        elif kind not in names[name]:
            yield ctx.finding(
                self.id, call,
                f"trace name {name!r} emitted as `{kind}` but registered "
                f"kinds are {names[name]!r} — update TRACE_NAMES or the "
                f"emitter", token=f"kind:{name}:{kind}")
