"""DS005 — signal-handler safety: handlers set flags, nothing else.

A handler registered via ``signal.signal`` runs *between two arbitrary
bytecodes of the main thread* — re-entering I/O, allocating heavily, or
taking a lock the interrupted code may already hold is how preemption
turns into a torn checkpoint or a deadlock (the exact failure the
resilience subsystem exists to kill; its own handler deliberately only
sets ``_preempt_signal`` and defers the autosave to the step boundary).

The rule resolves each registered handler (lambda inline, module function
by name, ``self._method``) and flags non-reentrant work in its body:
file/OS I/O, ``json``/``pickle`` dumps, subprocess spawns, lock
acquisition, thread joins, jax calls (allocation + dispatch), and logging
(the logging module takes a module-level lock). ``os.kill``/``sys.exit``/
``Event.set``/attribute flag writes are fine — that IS the pattern.

A deliberate exception (e.g. one best-effort log line) is recorded at the
call site with ``# dslint: disable=DS005 -- <why>``.
"""

import ast
from typing import Iterable, Optional

from deepspeed_tpu.tools.dslint import astutil
from deepspeed_tpu.tools.dslint.engine import FileContext, Rule

_FORBIDDEN_NAME_CALLS = {"open", "print", "exec", "eval", "input"}
_FORBIDDEN_DOTTED_PREFIXES = ("os.", "json.", "pickle.", "shutil.",
                              "subprocess.", "jax.", "logging.", "logger.",
                              "faulthandler.")
# os-level calls that ARE async-signal-safe-ish and idiomatic in handlers
_ALLOWED_DOTTED = {"os.kill", "os.getpid", "sys.exit", "os._exit",
                   "signal.signal", "os.write"}
_FORBIDDEN_ATTR_CALLS = {"write", "flush", "acquire", "join", "dump",
                         "save", "makedirs", "rename", "replace", "remove",
                         "unlink", "device_get", "block_until_ready",
                         "send", "sendall", "put", "connect",
                         "debug", "info", "warning", "error", "exception",
                         "critical", "log"}


def _handler_findings(rule, ctx: FileContext, handler_body: ast.AST,
                      handler_desc: str):
    for n in ast.walk(handler_body):
        if not isinstance(n, ast.Call):
            continue
        name = astutil.call_name(n)
        reason = None
        if isinstance(n.func, ast.Name) and n.func.id in _FORBIDDEN_NAME_CALLS:
            reason = f"{n.func.id}()"
        elif name and name in _ALLOWED_DOTTED:
            continue
        elif name and name.startswith(_FORBIDDEN_DOTTED_PREFIXES):
            reason = name
        elif (isinstance(n.func, ast.Attribute)
              and n.func.attr in _FORBIDDEN_ATTR_CALLS):
            reason = f".{n.func.attr}()"
        if reason:
            yield ctx.finding(
                rule.id, n,
                f"signal handler {handler_desc} does non-reentrant work "
                f"(`{reason}`): it can fire between any two bytecodes — "
                f"set a flag here and do the work at a safe point (step "
                f"boundary / main loop)", token=f"{handler_desc}:{reason}")


class SignalHandlerRule(Rule):
    id = "DS005"
    name = "signal-handler-safety"
    description = ("signal.signal handler doing non-reentrant work "
                   "(I/O, allocation, lock acquisition, logging)")

    def check(self, ctx: FileContext):
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if astutil.call_name(node) != "signal.signal":
                continue
            if len(node.args) < 2:
                continue
            handler = node.args[1]
            body, desc = self._resolve(ctx, handler)
            if body is None:
                continue
            findings.extend(_handler_findings(self, ctx, body, desc))
        return findings

    # ------------------------------------------------------------------
    def _resolve(self, ctx: FileContext, handler: ast.expr):
        if isinstance(handler, ast.Lambda):
            return handler.body, f"<lambda:{handler.lineno}>"
        if isinstance(handler, ast.Name):
            fn = self._find_def(ctx.tree, handler.id)
            if fn is not None:
                return fn, f"`{handler.id}`"
            return None, None
        attr = astutil.self_attr(handler)
        if attr:
            for cls in astutil.classes_of(ctx.tree):
                fn = astutil.methods_of(cls).get(attr)
                if fn is not None:
                    return fn, f"`{cls.name}.{attr}`"
        return None, None

    @staticmethod
    def _find_def(tree: ast.Module, name: str) -> Optional[ast.AST]:
        for n in ast.walk(tree):
            if isinstance(n, astutil.FunctionNode) and n.name == name:
                return n
        return None
