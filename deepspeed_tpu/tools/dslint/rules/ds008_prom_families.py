"""DS008 — Prometheus family uniqueness (one TYPE emission site each).

The Prometheus text parser rejects an exposition wholesale when a metric
family's metadata (``# TYPE``) appears twice — a real outage fixed in
PR 8 and re-pinned by hand in PRs 11/13. This rule mechanizes it
project-wide: every ``"# TYPE ..."`` string the package can emit is a
*claim*, and claims must not be able to collide.

A string constant ``"# TYPE dstpu_x summary"`` claims the concrete
family ``dstpu_x``. An f-string ``f"# TYPE dstpu_serving_{key} {kind}"``
claims the *static prefix* ``dstpu_serving_`` — the one emission site
owns that whole namespace. Findings:

* the same concrete family claimed at more than one site,
* a concrete family that falls inside a prefix claimed elsewhere (the
  fleet ``/metrics`` hazard: a hand-emitted gauge inside the counter
  loop's namespace — adding the gauge's key to the counter table would
  duplicate the family silently),
* two *different functions* claiming overlapping prefixes (inside one
  function the code can, and visibly does, keep the key sets disjoint),
* a TYPE f-string with no static family prefix at all (``f"# TYPE
  {name} ..."`` claims everything and can collide with anything).

The fix shape is the metrics.py discipline: route every family of a
namespace through ONE emission site whose f-string carries the namespace
inline.
"""

import ast
from typing import Iterable, List, NamedTuple, Optional, Tuple

from deepspeed_tpu.tools.dslint.engine import (Finding, ProjectContext,
                                               Rule)

_MARK = "# TYPE "


class _Claim(NamedTuple):
    relpath: str
    qualname: str               # enclosing function ("" at module level)
    node: ast.AST
    ctx: object
    family: Optional[str]       # concrete family, or None for a prefix
    prefix: Optional[str]       # static prefix, or None for concrete


def _classify(head: str, complete: bool) -> Tuple[Optional[str],
                                                  Optional[str]]:
    """``head`` is the literal text after ``"# TYPE "``. If it already
    contains the full family (a space follows it, or the string ends
    there as a plain constant), the claim is concrete; otherwise the
    head is a static family prefix."""
    if " " in head:
        return head.split(" ", 1)[0], None
    if complete:
        return (head, None) if head else (None, "")
    return None, head


def _iter_claims(ctx) -> Iterable[_Claim]:
    in_fstring = {id(v) for n in ast.walk(ctx.tree)
                  if isinstance(n, ast.JoinedStr) for v in n.values}
    for node in ast.walk(ctx.tree):
        if id(node) in in_fstring:
            continue                    # heads count via their JoinedStr
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value.startswith(_MARK):
            fam, pref = _classify(node.value[len(_MARK):], complete=True)
            yield _Claim(ctx.relpath, ctx.qualname(node), node, ctx,
                         fam, pref)
        elif isinstance(node, ast.JoinedStr) and node.values \
                and isinstance(node.values[0], ast.Constant) \
                and isinstance(node.values[0].value, str) \
                and node.values[0].value.startswith(_MARK):
            fam, pref = _classify(node.values[0].value[len(_MARK):],
                                  complete=False)
            yield _Claim(ctx.relpath, ctx.qualname(node), node, ctx,
                         fam, pref)


class PromFamilyRule(Rule):
    id = "DS008"
    name = "prometheus-family-uniqueness"
    description = ("a Prometheus metric family's `# TYPE` metadata is "
                   "emitted (or can be emitted) from more than one site "
                   "— duplicate metadata makes the text parser reject "
                   "the whole exposition")

    def finalize(self, project: ProjectContext) -> Iterable[Finding]:
        claims: List[_Claim] = []
        for ctx in project.files:
            if ctx.relpath.startswith("tests/") or "/tests/" in ctx.relpath \
                    or "tools/dslint" in ctx.relpath:
                continue                # the lint tool names the pattern
            claims.extend(_iter_claims(ctx))

        findings: List[Finding] = []
        concretes = [c for c in claims if c.family is not None]
        prefixes = [c for c in claims if c.prefix is not None]

        for c in prefixes:
            if c.prefix == "":
                findings.append(c.ctx.finding(
                    self.id, c.node,
                    "TYPE emission with no static family prefix — "
                    "`f\"# TYPE {name} ...\"` claims every family and "
                    "can collide with any other emission site; inline "
                    "the namespace (`f\"# TYPE dstpu_xxx_{key} ...\"`)",
                    token="prefix:"))

        seen = {}
        for c in concretes:
            prior = seen.get(c.family)
            if prior is not None and (prior.relpath, prior.node.lineno) \
                    != (c.relpath, c.node.lineno):
                findings.append(c.ctx.finding(
                    self.id, c.node,
                    f"family `{c.family}` TYPE metadata also emitted at "
                    f"{prior.relpath}:{prior.node.lineno} — exactly one "
                    f"emission site per family",
                    token=f"dup:{c.family}"))
            else:
                seen[c.family] = c

        for c in concretes:
            for p in prefixes:
                if p.prefix and c.family.startswith(p.prefix) \
                        and (p.relpath, p.node.lineno) \
                        != (c.relpath, c.node.lineno):
                    findings.append(c.ctx.finding(
                        self.id, c.node,
                        f"family `{c.family}` lies inside the namespace "
                        f"`{p.prefix}*` claimed by the dynamic TYPE "
                        f"emission at {p.relpath}:{p.node.lineno} — one "
                        f"key collision away from duplicate metadata; "
                        f"route this family through that site (or move "
                        f"it out of the namespace)",
                        token=f"shadow:{c.family}"))

        for i, a in enumerate(prefixes):
            for b in prefixes[i + 1:]:
                if not a.prefix or not b.prefix:
                    continue        # empty prefixes already reported
                if (a.relpath, a.qualname) == (b.relpath, b.qualname):
                    continue        # same function keeps its keys disjoint
                if a.prefix.startswith(b.prefix) \
                        or b.prefix.startswith(a.prefix):
                    findings.append(b.ctx.finding(
                        self.id, b.node,
                        f"dynamic TYPE namespaces overlap: `{b.prefix}*` "
                        f"here vs `{a.prefix}*` at {a.relpath}:"
                        f"{a.node.lineno} — two functions can emit the "
                        f"same family's metadata",
                        token=f"overlap:{b.prefix}"))
        return findings
