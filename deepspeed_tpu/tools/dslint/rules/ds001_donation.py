"""DS001 — donation safety: never read a pytree after donating it.

``jax.jit(..., donate_argnums=...)`` invalidates the donated input buffers
the moment the call dispatches; a later read of the same Python reference
returns a deleted array (``RuntimeError: Array has been deleted``) — or,
worse, silently stale data when the read races the async dispatch. PR 3's
metric-ring bug was exactly this shape: ``EngineState`` buffers captured
after the state had been donated to the next compiled step.

Detection (scoped, line-ordered heuristic — loops/branches are not
path-sensitive):

  * donating callables: ``f = jax.jit(g, donate_argnums=...)`` locals,
    ``self._f = jax.jit(...)`` attributes (class-wide), and direct
    ``jax.jit(g, donate_argnums=...)(args)`` calls
  * a call through one marks its donated positional args (plain names or
    ``self.attr``) as dead
  * any later read of a dead reference in the same function — without an
    intervening rebind — is a finding; rebinding in the same statement
    (``state = f(state)``) is the blessed pattern and is not flagged

Non-literal ``donate_argnums`` fall back to position 0 (the overwhelmingly
common ``donate_argnums=(0,)`` state-threading shape).
"""

import ast
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.tools.dslint import astutil
from deepspeed_tpu.tools.dslint.engine import FileContext, Rule

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def _donating_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Donated positions when ``call`` is a jit/pjit wrap with donation."""
    if astutil.call_name(call) not in _JIT_NAMES:
        return None
    kw = astutil.keyword_arg(call, "donate_argnums")
    if kw is None:
        return None
    pos = astutil.literal_int_tuple(kw)
    if pos is not None:
        return pos or None      # donate_argnums=() donates NOTHING
    return (0,)                 # non-literal: assume the common state-at-0


class DonationSafetyRule(Rule):
    id = "DS001"
    name = "donation-safety"
    description = ("read of a pytree after it was passed to a "
                   "donate_argnums callable in the same scope")

    def check(self, ctx: FileContext):
        findings = []
        # class-wide donating attributes: self._f = jax.jit(..., donate...)
        for cls in astutil.classes_of(ctx.tree):
            donating_attrs: Dict[str, Tuple[int, ...]] = {}
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                pos = _donating_positions(node.value)
                if pos is None:
                    continue
                for t in node.targets:
                    attr = astutil.self_attr(t)
                    if attr:
                        donating_attrs[f"self.{attr}"] = pos
            for meth in astutil.methods_of(cls).values():
                findings.extend(
                    self._check_scope(ctx, meth, dict(donating_attrs)))
        for fn in astutil.functions_of(ctx.tree):
            findings.extend(self._check_scope(ctx, fn, {}))
        return findings

    # ------------------------------------------------------------------
    def _check_scope(self, ctx: FileContext, func: ast.AST,
                     donating: Dict[str, Tuple[int, ...]]):
        """``donating``: callee dotted name -> donated positions (seeded
        with class-wide jit attributes; locals added as they are bound)."""
        # pass 1: local donating callables (f = jax.jit(..., donate...))
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                pos = _donating_positions(node.value)
                if pos is None:
                    continue
                for t in node.targets:
                    name = astutil.dotted_name(t)
                    if name:
                        donating[name] = pos

        # pass 2: donation events — (ref dotted name, line donated). Each
        # call is attributed to its innermost enclosing statement so the
        # "rebound by the same statement" exemption sees the right targets
        # even when the call sits inside a compound statement.
        parents = {}
        for node in ast.walk(func):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        def innermost_stmt(node):
            n = parents.get(node)
            while n is not None and not isinstance(n, ast.stmt):
                n = parents.get(n)
            return n

        dead: List[Tuple[str, int, str]] = []   # (ref, line, callee)
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            callee = astutil.dotted_name(node.func)
            pos = donating.get(callee) if callee else None
            if pos is None and isinstance(node.func, ast.Call):
                # direct jax.jit(fn, donate_argnums=...)(args)
                pos = _donating_positions(node.func)
                callee = callee or "jax.jit(...)"
            if pos is None:
                continue
            stmt = innermost_stmt(node)
            rebound = ({astutil.dotted_name(t)
                        for t in astutil.statement_targets(stmt)}
                       if stmt is not None else set())
            end = getattr(node, "end_lineno", None) or node.lineno
            for i in pos:
                if i >= len(node.args):
                    continue
                ref = astutil.dotted_name(node.args[i])
                if ref is None or ref in rebound:
                    continue              # rebound by the same statement
                dead.append((ref, end, callee))
        if not dead:
            return []

        # pass 3: stores per ref (to clear deadness) and offending loads
        stores: Dict[str, List[int]] = {}
        for stmt in ast.walk(func):
            if not isinstance(stmt, ast.stmt):
                continue
            for t in astutil.statement_targets(stmt):
                name = astutil.dotted_name(t)
                if name:
                    stores.setdefault(name, []).append(stmt.lineno)

        findings = []
        reported = set()
        for node in ast.walk(func):
            if not isinstance(node, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            ref = astutil.dotted_name(node)
            if ref is None:
                continue
            for dref, dline, callee in dead:
                if ref != dref or node.lineno <= dline:
                    continue
                if any(dline < s <= node.lineno for s in stores.get(ref, [])):
                    continue              # rebound before this read
                key = (ref, dline, node.lineno)
                if key in reported:
                    continue
                reported.add(key)
                findings.append(ctx.finding(
                    self.id, node,
                    f"`{ref}` read after being donated to `{callee}` "
                    f"(line {dline}): donated buffers are deleted at "
                    f"dispatch — rebind the result or snapshot what you "
                    f"need BEFORE the donating call", token=ref))
        return findings
