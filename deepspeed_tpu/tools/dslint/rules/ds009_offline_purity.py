"""DS009 — offline purity, both directions, as lint.

``hotpath.OFFLINE_ONLY_MODULES`` (the dstpu plan/trace analyzers) are
stdlib-only by contract: they file-load standalone on jax-less hosts and
replay whole dumps. Two invariants used to be pinned by scattered
``-X importtime`` subprocess tests; this rule derives both from the
module-level import graph the call-graph builder already indexes:

* an OFFLINE_ONLY module must not reach ``jax``/``jaxlib`` through any
  chain of module-level project imports (lazy function-level imports are
  exactly the idiom that keeps a module pure, and are not in the graph);
* no file containing hot-path code (a ``HOT_ROOTS`` file, or any file
  with a function reachable from a root) may import an OFFLINE_ONLY
  module at module level — the replay analyzers do unbounded host work
  and must never ride a per-step import.

A declared OFFLINE_ONLY path that no longer matches a module is drift
and fires on ``hotpath.py`` itself, same as a rotted DS002 root.
"""

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from deepspeed_tpu.tools.dslint.callgraph import get_callgraph
from deepspeed_tpu.tools.dslint.engine import (FileContext, Finding,
                                               ProjectContext, Rule)
from deepspeed_tpu.tools.dslint.hotpath import (ESCAPE_HATCHES, HOT_ROOTS,
                                                OFFLINE_ONLY_MODULES)

_DEVICE_RUNTIMES = ("jax", "jaxlib")
_DECLARATION_FILE = "tools/dslint/hotpath.py"


def _match_module(modules: Dict[str, object], path: str) -> Optional[str]:
    if path in modules:
        return path
    for rel in modules:
        if rel.endswith("/" + path) or path.endswith("/" + rel):
            return rel
    return None


class OfflinePurityRule(Rule):
    id = "DS009"
    name = "offline-purity"
    description = ("an OFFLINE_ONLY module reaches jax through its "
                   "module-level import graph, or a hot-path file "
                   "imports an OFFLINE_ONLY module")

    def __init__(self, offline=OFFLINE_ONLY_MODULES, roots=HOT_ROOTS,
                 hatches=ESCAPE_HATCHES):
        self.offline = offline
        self.roots = roots
        self.hatches = hatches

    def finalize(self, project: ProjectContext) -> Iterable[Finding]:
        graph = get_callgraph(project)
        by_path: Dict[str, FileContext] = {f.relpath: f
                                           for f in project.files}
        findings: List[Finding] = []

        offline_rels = []
        for path in self.offline:
            rel = _match_module(graph.modules, path)
            if rel is not None:
                offline_rels.append(rel)
                continue
            decl = next((c for r, c in by_path.items()
                         if r.endswith(_DECLARATION_FILE)), None)
            if decl is not None:
                findings.append(decl.finding(
                    self.id, decl.tree,
                    f"offline-module drift: `{path}` in "
                    f"OFFLINE_ONLY_MODULES matches no module — update "
                    f"hotpath.py alongside the rename/removal",
                    token=f"offline:{path}"))

        # direction 1: offline modules must not reach a device runtime
        for rel in offline_rels:
            chain = self._runtime_chain(graph, rel)
            ctx = by_path.get(rel)
            if chain is None or ctx is None:
                continue
            via, runtime = chain
            hop = via[1] if len(via) > 1 else rel
            line = graph.modules[rel].import_lines.get(hop, 1)
            route = " -> ".join(via + [runtime])
            findings.append(ctx.finding(
                self.id, ast.Pass(lineno=line, col_offset=0),
                f"offline-only module imports `{runtime}` "
                f"{'transitively ' if len(via) > 1 else ''}({route}) — "
                f"the replay analyzers must stay loadable on jax-less "
                f"hosts; make the import lazy or break the chain",
                token=f"runtime:{runtime}"))

        # direction 2: hot files must not import offline modules
        hot_files = {r.path for r in self.roots}
        root_keys = [k for k in (graph.resolve(r.path, r.qualname)
                                 for r in self.roots) if k]
        prune = {k for k in (graph.resolve(h.path, h.qualname)
                             for h in self.hatches
                             if h.mode == "prune") if k}
        for key in graph.reachable_from(root_keys, prune=prune):
            info = graph.functions.get(key)
            if info is not None:
                hot_files.add(info.relpath)
        for hot in sorted(hot_files):
            rel = _match_module(graph.modules, hot)
            ctx = rel and by_path.get(rel)
            if not ctx:
                continue
            mod = graph.modules[rel]
            for off in offline_rels:
                if off in mod.internal_imports:
                    line = mod.import_lines.get(off, 1)
                    findings.append(ctx.finding(
                        self.id, ast.Pass(lineno=line, col_offset=0),
                        f"hot-path file imports offline-only module "
                        f"`{off}` at module level — the replay analyzer "
                        f"must never ride a per-step path; use the lazy "
                        f"package re-export or a function-level import",
                        token=f"import:{off}"))
        return findings

    # ------------------------------------------------------------------
    def _runtime_chain(self, graph, start: str
                       ) -> Optional[Tuple[List[str], str]]:
        """BFS over module-level project imports; returns the module
        chain from ``start`` to the first module that imports a device
        runtime, plus the runtime name."""
        pred: Dict[str, Optional[str]] = {start: None}
        queue = [start]
        while queue:
            rel = queue.pop(0)
            mod = graph.modules.get(rel)
            if mod is None:
                continue
            for rt in _DEVICE_RUNTIMES:
                if rt in mod.external_imports:
                    chain = [rel]
                    while pred[chain[-1]] is not None:
                        chain.append(pred[chain[-1]])
                    return list(reversed(chain)), rt
            for nxt in sorted(mod.internal_imports):
                if nxt not in pred:
                    pred[nxt] = rel
                    queue.append(nxt)
        return None
