"""DS004 — thread-shared state without a lock.

Every background thread this repo grew (``PrefetchLoader`` worker, the
serve loop, the step watchdog, checkpoint finalizers) shares instance
attributes with the main thread; each PR has hand-fixed at least one
unsynchronized access. The rule mechanizes the review question: *which
attributes cross the thread boundary, and does an unprotected WRITE sit on
either side?*

Per class:

  * thread-side methods = targets of ``threading.Thread(target=self._x)``
    plus their intra-class call closure, plus methods escaping as
    callbacks (``self._m`` passed as a call argument — watchdog
    ``on_flag=...`` shape) which may run on a foreign thread
  * an attribute fires when one side WRITES it without holding a lock
    (``with self._lock`` / explicit ``.acquire()``) and the other side
    touches it at all — ``__init__`` writes are exempt (they happen before
    the thread starts), and attributes holding synchronization primitives
    (Lock/Event/Queue/deque) are exempt (their methods are thread-safe)

Deliberate lock-free flags (GIL-atomic booleans, sticky one-way latches)
are fine — mark them ``# dslint: disable=DS004 -- <why it is safe>`` so
the review decision is recorded at the access.
"""

import ast
import dataclasses
from typing import Dict, List, Set

from deepspeed_tpu.tools.dslint import astutil
from deepspeed_tpu.tools.dslint.engine import FileContext, Rule

_SAFE_TYPES = {"Lock", "RLock", "Event", "Condition", "Semaphore",
               "BoundedSemaphore", "Barrier", "Queue", "LifoQueue",
               "PriorityQueue", "SimpleQueue", "deque", "local"}


@dataclasses.dataclass
class _Access:
    method: str
    node: ast.AST
    is_store: bool
    protected: bool


def _thread_target_methods(cls: ast.ClassDef) -> Set[str]:
    """Methods started as Thread targets or escaping as callbacks."""
    out: Set[str] = set()
    method_names = set(astutil.methods_of(cls))
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node) or ""
        is_thread = name.split(".")[-1] == "Thread"
        candidates = []
        if is_thread:
            kw = astutil.keyword_arg(node, "target")
            if kw is not None:
                candidates.append(kw)
        else:
            # escape analysis: self._m handed to any call (on_flag=...,
            # register(...)) may be invoked from a foreign thread
            candidates.extend(node.args)
            candidates.extend(kw.value for kw in node.keywords)
        for cand in candidates:
            attr = astutil.self_attr(cand)
            if attr and attr in method_names:
                out.add(attr)
    return out


def _closure(cls: ast.ClassDef, seeds: Set[str]) -> Set[str]:
    methods = astutil.methods_of(cls)
    seen, frontier = set(seeds), list(seeds)
    while frontier:
        m = methods.get(frontier.pop())
        if m is None:
            continue
        for node in ast.walk(m):
            if isinstance(node, ast.Call):
                attr = astutil.self_attr(node.func)
                if attr and attr in methods and attr not in seen:
                    seen.add(attr)
                    frontier.append(attr)
    return seen


def _safe_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attributes initialized to synchronization primitives."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            tname = (astutil.call_name(node.value) or "").split(".")[-1]
            if tname in _SAFE_TYPES:
                for t in node.targets:
                    attr = astutil.self_attr(t)
                    if attr:
                        out.add(attr)
    return out


class ThreadSharedStateRule(Rule):
    id = "DS004"
    name = "thread-shared-state"
    description = ("instance attribute written without a lock on one side "
                   "of a thread boundary and touched on the other")

    def check(self, ctx: FileContext):
        findings = []
        for cls in astutil.classes_of(ctx.tree):
            findings.extend(self._check_class(ctx, cls))
        return findings

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef):
        thread_side = _closure(cls, _thread_target_methods(cls))
        if not thread_side:
            return []
        safe = _safe_attrs(cls)
        methods = astutil.methods_of(cls)

        accesses: Dict[str, List[_Access]] = {}
        for mname, m in methods.items():
            locked = astutil.lock_protected_lines(m)
            for node in ast.walk(m):
                attr = astutil.self_attr(node)
                if attr is None or attr in safe:
                    continue
                is_store = isinstance(node.ctx, (ast.Store, ast.Del))
                accesses.setdefault(attr, []).append(_Access(
                    method=mname, node=node, is_store=is_store,
                    protected=node.lineno in locked))

        findings = []
        for attr, acc in sorted(accesses.items()):
            t_side = [a for a in acc if a.method in thread_side]
            o_side = [a for a in acc
                      if a.method not in thread_side
                      and a.method != "__init__"]
            if not t_side or not o_side:
                continue
            bad_writes = (
                [a for a in t_side if a.is_store and not a.protected]
                or [a for a in o_side if a.is_store and not a.protected])
            if not bad_writes:
                continue
            w = bad_writes[0]
            other = (o_side if w.method in thread_side else t_side)[0]
            findings.append(ctx.finding(
                self.id, w.node,
                f"`self.{attr}` written in `{w.method}` without a lock but "
                f"shared across the thread boundary (also touched in "
                f"`{other.method}`): guard both sides with a Lock, use a "
                f"threading.Event/queue.Queue, or record why lock-free is "
                f"safe with `# dslint: disable=DS004 -- reason`",
                token=attr))
        return findings
