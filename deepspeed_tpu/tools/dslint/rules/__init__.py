"""dslint rule registry: one module per rule, IDs DS001..DS009.

Adding a rule: subclass ``Rule`` in a new ``ds0XX_*.py``, give it ``id``/
``name``/``description``, implement ``check`` (per-file) and/or
``finalize`` (project-wide), and append it to ``ALL_RULES`` here. Add a
fires/doesn't-fire fixture pair under ``tests/dslint_fixtures/`` and a case
in ``tests/test_dslint.py`` — the rule-coverage test fails on a rule with
no fixture.
"""

from deepspeed_tpu.tools.dslint.rules.ds001_donation import DonationSafetyRule
from deepspeed_tpu.tools.dslint.rules.ds002_hot_sync import HotPathSyncRule
from deepspeed_tpu.tools.dslint.rules.ds003_truthiness import (
    ArrayTruthinessRule)
from deepspeed_tpu.tools.dslint.rules.ds004_threads import ThreadSharedStateRule
from deepspeed_tpu.tools.dslint.rules.ds005_signals import SignalHandlerRule
from deepspeed_tpu.tools.dslint.rules.ds006_config_keys import ConfigKeyDriftRule
from deepspeed_tpu.tools.dslint.rules.ds007_trace_names import TraceNameRule
from deepspeed_tpu.tools.dslint.rules.ds008_prom_families import (
    PromFamilyRule)
from deepspeed_tpu.tools.dslint.rules.ds009_offline_purity import (
    OfflinePurityRule)

ALL_RULES = (
    DonationSafetyRule,
    HotPathSyncRule,
    ArrayTruthinessRule,
    ThreadSharedStateRule,
    SignalHandlerRule,
    ConfigKeyDriftRule,
    TraceNameRule,
    PromFamilyRule,
    OfflinePurityRule,
)


def get_rules():
    """Fresh rule instances (project rules keep per-run state)."""
    return [cls() for cls in ALL_RULES]
