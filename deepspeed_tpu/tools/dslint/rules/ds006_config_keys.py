"""DS006 — config-key drift between ``config/constants.py`` and reality.

The constants module exists so every config key has exactly one spelling;
drift shows up two ways and both have bitten:

  * a raw string key read straight off the user config dict
    (``self._raw.get("resilience")``) — invisible to rename refactors and
    to anyone grepping the constant
  * a constant nothing references — usually a key whose reader was
    refactored away while the constant (and the docs pointing at it)
    survived, advertising config surface that silently does nothing

This is a project-wide rule: it parses the constants module once, then
(a) flags snake_case string keys read from config-dict receivers
(``_raw``/``ds_config``/``config_dict``/...) that are not values in the
constants module, and (b) flags constants no other file references.
Group-internal subkeys (``"enabled"`` etc.) parsed by dataclass kwargs are
exempt via ``_SUBKEY_ALLOWLIST``.
"""

import ast
import os
import re
from typing import Dict, Set

from deepspeed_tpu.tools.dslint import astutil
from deepspeed_tpu.tools.dslint.engine import (FileContext, ProjectContext,
                                               Rule)

_CONSTANTS_SUFFIX = "config/constants.py"
#: receiver leaf names treated as "the raw user config dict"
_CONFIG_RECEIVERS = {"_raw", "ds_config", "config_dict", "user_config",
                     "raw_config"}
#: keys that live INSIDE a config group (dataclass kwargs), not at top level
_SUBKEY_ALLOWLIST = {"enabled", "type", "params"}
_SNAKE_RE = re.compile(r"^[a-z][a-z0-9_]*$")


class ConfigKeyDriftRule(Rule):
    id = "DS006"
    name = "config-key-drift"
    description = ("raw config keys missing from config/constants.py, and "
                   "constants nothing references")

    def __init__(self):
        self._reads = []          # (ctx, node, key) raw string key reads
        self._refs: Set[str] = set()   # constant NAMES referenced anywhere

    def begin_run(self):
        self._reads = []
        self._refs = set()

    def check(self, ctx: FileContext):
        if ctx.relpath.endswith(_CONSTANTS_SUFFIX):
            return []
        for node in ast.walk(ctx.tree):
            # references to constants: bare NAME loads and module-attr reads
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                self._refs.add(node.id)
            elif isinstance(node, ast.Attribute):
                self._refs.add(node.attr)
            elif isinstance(node, ast.ImportFrom):
                self._refs.update(a.name for a in node.names)

            # raw key reads: recv.get("key"...) / recv["key"] / "key" in recv
            if isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("get", "pop", "setdefault")
                        and self._is_config_receiver(node.func.value)
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    self._reads.append((ctx, node.args[0],
                                        node.args[0].value))
            elif isinstance(node, ast.Subscript):
                if (self._is_config_receiver(node.value)
                        and isinstance(node.slice, ast.Constant)
                        and isinstance(node.slice.value, str)):
                    self._reads.append((ctx, node.slice, node.slice.value))
            elif isinstance(node, ast.Compare):
                if (len(node.ops) == 1
                        and isinstance(node.ops[0], (ast.In, ast.NotIn))
                        and isinstance(node.left, ast.Constant)
                        and isinstance(node.left.value, str)
                        and self._is_config_receiver(node.comparators[0])):
                    self._reads.append((ctx, node.left, node.left.value))
        return []

    @staticmethod
    def _is_config_receiver(expr: ast.expr) -> bool:
        name = astutil.dotted_name(expr)
        return bool(name) and name.split(".")[-1] in _CONFIG_RECEIVERS

    # ------------------------------------------------------------------
    def finalize(self, project: ProjectContext):
        const_ctx = next((f for f in project.files
                          if f.relpath.endswith(_CONSTANTS_SUFFIX)), None)
        if const_ctx is None:
            return []           # nothing to check against in this run
        key_values: Set[str] = set()
        const_defs: Dict[str, ast.AST] = {}
        for node in const_ctx.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id.isupper():
                    const_defs[t.id] = t
                    if (isinstance(node.value, ast.Constant)
                            and isinstance(node.value.value, str)):
                        key_values.add(node.value.value)
                    elif isinstance(node.value, ast.Call):
                        # frozenset({...}) of keys: every member is a key
                        for el in ast.walk(node.value):
                            if (isinstance(el, ast.Constant)
                                    and isinstance(el.value, str)):
                                key_values.add(el.value)

        findings = []
        for ctx, node, key in self._reads:
            if key in key_values or key in _SUBKEY_ALLOWLIST:
                continue
            if not _SNAKE_RE.match(key):
                continue
            findings.append(ctx.finding(
                self.id, node,
                f'raw config key "{key}" has no constant in '
                f"config/constants.py: add one (single spelling, greppable, "
                f"rename-safe) and read through it", token=f"key:{key}"))

        # "referenced nowhere" is only meaningful when the run actually saw
        # the whole package the constants serve — on a partial run (single
        # file / subpackage) every constant would look unused
        if self._run_covers_package(project, const_ctx):
            for name, node in sorted(const_defs.items()):
                if name in self._refs:
                    continue
                findings.append(const_ctx.finding(
                    self.id, node,
                    f"constant `{name}` is referenced nowhere outside "
                    f"constants.py: dead config surface — wire it to its "
                    f"reader or remove it", token=f"unused:{name}"))
        return findings

    @staticmethod
    def _run_covers_package(project: ProjectContext,
                            const_ctx: FileContext) -> bool:
        """True when every .py under the constants module's package root
        (the directory containing ``config/``) is in this run's file set."""
        from deepspeed_tpu.tools.dslint.engine import iter_python_files
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(const_ctx.abspath)))
        in_run = {os.path.abspath(f.abspath) for f in project.files}
        return all(p in in_run for p in iter_python_files([root]))
