"""DS002 — host sync in a registered hot path.

Generalizes the original ``tests/test_no_hot_sync.py`` AST tripwire to
every function in the hot-path registry (``hotpath.HOT_PATHS``): the
per-step/per-tick fast paths must never regrow ``float()``, ``.item()``,
``jax.device_get``, ``block_until_ready`` or friends — one sync silently
re-serializes the whole pipeline while every timing test keeps passing.

Three enforcement shapes per registry spec:

  hot_functions   any forbidden call anywhere in the function is a finding
  guard_branches  only ``if ...<guard_attr>`` branches are checked (async
                  fan-in points whose synchronous fallback may sync)
  confine         a call (e.g. ``.device_get``) is allowed ONLY in the
                  listed functions of that file; anywhere else it fires

A registered function that no longer exists is ALSO a finding (registry
drift) — renaming a hot function without updating the registry must not
silently retire the tripwire.
"""

import ast
import os
from typing import Optional, Tuple

from deepspeed_tpu.tools.dslint import astutil
from deepspeed_tpu.tools.dslint.engine import FileContext, Rule
from deepspeed_tpu.tools.dslint.hotpath import HOT_PATHS, HotPathSpec


def _matches(call: ast.Call, matcher: str) -> bool:
    """``"float"`` = bare-name call; ``".item"`` = attribute call with that
    attr on any receiver; ``"np.asarray"`` = exact dotted name."""
    if matcher.startswith("."):
        return (isinstance(call.func, ast.Attribute)
                and call.func.attr == matcher[1:])
    if "." in matcher:
        return astutil.call_name(call) == matcher
    return isinstance(call.func, ast.Name) and call.func.id == matcher


def _forbidden_calls(node: ast.AST, forbidden: Tuple[str, ...]):
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            for m in forbidden:
                if _matches(n, m):
                    yield n, m
                    break


def _stmt_span(stmts) -> set:
    lines = set()
    for s in stmts:
        hi = max((getattr(x, "end_lineno", None) or s.lineno)
                 for x in ast.walk(s))
        lines.update(range(s.lineno, hi + 1))
    return lines


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _guard_negated(test: ast.expr, guard_attr: str) -> bool:
    return any(
        isinstance(x, ast.UnaryOp) and isinstance(x.op, ast.Not)
        and any(isinstance(y, ast.Attribute) and y.attr == guard_attr
                for y in ast.walk(x.operand))
        for x in ast.walk(test))


def _sync_only_lines(fn: ast.AST, branches, guard_attr: str) -> set:
    """Lines that provably execute ONLY when the guard is false (the
    designed synchronous fallback): the body of a ``not guard`` If, the
    else of a positive-guard If, and — when the async side early-returns —
    the tail of the enclosing statement list. Everything else (shared code
    + the async side) can run in async mode and must stay sync-free."""
    stmt_lists = []
    for node in ast.walk(fn):
        for field in ("body", "orelse", "finalbody"):
            lst = getattr(node, field, None)
            if isinstance(lst, list) and lst \
                    and all(isinstance(s, ast.stmt) for s in lst):
                stmt_lists.append(lst)
    sync = set()
    for br in branches:
        negated = _guard_negated(br.test, guard_attr)
        sync_side = br.body if negated else br.orelse
        async_side = br.orelse if negated else br.body
        sync.update(_stmt_span(sync_side))
        if _terminates(async_side):
            # the async side never falls through: whatever follows this If
            # in its statement list only runs in synchronous mode
            for lst in stmt_lists:
                if br in lst:
                    sync.update(_stmt_span(lst[lst.index(br) + 1:]))
    return sync


class HotPathSyncRule(Rule):
    id = "DS002"
    name = "host-sync-in-hot-path"
    description = ("host synchronization (float()/.item()/device_get/"
                   "block_until_ready) inside a registered hot path")

    def __init__(self, specs: Tuple[HotPathSpec, ...] = HOT_PATHS):
        self.specs = specs

    # ------------------------------------------------------------------
    def check(self, ctx: FileContext):
        findings = []
        # match on the ABSOLUTE path (full-component suffix), not the
        # run-relative one: `cd deepspeed_tpu && dslint .` or an unusual
        # --root must not silently un-register the tripwire
        abspath = os.path.abspath(ctx.abspath).replace(os.sep, "/")
        for spec in self.specs:
            if not (abspath.endswith("/" + spec.path)
                    or abspath == spec.path or ctx.relpath == spec.path):
                continue
            findings.extend(self._check_spec(ctx, spec))
        return findings

    def _scope(self, ctx: FileContext, spec: HotPathSpec
               ) -> Optional[ast.AST]:
        if spec.cls is None:
            return ctx.tree
        for cls in astutil.classes_of(ctx.tree):
            if cls.name == spec.cls:
                return cls
        return None

    def _check_spec(self, ctx: FileContext, spec: HotPathSpec):
        findings = []
        scope = self._scope(ctx, spec)
        if scope is None:
            findings.append(ctx.finding(
                self.id, ctx.tree,
                f"hot-path registry drift: class `{spec.cls}` not found in "
                f"{spec.path} — update deepspeed_tpu/tools/dslint/hotpath.py "
                f"alongside the refactor", token=f"registry:{spec.cls}"))
            return findings
        methods = {n.name: n for n in astutil.functions_of(scope)}

        for name in spec.hot_functions:
            fn = methods.get(name)
            if fn is None:
                findings.append(ctx.finding(
                    self.id, scope,
                    f"hot-path registry drift: `{name}` not found — update "
                    f"hotpath.py alongside the rename/removal",
                    token=f"registry:{name}"))
                continue
            for call, m in _forbidden_calls(fn, spec.forbidden):
                findings.append(ctx.finding(
                    self.id, call,
                    f"`{m}` in hot path `{name}`: a host sync here "
                    f"serializes every step — route readback through the "
                    f"designated drain", token=f"{name}:{m}"))

        for name, guard_attr in spec.guard_branches:
            fn = methods.get(name)
            if fn is None:
                findings.append(ctx.finding(
                    self.id, scope,
                    f"hot-path registry drift: guarded function `{name}` "
                    f"not found — update hotpath.py",
                    token=f"registry:{name}"))
                continue
            branches = [
                n for n in ast.walk(fn)
                if isinstance(n, ast.If)
                and any(isinstance(x, ast.Attribute) and x.attr == guard_attr
                        for x in ast.walk(n.test))]
            if not branches:
                findings.append(ctx.finding(
                    self.id, fn,
                    f"hot-path registry drift: `{name}` lost its "
                    f"`{guard_attr}` branch — update hotpath.py",
                    token=f"registry:{name}:{guard_attr}"))
                continue
            # scan everything that can execute in async mode: the whole
            # function MINUS the statements provably on the sync-only side
            # (the negated-guard body, the positive guard's else branch,
            # and — when a guard branch early-returns — the tail after it).
            # Early-return refactors therefore cannot retire the tripwire.
            sync_lines = _sync_only_lines(fn, branches, guard_attr)
            for call, m in _forbidden_calls(fn, spec.forbidden):
                if call.lineno in sync_lines:
                    continue         # the designed synchronous fallback
                findings.append(ctx.finding(
                    self.id, call,
                    f"`{m}` on the `{guard_attr}` (async) side of "
                    f"`{name}`: this push path queues device arrays "
                    f"verbatim — a transfer here re-serializes every step",
                    token=f"{name}:{guard_attr}:{m}"))

        for matcher, allowed in (spec.confine or {}).items():
            # confinement is FILE-wide: module functions plus every class's
            # methods (a helper class added later must not dodge the net)
            fns = list(astutil.functions_of(ctx.tree))
            for cls in astutil.classes_of(ctx.tree):
                fns += list(astutil.functions_of(cls))
            for fn in fns:
                if fn.name in allowed:
                    continue
                for call, m in _forbidden_calls(fn, (matcher,)):
                    findings.append(ctx.finding(
                        self.id, call,
                        f"`{m}` outside its designated functions "
                        f"(allowed: {', '.join(sorted(allowed))}) in "
                        f"`{fn.name}` — route readback through the drain or "
                        f"add a deliberate exemption to hotpath.py with a "
                        f"comment explaining why it cannot lag",
                        token=f"confine:{fn.name}:{m}"))
        return findings
