"""DS002 — host sync reachable from a registered hot root.

dslint v2 rewrote this rule from registry membership to **taint
propagation**: instead of enumerating every hot function by hand
(the retired 300-line ``hotpath.HOT_PATHS`` registry), the rule builds
the project call graph (``callgraph.py``), computes the closure of the
declared ``HOT_ROOTS``, and scans the own body of every reached function
for host-sync sinks — ``float()``, ``.item()``, ``jax.device_get``,
``block_until_ready``, ``np.asarray`` and friends. A helper extracted
out of a hot function, or a new callee a hot path grows, is covered the
moment the edge exists; nothing has to be registered.

The designed synchronous points are declared as ``ESCAPE_HATCHES``:

  sync_ok   own-body sinks exempt, callees still traversed (THE drain)
  prune     subtree exempt and not traversed (the host offload step)
  guarded   only lines that provably execute when ``guard_attr`` is
            false are exempt (async fan-in with a sync fallback branch)

Drift is still a finding: a root or hatch whose function no longer
resolves (renamed without updating ``hotpath.py``) fires on the file it
pointed at — the tripwire cannot silently rot. Calls the graph cannot
resolve degrade to statistics (``CallGraph.unresolved``), never to
findings.
"""

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from deepspeed_tpu.tools.dslint import astutil
from deepspeed_tpu.tools.dslint.callgraph import (CallGraph, get_callgraph,
                                                  own_body_nodes)
from deepspeed_tpu.tools.dslint.engine import (FileContext, Finding,
                                               ProjectContext, Rule)
from deepspeed_tpu.tools.dslint.hotpath import (DEFAULT_FORBIDDEN,
                                                ESCAPE_HATCHES, HOST_NUMPY_FILES,
                                                HOT_ROOTS, EscapeHatch,
                                                HotRoot)

_NP_MATCHERS = ("np.asarray", "np.array")


def _matches(call: ast.Call, matcher: str) -> bool:
    """``"float"`` = bare-name call; ``".item"`` = attribute call with that
    attr on any receiver; ``"np.asarray"`` = exact dotted name."""
    if matcher.startswith("."):
        return (isinstance(call.func, ast.Attribute)
                and call.func.attr == matcher[1:])
    if "." in matcher:
        return astutil.call_name(call) == matcher
    return isinstance(call.func, ast.Name) and call.func.id == matcher


def _forbidden_calls(nodes: Iterable[ast.AST], forbidden: Tuple[str, ...]):
    for n in nodes:
        if isinstance(n, ast.Call):
            for m in forbidden:
                if _matches(n, m):
                    yield n, m
                    break


def _stmt_span(stmts) -> set:
    lines = set()
    for s in stmts:
        hi = max((getattr(x, "end_lineno", None) or s.lineno)
                 for x in ast.walk(s))
        lines.update(range(s.lineno, hi + 1))
    return lines


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _mentions_guard(node: ast.AST, guard_attr: str) -> bool:
    """True if the expression reads ``guard_attr`` — either as a plain
    attribute access or through ``getattr(obj, "guard_attr", default)``
    (the duck-typed form call sites use against foreign objects)."""
    for x in ast.walk(node):
        if isinstance(x, ast.Attribute) and x.attr == guard_attr:
            return True
        if (isinstance(x, ast.Call) and isinstance(x.func, ast.Name)
                and x.func.id == "getattr" and len(x.args) >= 2
                and isinstance(x.args[1], ast.Constant)
                and x.args[1].value == guard_attr):
            return True
    return False


def _guard_negated(test: ast.expr, guard_attr: str) -> bool:
    return any(
        isinstance(x, ast.UnaryOp) and isinstance(x.op, ast.Not)
        and _mentions_guard(x.operand, guard_attr)
        for x in ast.walk(test))


def _sync_only_lines(fn: ast.AST, branches, guard_attr: str) -> set:
    """Lines that provably execute ONLY when the guard is false (the
    designed synchronous fallback): the body of a ``not guard`` If, the
    else of a positive-guard If, and — when the async side early-returns —
    the tail of the enclosing statement list. Everything else (shared code
    + the async side) can run in async mode and must stay sync-free."""
    stmt_lists = []
    for node in ast.walk(fn):
        for field in ("body", "orelse", "finalbody"):
            lst = getattr(node, field, None)
            if isinstance(lst, list) and lst \
                    and all(isinstance(s, ast.stmt) for s in lst):
                stmt_lists.append(lst)
    sync = set()
    for br in branches:
        negated = _guard_negated(br.test, guard_attr)
        sync_side = br.body if negated else br.orelse
        async_side = br.orelse if negated else br.body
        sync.update(_stmt_span(sync_side))
        if _terminates(async_side):
            # the async side never falls through: whatever follows this If
            # in its statement list only runs in synchronous mode
            for lst in stmt_lists:
                if br in lst:
                    sync.update(_stmt_span(lst[lst.index(br) + 1:]))
    return sync


class HotPathSyncRule(Rule):
    id = "DS002"
    name = "host-sync-in-hot-path"
    description = ("host synchronization (float()/.item()/device_get/"
                   "block_until_ready) in a function reachable from a "
                   "registered hot root")

    def __init__(self, roots: Tuple[HotRoot, ...] = HOT_ROOTS,
                 hatches: Tuple[EscapeHatch, ...] = ESCAPE_HATCHES,
                 host_numpy_files: Tuple[str, ...] = HOST_NUMPY_FILES):
        self.roots = roots
        self.hatches = hatches
        self.host_numpy_files = host_numpy_files

    # ------------------------------------------------------------------
    def finalize(self, project: ProjectContext) -> Iterable[Finding]:
        graph = get_callgraph(project)
        by_path: Dict[str, FileContext] = {f.relpath: f
                                           for f in project.files}
        findings: List[Finding] = []

        # roots: resolve; a root whose file is in this run but whose
        # function is gone is DRIFT — the declaration must move with the
        # refactor, silently retiring coverage is the failure mode the
        # old registry had
        root_of: Dict[str, HotRoot] = {}
        for root in self.roots:
            ctx = self._ctx_for(by_path, root.path)
            key = graph.resolve(root.path, root.qualname)
            if key is not None:
                root_of.setdefault(key, root)
            elif ctx is not None:
                findings.append(ctx.finding(
                    self.id, ctx.tree,
                    f"hot-root drift: `{root.qualname}` not found in "
                    f"{root.path} — update hotpath.py HOT_ROOTS alongside "
                    f"the rename/removal", token=f"hot-root:{root.qualname}"))

        hatch_of: Dict[str, EscapeHatch] = {}
        for hatch in self.hatches:
            ctx = self._ctx_for(by_path, hatch.path)
            key = graph.resolve(hatch.path, hatch.qualname)
            if key is not None:
                hatch_of[key] = hatch
            elif ctx is not None:
                findings.append(ctx.finding(
                    self.id, ctx.tree,
                    f"escape-hatch drift: `{hatch.qualname}` not found in "
                    f"{hatch.path} — update hotpath.py ESCAPE_HATCHES "
                    f"alongside the rename/removal",
                    token=f"hatch:{hatch.qualname}"))

        prune = {k for k, h in hatch_of.items() if h.mode == "prune"}
        pred = graph.reachable_from(sorted(root_of), prune=prune)

        for key in sorted(pred):
            if key in prune:
                continue
            hatch = hatch_of.get(key)
            if hatch is not None and hatch.mode == "sync_ok":
                continue
            info = graph.functions.get(key)
            ctx = info and by_path.get(info.relpath)
            if ctx is None:
                continue            # reached a file outside this run
            findings.extend(self._scan(graph, pred, root_of, key, info,
                                       ctx, hatch))
        return findings

    # ------------------------------------------------------------------
    def _ctx_for(self, by_path: Dict[str, FileContext], path: str
                 ) -> Optional[FileContext]:
        ctx = by_path.get(path)
        if ctx is not None:
            return ctx
        for rel, c in by_path.items():
            if rel.endswith("/" + path) or path.endswith("/" + rel):
                return c
        return None

    def _forbidden_for(self, root: HotRoot, relpath: str
                       ) -> Tuple[str, ...]:
        forb = root.forbidden
        if any(relpath == p or relpath.endswith("/" + p)
               for p in self.host_numpy_files):
            forb = tuple(m for m in forb if m not in _NP_MATCHERS)
        return forb

    def _root_chain(self, graph: CallGraph, pred, root_of, key
                    ) -> Tuple[HotRoot, str]:
        chain = graph.path_to(pred, key)
        root = root_of.get(chain[0]) if chain else None
        if root is None:            # should not happen; defensive
            root = next(iter(root_of.values()))
            return root, root.qualname
        names = [graph.functions[k].qualname for k in chain
                 if k in graph.functions]
        if len(names) > 4:
            names = names[:2] + ["..."] + names[-2:]
        return root, " -> ".join(names)

    def _scan(self, graph: CallGraph, pred, root_of, key, info, ctx,
              hatch: Optional[EscapeHatch]):
        root, chain = self._root_chain(graph, pred, root_of, key)
        forbidden = self._forbidden_for(root, info.relpath)
        sync_lines: set = set()
        if hatch is not None and hatch.mode == "guarded":
            branches = [
                n for n in ast.walk(info.node)
                if isinstance(n, ast.If)
                and _mentions_guard(n.test, hatch.guard_attr)]
            if not branches:
                yield ctx.finding(
                    self.id, info.node,
                    f"escape-hatch drift: `{info.qualname}` lost its "
                    f"`{hatch.guard_attr}` branch — update hotpath.py",
                    token=f"hatch:{info.qualname}:{hatch.guard_attr}")
                return
            sync_lines = _sync_only_lines(info.node, branches,
                                          hatch.guard_attr)
        for call, m in _forbidden_calls(own_body_nodes(info.node),
                                        forbidden):
            if call.lineno in sync_lines:
                continue            # the designed synchronous fallback
            yield ctx.finding(
                self.id, call,
                f"`{m}` in `{info.qualname}`, reachable from hot root "
                f"`{root.qualname}` ({chain}): a host sync here "
                f"serializes every step/tick — route readback through "
                f"the designated drain or declare an escape hatch in "
                f"hotpath.py", token=f"hot:{m}")
