"""Checked-in baseline of grandfathered findings.

The baseline lets dslint land with the tree non-clean and ratchet from
there: existing findings are recorded once (``dslint --write-baseline``),
new code must be clean, and fixing a grandfathered finding surfaces the
entry as *stale* so it can be expired (re-run ``--write-baseline``) instead
of silently shielding a future regression at the same anchor.

Entries are keyed ``(rule, path, anchor)`` with an occurrence ``count`` —
anchors carry no line numbers, so edits elsewhere in the file never churn
the baseline, and introducing a *second* violation at an anchor that
grandfathers one is still reported.
"""

import collections
import json
import os
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.tools.dslint.engine import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = "dslint_baseline.json"


def load_baseline(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"unsupported baseline version {data.get('version')!r} in {path} "
            f"(expected {BASELINE_VERSION})")
    return data


def find_default_baseline(start: str) -> Optional[str]:
    """Walk up from ``start`` looking for the checked-in baseline file."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        cand = os.path.join(d, DEFAULT_BASELINE_NAME)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def _covered(entry: dict, covered_paths, active_rules) -> bool:
    """Did a run with this coverage actually re-evaluate ``entry``?"""
    if covered_paths is not None and entry["path"] not in covered_paths:
        return False
    if active_rules is not None and entry["rule"] not in active_rules:
        return False
    return True


def write_baseline(path: str, findings: List[Finding],
                   prior: Optional[dict] = None,
                   covered_paths=None, active_rules=None):
    """Serialize current findings as the new baseline (sorted, counted).

    With ``prior`` + coverage sets, entries the run did NOT re-evaluate
    (file outside the linted paths, rule deselected) are carried over
    verbatim — ``--write-baseline`` on a subset must never truncate the
    repo baseline for everything else.

    ``DS000`` parse errors are never grandfathered: an unparseable file is
    an UNLINTED file, and hiding it behind the baseline would make every
    future violation in it invisible.
    """
    findings = [f for f in findings if f.rule != "DS000"]
    counts: Dict[Tuple[str, str, str], int] = collections.Counter(
        f.key for f in findings)
    messages: Dict[Tuple[str, str, str], str] = {}
    for f in findings:
        messages.setdefault(f.key, f.message)
    entries = [{"rule": rule, "path": p, "anchor": anchor, "count": n,
                "message": messages[(rule, p, anchor)]}
               for (rule, p, anchor), n in sorted(counts.items())]
    if prior is not None:
        kept = [e for e in prior.get("entries", [])
                if not _covered(e, covered_paths, active_rules)]
        entries = sorted(entries + kept,
                         key=lambda e: (e["rule"], e["path"], e["anchor"]))
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": BASELINE_VERSION, "entries": entries}, f,
                  indent=2, sort_keys=False)
        f.write("\n")


def match_baseline(findings: List[Finding], baseline: Optional[dict],
                   covered_paths=None, active_rules=None
                   ) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """Split findings into (new, grandfathered) and report stale entries.

    Per key, up to ``count`` findings are absorbed by the baseline; the
    rest stay live. Entries whose key matched fewer findings than their
    count are stale (the violation was fixed — expire the entry) — but
    only when the run actually re-evaluated them: an entry for a file
    outside ``covered_paths`` or a rule outside ``active_rules`` (a
    partial / --select run) is simply not judged.
    """
    if not baseline:
        return list(findings), [], []
    budget: Dict[Tuple[str, str, str], int] = {}
    entry_by_key: Dict[Tuple[str, str, str], dict] = {}
    for e in baseline.get("entries", []):
        key = (e["rule"], e["path"], e["anchor"])
        budget[key] = budget.get(key, 0) + int(e.get("count", 1))
        entry_by_key[key] = e
    new: List[Finding] = []
    grandfathered: List[Finding] = []
    used: Dict[Tuple[str, str, str], int] = collections.defaultdict(int)
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            used[f.key] += 1
            grandfathered.append(f)
        else:
            new.append(f)
    stale = [entry_by_key[k] for k, leftover in sorted(budget.items())
             if leftover > 0
             and _covered(entry_by_key[k], covered_paths, active_rules)]
    return new, grandfathered, stale
